"""Query-string pipeline front end (reference: Pipeline/PipelineBuilder.java).

The reference's whole run-time configuration surface is one
``k=v&k=v`` string (README "Run-time configuration";
PipelineBuilder.java:94-295). This builder preserves that surface —
same reserved keys, same required/optional semantics, same error
messages, same seed-1 shuffle + 70/30 split, same ``config_*``
pass-through and ``result_path`` report file — over the TPU-native
data path: epochs load once into a dense batch, features are extracted
by one jitted program, classifiers consume whole batches.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
from typing import Dict, Optional, Union

import numpy as np

from .. import obs
from ..features import registry as fe_registry
from ..io import deadline as deadline_mod
from ..io import provider, sources
from ..models import registry as clf_registry
from ..models import stats
from ..obs import chaos, events
from ..utils import java_compat

logger = logging.getLogger(__name__)


def get_query_map(query: str) -> Dict[str, str]:
    """k=v&k=v parse; empty values tolerated (PipelineBuilder.java:49-68).

    Values split at the FIRST ``=`` only: the reference's quirk of
    truncating a value at its second ``=`` (``split('=')[1]``) ate the
    option grammar of every parameter that legitimately carries ``=``
    — ``fe=dwt-4:level=4:stats=energy``, ``fe_sweep=``, the
    ``faults=remote.request:p=0.2`` chaos spec — and forced per-key
    re-extraction workarounds downstream (the PR 7 builder note).
    Fixed at the parser, so option values with embedded ``=`` survive
    everywhere; values without one — every reference query ever
    written — parse byte-identically (round-trips pinned in
    tests/test_pipeline.py).
    """
    out: Dict[str, str] = {}
    for param in query.split("&"):
        name, sep, value = param.partition("=")
        out[name] = value if sep else ""
    return out


def get_raw_param(query: str, name: str) -> Optional[str]:
    """The full (first-'='-to-end) value of one query parameter, or
    None when absent.

    Since :func:`get_query_map` stopped truncating at the second
    ``=``, this agrees with the map for every present parameter; it
    remains the seam for distinguishing a missing parameter from an
    empty one without building the whole map.
    """
    for param in query.split("&"):
        if param.startswith(name + "="):
            return param[len(name) + 1:]
    return None


def decode_percent_query(query: str) -> str:
    """Percent-decode a network-submitted query string, pairwise.

    Network submissions (``gateway/``) URL-encode option values whose
    grammar carries reserved characters —
    ``fe=dwt-8%3Alevel%3D5%3Astats%3Denergy%2Cmean`` — while the
    journal/IR currency is the decoded string. Decoding must happen
    pair by pair (split on ``&`` and the FIRST ``=`` first, THEN
    unquote), or a decoded ``=``/``&`` would be re-parsed as query
    structure. A decoded value containing a literal ``&`` (or a
    decoded name containing ``&``/``=``) cannot be represented in the
    ``k=v&k=v`` surface at all and is rejected loudly rather than
    silently re-split. Strings without ``%`` pass through
    byte-identically — every query ever written is unchanged
    (round-trips pinned in tests/test_pipeline.py).
    """
    if "%" not in query:
        return query
    from urllib.parse import unquote

    parts = []
    for param in query.split("&"):
        name, sep, value = param.partition("=")
        name = unquote(name)
        value = unquote(value)
        if "&" in name or "=" in name or "&" in value:
            raise ValueError(
                f"percent-decoded query parameter {param!r} contains a "
                f"reserved '&'/'=' the k=v&k=v surface cannot represent"
            )
        parts.append(name + sep + value)
    return "&".join(parts)


class PipelineBuilder:
    def __init__(
        self,
        query: str,
        filesystem: Optional[sources.FileSystem] = None,
    ):
        self.query = query
        # None = route by the input URI scheme (http/gs/file/local) in
        # the provider; an explicit filesystem overrides routing.
        self._fs = filesystem
        #: ClassificationStatistics; FanOutStatistics (a dict of
        #: them, one per name) for classifiers= runs; or
        #: PopulationStatistics (per-member dict + summary) when
        #: population axes (cv=/seeds=/sweep=) were requested
        self.statistics: Optional[
            Union[
                stats.ClassificationStatistics,
                stats.FanOutStatistics,
                stats.PopulationStatistics,
            ]
        ] = None
        #: per-stage wall times for the run (obs.StageTimer)
        self.timers = obs.StageTimer()
        #: obs.report.RunTelemetry when the run opted in (``report=`` /
        #: EEG_TPU_RUN_REPORT_DIR), else None
        self.telemetry = None
        #: per-run metrics scope (obs.Metrics child) for the last run
        self.run_metrics: Optional[obs.Metrics] = None
        #: degradation-ladder history of the last run, oldest first
        self.degradation_history: list = []
        #: bf16 feature-path resolution of the last fused run
        #: ({"requested", "used", "gate"}); None for f32 runs. Set
        #: whether or not telemetry is on — bench lines read it here.
        self.precision_resolved: Optional[dict] = None
        #: whether the last fused run's ingest overlapped (the
        #: double-buffered staging path); None before any fused run
        self.overlap_resolved: Optional[bool] = None
        #: mesh resolution of the last run ({"requested", "rung",
        #: "shape", ...}); None when the run asked for no mesh
        #: (devices=/mesh_axes= absent). Set whether or not telemetry
        #: is on — bench lines read it here, like precision_resolved.
        self.mesh_resolved: Optional[dict] = None
        #: prefix-dedup attribution of the last run ({"role",
        #: "prefix_key", and leader build_seconds / follower
        #: leader_plan + bytes/seconds saved} — scheduler/dedup.py);
        #: None when the run shared no prefix work. Set whether or not
        #: telemetry is on, like precision_resolved.
        self.dedup_resolved: Optional[dict] = None
        #: a requested-but-not-live pod's record (processes=1, or a
        #: degraded bootstrap) pending its fold into mesh_resolved
        self._pod_block: Optional[dict] = None

    @contextlib.contextmanager
    def _stage(self, name: str, **attrs):
        """One pipeline stage: StageTimer accumulation + a telemetry
        span (``stage.<name>``) carrying the stage's attributes —
        a no-op context beyond the timer when telemetry is off."""
        with self.timers.stage(name), events.span(f"stage.{name}", **attrs):
            yield

    def execute(
        self,
    ) -> Union[stats.ClassificationStatistics, stats.FanOutStatistics]:
        """Parse the query into its :class:`~.plan.ExecutionPlan` IR
        and run it through the scheduler's single-plan path — the thin
        shim the monolithic orchestration body collapsed into when the
        run machinery moved to ``scheduler/runtime.py`` (ROADMAP item
        5). Every query string that ever worked routes through the IR
        and produces bit-identical statistics; multi-plan callers use
        ``scheduler.PlanExecutor`` directly and get per-plan fault
        domains, admission control, deadlines, and the crash-only
        journal on top of this exact code path."""
        from ..scheduler import runtime
        from .plan import ExecutionPlan

        return runtime.execute_plan(ExecutionPlan.parse(self.query), self)

    def _execute(
        self, plan
    ) -> Union[stats.ClassificationStatistics, stats.FanOutStatistics]:
        query_map = plan.query_map

        # 1. input (PipelineBuilder.java:104-113; the IR validated
        # presence — this re-derivation keeps the provider contract)
        if "info_file" in query_map:
            files = [query_map["info_file"]]
        elif "eeg_file" in query_map and "guessed_num" in query_map:
            files = [query_map["eeg_file"], query_map["guessed_num"]]
        else:
            raise ValueError("Missing the input file argument")

        # ingest_workers= bounds the provider's parallel parse pool;
        # prefetch= its decoded look-ahead (both default from
        # EEG_TPU_INGEST_WORKERS / EEG_TPU_PREFETCH_DEPTH). The merge
        # is order-preserving, so epoch order and the balance counters
        # are bit-identical at any pool size.
        def make_provider():
            return provider.OfflineDataProvider(
                files,
                filesystem=self._fs,
                workers=self._int_param(query_map, "ingest_workers"),
                prefetch_depth=self._int_param(query_map, "prefetch"),
            )

        # processes=/coordinator=/process_id= (env twins
        # JAX_NUM_PROCESSES/JAX_COORDINATOR/JAX_PROCESS_ID): the
        # pod-scale multi-process family (ROADMAP item 2's last leg).
        # Bootstrap runs FIRST — jax.distributed must initialize
        # before anything touches a backend, and _resolve_mesh's
        # jax.devices() is a backend touch. A live pod supersedes
        # devices=/mesh_axes= with the hybrid DCN x ICI mesh; a
        # bootstrap failure (coordinator unreachable, a peer host
        # missing) degrades pod -> single-host mesh -> single device
        # -> host, recorded like every other rung drop.
        #
        # devices=/mesh_axes=: the multi-device scale-out family
        # (ROADMAP item 2). A requested mesh threads into the fused
        # ingest (parallel/sharded_ingest — the epoch batch sharded
        # over devices) and the population engine (the member axis
        # sharded, parallel/population). Mesh-unavailable/unhealthy
        # is the ladder's new TOP rung: the run degrades to the
        # single-device path (recorded — rung, shape, evidence in
        # run_report.json and on the bench line), which can itself
        # degrade to host exactly as before. Absent both parameter
        # families, this resolves to None and the path is
        # byte-identical to every query ever written.
        pod_runtime = self._resolve_pod(plan.pod)
        if pod_runtime is not None:
            mesh = pod_runtime.mesh
            if plan.mesh is not None:
                logger.info(
                    "pod bootstrap succeeded: the hybrid DCN x ICI "
                    "mesh supersedes devices=/mesh_axes="
                )
        else:
            mesh = self._resolve_mesh(plan.mesh)
            self._note_pod_block()

        # task=seizure: the continuous-EEG seizure workload
        # (docs/workloads.md) — sliding-window epoching over interval
        # annotations, pluggable subband features, cost-sensitive
        # training, imbalanced-class statistics. The default (absent /
        # task=p300) is the reference's marker-locked path, untouched.
        task = query_map.get("task", "")
        if task and task not in ("p300", "seizure"):
            raise ValueError(
                f"unknown task {task!r}; supported: p300 (default), "
                f"seizure"
            )
        if task == "seizure":
            if query_map.get("serve") == "true":
                from ..serve import pipeline as serve_pipeline

                statistics, serve_block, workload = (
                    serve_pipeline.run_serve_seizure(
                        query_map, make_provider, self._stage
                    )
                )
                if self.telemetry is not None:
                    # the lifecycle block is a top-level report field;
                    # popped so the serve block doesn't carry a
                    # second copy of the same dict
                    self.telemetry.lifecycle = serve_block.pop(
                        "lifecycle", None
                    )
                    self.telemetry.serve = serve_block
                    self.telemetry.workload = workload
                return self._finish_run(statistics, query_map)
            return self._finish_run(
                self._execute_seizure(
                    query_map, make_provider, mesh, plan
                ),
                query_map,
            )
        if query_map.get("fe_sweep"):
            raise ValueError(
                "fe_sweep= compares feature configs over the seizure "
                "workload; it requires task=seizure"
            )

        # serve=true: the online inference mode (serve/pipeline.py) —
        # the saved classifier loads once, every kept epoch becomes a
        # deadline-bounded request through the resident micro-batching
        # service, and the statistics are pinned bit-identical to the
        # batch load_clf= run on the same inputs (docs/serving.md).
        if query_map.get("serve") == "true":
            from ..serve import pipeline as serve_pipeline

            statistics, serve_block = serve_pipeline.run_serve(
                query_map, make_provider, self._stage
            )
            if self.telemetry is not None:
                # one copy in the report: lifecycle is its own block
                self.telemetry.lifecycle = serve_block.pop(
                    "lifecycle", None
                )
                self.telemetry.serve = serve_block
            return self._finish_run(statistics, query_map)

        odp = make_provider()

        # 2. feature extraction (PipelineBuilder.java:128-139).
        # fe=dwt-8-fused is the TPU fast-path mode: ingest + DWT run as
        # one on-device program (provider.load_features_device), so no
        # host epoch batch ever exists and classifiers consume feature
        # rows directly. All other fe= values follow the reference
        # shape: epochs load first, the registry extractor maps them.
        # dwt-<i>-fused-pallas routes the same mode through the Pallas
        # ingest kernel (ops/ingest_pallas.py); dwt-<i>-fused-block
        # through the tile-row-gather + 128-variant-bank formulation
        # (device_ingest.make_block_ingest_featurizer). Any registry
        # wavelet index works, like the host fe= family.
        fused_match = re.fullmatch(
            r"dwt-(\d+)-fused(-pallas|-block|-xla|-decode)?",
            query_map.get("fe", ""),
        )
        fused = fused_match is not None
        # precision=bf16 computes the fused DWT matmul in bfloat16;
        # precision=int8 quantizes the finished f32 feature rows per
        # subband — both behind a per-run f32-reference accuracy gate
        # (the decode rung's feature — ops/decode_ingest.py);
        # EEG_TPU_PRECISION sets the process default, the query wins
        # per run. f32 is and stays the default: the ~1e-7 ladder
        # contract is an f32 contract.
        from ..ops import decode_ingest as _decode_ingest

        precision = (
            query_map.get("precision")
            or os.environ.get("EEG_TPU_PRECISION")
            or "f32"
        )
        if precision not in _decode_ingest.PRECISIONS:
            raise ValueError(
                f"precision= must be f32, bf16, int8, or int4, got "
                f"{precision!r}"
            )
        if precision != "f32" and not fused:
            raise ValueError(
                f"precision={precision} applies to the fused fe= modes "
                "(fe=dwt-<i>-fused[-decode]); host-path features are "
                "the bit-parity reference and stay f64"
            )
        # overlap= toggles the double-buffered ingest/compute overlap
        # (io/staging.prefetch with a featurize stage_fn); absent, the
        # EEG_TPU_OVERLAP env decides in the provider. Statistics are
        # bit-identical either way (pinned) — overlap reschedules
        # work, never changes it.
        overlap_value = query_map.get("overlap", "")
        if overlap_value not in ("", "true", "false"):
            raise ValueError(
                f"overlap= must be true or false, got {overlap_value!r}"
            )
        overlap = None if not overlap_value else overlap_value == "true"
        if fused:
            from ..ops import device_ingest

            wavelet_index = int(fused_match.group(1))
            # bare -fused resolves per platform (block on
            # accelerators - 21x the element gather on the r4 chip -
            # decode on CPU, where the slice-scan cut beats the
            # element gather ~8.6x); explicit suffixes always win. A
            # non-f32 precision request resolves to decode — the rung
            # that carries the reduced-precision twins.
            suffix = fused_match.group(2)
            if suffix is None:
                backend = (
                    "decode"
                    if precision != "f32"
                    else device_ingest.default_fused_backend()
                )
            else:
                backend = {
                    "-pallas": "pallas",
                    "-block": "block",
                    "-xla": "xla",
                    "-decode": "decode",
                }[suffix]
                if precision != "f32" and backend != "decode":
                    raise ValueError(
                        f"precision={precision} rides the decode rung; "
                        f"it cannot combine with the explicit "
                        f"fe=...-fused{suffix} backend"
                    )
            # content-addressed feature cache (io/feature_cache.py):
            # keyed on the triplet bytes + channel set + window +
            # extractor geometry — deliberately NOT the backend rung
            # (every rung is tolerance-identical by contract), so a
            # hit serves whatever backend computed the entry first and
            # skips the degradation ladder entirely. cache=false opts
            # a run out; EEG_TPU_NO_FEATURE_CACHE=1 disables globally.
            from ..io import feature_cache

            cache = (
                feature_cache.open_cache()
                if query_map.get("cache", "true") != "false"
                else None
            )
            if pod_runtime is not None:
                # the pod path IS its own cache story: each host reads
                # 1/N of the waveform bytes, and a content key would
                # need every process to digest bytes it deliberately
                # never reads. The gated precision rungs need the f32
                # reference recording in memory for the same reason —
                # refuse loudly rather than serve ungated numerics.
                if precision != "f32":
                    raise ValueError(
                        f"precision={precision} runs behind a per-run "
                        "f32 reference gate the pod-partitioned "
                        "ingest cannot stage; pod runs compute f32"
                    )
                if cache is not None:
                    logger.info(
                        "pod run: feature cache bypassed (partitioned "
                        "ingest reads 1/N of the bytes instead)"
                    )
                    cache = None
            cache_key = None
            prepared = None
            features = targets = None
            build_slot = None
            landed = None
            #: the run's resolved numeric class; may drop to f32 when
            #: the bf16 gate trips or a non-decode rung lands
            precision_used = precision
            gate_record = None
            # cross-tenant plan-prefix dedup (scheduler/dedup.py): the
            # plan's canonical ingest+featurize prefix is claimed
            # BEFORE any I/O — a follower whose leader already built
            # this prefix reuses the in-memory result and never reads
            # a byte; a leader computes exactly as an undeduped run
            # and publishes at the end. dedup=false opts a run out.
            from ..scheduler import dedup as dedup_mod

            dedup_claim = None
            if pod_runtime is None and dedup_mod.eligible(plan):
                with self._stage("ingest", phase="prefix_dedup"):
                    dedup_claim = dedup_mod.acquire_for(plan)
            try:
                if (
                    dedup_claim is not None
                    and dedup_claim.role == "follower"
                ):
                    features, targets = dedup_claim.value
                    landed = "dedup"
                    if precision != "f32":
                        # the leader resolved the gate for this exact
                        # prefix; the follower inherits its decision
                        precision_used = dedup_claim.meta.get(
                            "precision_used", precision
                        )
                        gate_record = {
                            "source": "dedup",
                            "leader_plan": dedup_claim.leader_plan,
                        }
                    self._note_dedup(dedup_claim, rows=len(targets))
                    logger.info(
                        "prefix dedup hit (%d rows, leader %s): ingest "
                        "+ featurization skipped",
                        len(targets), dedup_claim.leader_plan,
                    )
                if landed is None and cache is not None:
                    try:
                        # ONE read pass: digests (for the content key) and
                        # parsed recordings come from the same bytes
                        # (provider.prepare_fused_run), so a cold
                        # cache-enabled run no longer reads every file
                        # twice; on a miss the ladder below featurizes the
                        # already-parsed recordings from memory
                        with self._stage("ingest", phase="cache_lookup"):
                            prepared = odp.prepare_fused_run(
                                provider.fused_extractor_id(
                                    wavelet_index, precision
                                )
                            )
                            cache_key = prepared.key
                            # single-flight (io/feature_cache.py):
                            # the first run to reach this key
                            # proceeds; a concurrent run missing the
                            # SAME entry blocks here until the leader
                            # stores it, then its lookup below hits —
                            # exactly one rebuild is kept
                            # (tests/test_feature_cache.py,
                            # tests/test_scheduler.py)
                            build_slot = cache.begin_build(cache_key)
                            hit = cache.lookup(cache_key)
                    except deadline_mod.DeadlineExceededError:
                        # the plan's budget died waiting on another
                        # tenant's rebuild — fail fast; "degrade to
                        # uncached" would run the very rebuild the
                        # deadline can't cover
                        raise
                    except Exception as e:
                        # an unreadable input surfaces properly from the
                        # compute path below; a broken cache dir must not
                        # kill a run the uncached path can finish. This
                        # run will never store the entry, so the
                        # single-flight slot is released NOW — holding
                        # it would stall concurrent same-key plans for
                        # the whole uncached run, for nothing.
                        logger.warning(
                            "feature cache unavailable (%s: %s); running "
                            "uncached", type(e).__name__, e,
                        )
                        if build_slot is not None:
                            build_slot.release()
                            build_slot = None
                        cache = cache_key = prepared = hit = None
                    if hit is not None:
                        features, targets = hit
                        landed = "cache"
                        if precision != "f32":
                            # the entry was gated when it was computed and
                            # stored (keys carry the precision class — a
                            # non-f32 entry can only have passed its gate)
                            gate_record = {"source": "cache"}
                        logger.info(
                            "feature cache hit (%d rows): ingest + "
                            "featurization skipped", len(targets),
                        )
                if landed is None and precision != "f32":
                    if prepared is None:
                        # cache=false still needs the parsed recordings
                        # for the f32 reference check; the ladder below
                        # then featurizes them from memory — the gate
                        # never costs a second read
                        with self._stage("ingest", phase="cache_lookup"):
                            prepared = odp.prepare_fused_run(
                                provider.fused_extractor_id(
                                    wavelet_index, precision
                                )
                            )
                    # the per-run accuracy gate: the rung's feature
                    # rows vs f32 on the first recording, judged
                    # against the rung's documented tolerance (ops/
                    # decode_ingest). Above the gate the run computes
                    # f32 — recorded, never silent. The content digest
                    # keys the gate memo (a repeated in-process gating
                    # of the same session replays the decision instead
                    # of re-paying the double featurize), and the
                    # record's gate_seconds separates gate overhead
                    # from steady-state throughput in the report.
                    with self._stage(
                        "ingest", phase=f"{precision}_gate"
                    ):
                        gate_record = odp.precision_gate_check(
                            prepared.recordings, wavelet_index,
                            precision=precision,
                            content_key=(
                                prepared.digests[0][2]
                                if prepared.digests else None
                            ),
                        )
                    events.event(
                        f"pipeline.{precision}_gate", **gate_record
                    )
                    if not gate_record["ok"]:
                        precision_used = "f32"
                        obs.metrics.count(
                            f"pipeline.{precision}_gate_disabled"
                        )
                        logger.warning(
                            "pipeline.%s_gate auto-disable: max abs dev "
                            "%.3e > gate %.3e; the run computes f32",
                            precision,
                            gate_record["max_abs_dev"],
                            gate_record["tolerance"],
                        )
                        # a gated-off run IS an f32 run: re-key from the
                        # same read pass and give the f32 cache a chance
                        # before featurizing. The single-flight slot
                        # moves to the NEW key — holding the non-f32 key
                        # while building the f32 entry would let a
                        # concurrent f32 run of the same content race
                        # the rebuild the guard exists to serialize.
                        if cache is not None:
                            cache_key = odp.run_key_for(
                                prepared,
                                provider.fused_extractor_id(
                                    wavelet_index, "f32"
                                ),
                            )
                            if build_slot is not None:
                                build_slot.release()
                            build_slot = cache.begin_build(cache_key)
                            hit = cache.lookup(cache_key)
                            if hit is not None:
                                features, targets = hit
                                landed = "cache"
                                logger.info(
                                    "feature cache hit (%d rows, f32 "
                                    "fallback): ingest + featurization "
                                    "skipped", len(targets),
                                )
                # backend degradation ladder (io/provider.py): a fused
                # backend that fails to lower, OOMs, or sits on unhealthy
                # devices degrades decode -> pallas -> block -> xla ->
                # host epochs + registry extractor instead of killing the
                # run. Same
                # ClassificationStatistics out the other end, every step
                # down counted in obs.metrics. degrade=false opts out
                # (fail fast on the requested backend).
                degrade = query_map.get("degrade", "true") != "false"
                ladder = (
                    provider.degradation_ladder(backend)
                    if degrade
                    else [backend]
                )
                if pod_runtime is not None:
                    # pod runs fail FAST on rung errors: per-host
                    # mid-run degradation cannot be coordinated — a
                    # host that silently walks down the ladder (or
                    # lands the collective-free host floor) while its
                    # peers sit inside the feature all-gather would
                    # strand them in a collective that never
                    # completes. A loud failure ends this process's
                    # plan instead, and the coordination service's
                    # peer-failure propagation (or the resident
                    # executor's retry) takes it from there; the pod
                    # DEGRADES only at the bootstrap rung, before any
                    # collective exists.
                    ladder = [backend]
                if landed is not None:
                    ladder = []
                for rung in ladder:
                    if rung == "host":
                        break
                    try:
                        with self._stage("ingest", backend=rung):
                            features, targets = odp.load_features_device(
                                wavelet_index=wavelet_index,
                                backend=rung,
                                # a live pod partitions whole
                                # recordings per host; the hybrid
                                # mesh is the POPULATION's to shard —
                                # per-recording time sharding stays a
                                # single-host mesh feature
                                mesh=None if pod_runtime else mesh,
                                pod=pod_runtime,
                                recordings=(
                                    None if prepared is None
                                    else prepared.recordings
                                ),
                                # bf16 is the decode rung's feature: a
                                # lower rung landing means the run
                                # computes f32 (recorded below)
                                precision=(
                                    precision_used
                                    if rung == "decode"
                                    else "f32"
                                ),
                                overlap=overlap,
                            )
                        landed = rung
                        break
                    except OSError:
                        # input/IO errors (missing or unreadable recording,
                        # a remote endpoint that already exhausted its
                        # retries + circuit): every rung would re-read the
                        # same input and fail identically — surface the
                        # root cause at once instead of masking it under
                        # three backend attempts and a device probe.
                        # ValueError stays degradable: backend-capability
                        # limits (the block slab bound, the Pallas
                        # window<=chunk/2 constraint) are ValueErrors the
                        # next rung may not share.
                        raise
                    except Exception as e:
                        if len(ladder) == 1:
                            raise
                        evidence = f"{type(e).__name__}: {e}"
                        logger.error(
                            "pipeline.degrade rung_failed backend=%s "
                            "requested=%s evidence=%s",
                            rung, backend, evidence,
                        )
                        obs.metrics.count("pipeline.degraded")
                        obs.metrics.count(f"pipeline.degraded.from.{rung}")
                        events.event(
                            "pipeline.degraded", rung=rung, error=evidence
                        )
                        self.degradation_history.append(
                            {"from": rung, "error": evidence}
                        )
                        if self._devices_unhealthy():
                            # dead hardware fails every device rung the
                            # same way — jump straight to the host floor
                            obs.metrics.count(
                                "pipeline.degraded.unhealthy_devices"
                            )
                            logger.error(
                                "pipeline.degrade unhealthy_devices=true: "
                                "skipping remaining device backends"
                            )
                            events.event("pipeline.degraded.unhealthy_devices")
                            break
                if landed is not None:
                    if landed not in (backend, "cache", "dedup"):
                        logger.warning(
                            "pipeline.degrade landed requested=%s landed=%s "
                            "steps=%d",
                            backend, landed, len(self.degradation_history),
                        )
                    events.event(
                        "pipeline.rung_landed", requested=backend, landed=landed
                    )
                    if precision_used != "f32" and landed not in (
                        "decode", "cache", "dedup"
                    ):
                        # the decode rung failed and a lower (f32) rung
                        # landed: the run's features are f32 — the cache
                        # entry must carry the f32 key, and the report the
                        # true numeric class. The single-flight slot moves
                        # to the f32 key before the store below — but
                        # NON-blocking: the features are already in
                        # memory, so when another tenant holds the f32
                        # key mid-rebuild of this same content-addressed
                        # entry, waiting (or dying on a deadline) for a
                        # store the holder is about to make is pure
                        # waste — skip it instead.
                        precision_used = "f32"
                        if cache is not None and prepared is not None:
                            cache_key = odp.run_key_for(
                                prepared,
                                provider.fused_extractor_id(
                                    wavelet_index, "f32"
                                ),
                            )
                            if build_slot is not None:
                                build_slot.release()
                            build_slot = cache.try_begin_build(cache_key)
                            if build_slot is None:
                                cache_key = None
                    self.overlap_resolved = (
                        provider.default_overlap()
                        if overlap is None
                        else overlap
                    )
                    self.precision_resolved = (
                        {
                            "requested": precision,
                            "used": precision_used,
                            "gate": gate_record,
                        }
                        if precision != "f32"
                        else None
                    )
                    if self.telemetry is not None:
                        self.telemetry.backend = {
                            "requested": backend, "landed": landed,
                        }
                        self.telemetry.overlap = self.overlap_resolved
                        self.telemetry.precision = self.precision_resolved
                    if (
                        landed != "cache"
                        and cache is not None
                        and cache_key is not None
                    ):
                        cache.store(cache_key, features, targets)
                    if (
                        dedup_claim is not None
                        and dedup_claim.role == "leader"
                    ):
                        # publish whatever the run actually landed on
                        # (disk-cache hits included — the in-memory
                        # copy spares followers even the read+digest
                        # pass); the resolved precision rides along so
                        # bf16 followers inherit the gate decision
                        dedup_claim.publish(
                            (features, targets),
                            meta={"precision_used": precision_used},
                        )
                        self._note_dedup(dedup_claim, rows=len(targets))
                    fe = None
                    n = len(targets)
                else:
                    # the host floor of the ladder: reference-shaped epoch
                    # loading plus the registry extractor — slower, but the
                    # run survives and the statistics contract holds. This
                    # path never stores the entry, so holding the
                    # single-flight slot through the slow host load would
                    # only block a neighbour that could rebuild and store.
                    if build_slot is not None:
                        build_slot.release()
                        build_slot = None
                    logger.error(
                        "pipeline.degrade landed requested=%s landed=host "
                        "(epochs + registry dwt-%d)", backend, wavelet_index
                    )
                    obs.metrics.count("pipeline.degraded.to_host")
                    events.event(
                        "pipeline.rung_landed", requested=backend, landed="host"
                    )
                    self.degradation_history.append(
                        {"from": backend, "to": "host"}
                    )
                    # the host floor is the f64 bit-parity path; the
                    # requested non-f32 rung never ran. Set on the
                    # builder whether or not telemetry is on (the
                    # bench-attribution contract precision_resolved
                    # documents).
                    self.precision_resolved = (
                        {
                            "requested": precision,
                            "used": "host-f64",
                            "gate": gate_record,
                        }
                        if precision != "f32"
                        else None
                    )
                    if self.telemetry is not None:
                        self.telemetry.backend = {
                            "requested": backend, "landed": "host",
                        }
                        self.telemetry.precision = self.precision_resolved
                    fused = False
                    fe = fe_registry.create(f"dwt-{wavelet_index}")
                    with self._stage("ingest", backend="host"):
                        batch = odp.load()
                    n = len(batch)
            finally:
                if build_slot is not None:
                    build_slot.release()
                if dedup_claim is not None:
                    # an unpublished leader (host floor, ladder
                    # exhaustion, any raise) abandons: the first
                    # waiting follower is promoted and computes its
                    # own prefix — leader chaos costs followers time,
                    # never correctness
                    dedup_claim.settle()
        else:
            with self._stage("ingest"):
                batch = odp.load()
            if "fe" not in query_map:
                raise ValueError("Missing the feature extraction argument")
            fe = fe_registry.create(query_map["fe"])
            n = len(batch)
        obs.metrics.count("pipeline.epochs_loaded", n)

        # 3. classifier (PipelineBuilder.java:151-284)
        # population axes (models/population.py): cv=/cv_mode=/seeds=/
        # sweep= expand SGD-family training into a member population
        # trained as one vmapped program (population_mode=looped runs
        # the sequential twin — the bench baseline)
        from ..models import population

        pop_spec = population.PopulationSpec.from_query_map(query_map)
        if pop_spec.active:
            if "load_clf" in query_map:
                raise ValueError(
                    "population axes (cv=/seeds=/sweep=) train models; "
                    "they cannot combine with load_clf="
                )
            if query_map.get("save_clf") == "true":
                raise ValueError(
                    "population runs train many members; save_clf= "
                    "has no single model to persist"
                )
            if query_map.get("elastic") == "true":
                raise ValueError(
                    "population training does not support elastic=true; "
                    "the stacked program has no per-member checkpoints"
                )

        if "classifiers" in query_map:
            # shared-feature fan-out: the expensive-to-produce feature
            # matrix is computed once (above) and every requested
            # classifier trains + tests against the same in-memory
            # rows — the reference trains exactly one classifier per
            # execution, so comparing five meant five full
            # ingest+featurization passes. Single-classifier
            # train_clf= runs are untouched (byte-identical output).
            statistics = self._execute_fanout(
                query_map,
                n,
                features=features if fused else None,
                targets=targets if fused else None,
                batch=None if fused else batch,
                fe=fe,
                pop_spec=pop_spec,
                mesh=mesh,
            )

        elif "train_clf" in query_map and pop_spec.active:
            name = query_map["train_clf"]
            if name not in population.SGD_FAMILY:
                raise ValueError(
                    "population axes (cv=/seeds=/sweep=) apply to the "
                    f"SGD family ({', '.join(population.SGD_FAMILY)}); "
                    f"{name!r} trains one model per run"
                )
            statistics = self._execute_population(
                query_map, name, pop_spec,
                features=features if fused else None,
                targets=targets if fused else None,
                batch=None if fused else batch,
                fe=fe,
                mesh=mesh,
            )

        elif "train_clf" in query_map:
            classifier = clf_registry.create(query_map["train_clf"])

            train_idx, test_idx = java_compat.train_test_split_indices(n, seed=1)
            config = {
                k: v for k, v in query_map.items() if k.startswith("config_")
            }
            classifier.set_config(config)
            # elastic=true&checkpoint_path=<dir>: the train stage runs
            # through fit_elastic — chunked training with per-chunk
            # checkpoints, bounded restarts, and a divergence sentinel
            # (obs/failure.py), so a mid-train transient restores the
            # latest checkpoint instead of restarting the run. The
            # SGD/NN families checkpoint mid-scan; tree growers train
            # monolithically with a logged note.
            elastic_kwargs = self._elastic_kwargs(query_map)
            with self._stage(
                "train",
                classifier=query_map["train_clf"],
                elastic=elastic_kwargs is not None,
            ):
                if elastic_kwargs is None:
                    if fused:
                        classifier.fit(
                            features[train_idx], targets[train_idx]
                        )
                    else:
                        classifier.train(
                            batch.epochs[train_idx],
                            batch.targets[train_idx],
                            fe,
                        )
                elif fused:
                    classifier.fit_elastic(
                        features[train_idx], targets[train_idx],
                        **elastic_kwargs,
                    )
                else:
                    classifier.train_elastic(
                        batch.epochs[train_idx], batch.targets[train_idx],
                        fe, **elastic_kwargs,
                    )
            if elastic_kwargs is not None:
                # the checkpoints' job (surviving a crash of THIS run)
                # is done; left behind, the next run under the same
                # checkpoint_path would restore this finished
                # trajectory and silently skip its own training
                elastic_kwargs["manager"].clear()
            logger.info("trained %s", query_map["train_clf"])

            if query_map.get("save_clf") == "true":
                if "save_name" not in query_map:
                    raise ValueError(
                        "Please provide a location to save a classifier "
                        "within the save_name query parameter"
                    )
                classifier.save(query_map["save_name"])

            with self._stage("test", classifier=query_map["train_clf"]):
                statistics = (
                    classifier.test_features(
                        features[test_idx], targets[test_idx]
                    )
                    if fused
                    else classifier.test(
                        batch.epochs[test_idx], batch.targets[test_idx]
                    )
                )

        elif "load_clf" in query_map:
            classifier = clf_registry.create(query_map["load_clf"])
            if "load_name" not in query_map:
                raise ValueError("Classifier location not provided")

            # load mode tests on ALL shuffled data — no split
            # (PipelineBuilder.java:261-278)
            perm = java_compat.java_shuffle_indices(n, seed=1)
            if not fused:
                classifier.set_feature_extraction(fe)
            classifier.load(query_map["load_name"])
            with self._stage("test", classifier=query_map["load_clf"]):
                statistics = (
                    classifier.test_features(features[perm], targets[perm])
                    if fused
                    else classifier.test(batch.epochs[perm], batch.targets[perm])
                )

        else:
            raise ValueError("Missing classifier argument")

        return self._finish_run(statistics, query_map)

    def _finish_run(self, statistics, query_map):
        """Shared run tail: logging, the atomic ``result_path`` report,
        and the statistics hand-off (used by the batch chain and the
        ``serve=`` mode alike)."""
        logger.info("statistics:\n%s", statistics)
        logger.info("stage timings:\n%s", self.timers.report())
        if chaos.active_plan() is not None:
            logger.info("chaos plan after run: %r", chaos.active_plan())
            logger.info("metrics: %s", obs.metrics.to_json())

        if "result_path" in query_map:
            from ..checkpoint.manager import atomic_write_text

            # tmp + os.replace (the checkpoint store's atomic-write
            # discipline): a crash mid-write can no longer leave a
            # truncated report. PrintWriter.println parity: a newline
            # after toString().
            atomic_write_text(
                query_map["result_path"], str(statistics) + "\n"
            )

        self.statistics = statistics
        return statistics

    # -- the seizure workload ------------------------------------------

    @staticmethod
    def _seizure_classifier(name):
        """Registry classifier with the TRUE confusion matrix. The
        MLlib-path classifiers swap fp/fn in their reports — a pinned
        reference bug-as-behavior (models/stats.from_arrays) the P300
        surface must reproduce. The seizure workload's precision/
        recall/expected-cost are computed FROM fp/fn, so it opts out:
        its statistics label the matrix correctly (documented in
        docs/workloads.md)."""
        clf = clf_registry.create(name)
        clf.confusion_only_stats = False
        return clf

    @staticmethod
    def seizure_weights(query_map, targets) -> tuple:
        """Resolve the cost-sensitive knobs to (weight_pos, weight_neg,
        cost_fp, cost_fn).

        ``class_weight=balanced`` weights positives by the run's
        negative/positive ratio (computed over the FULL row set before
        any split — deterministic and shared by every population
        member); ``class_weight=<float>`` sets the positive weight
        directly; otherwise the misclassification costs double as the
        training weights (``weight_pos = cost_fn``: missing a seizure
        costs ``cost_fn``, so positives push the boundary that hard).
        The costs always parameterize the expected-cost statistic,
        whatever trained the model.
        """
        cost_fp = float(query_map.get("cost_fp") or 1.0)
        cost_fn = float(query_map.get("cost_fn") or 1.0)
        if cost_fp <= 0 or cost_fn <= 0:
            raise ValueError(
                f"cost_fp=/cost_fn= must be > 0, got "
                f"{cost_fp}/{cost_fn}"
            )
        cw = query_map.get("class_weight", "")
        if cw == "balanced":
            n_pos = float(np.sum(np.asarray(targets) == 1.0))
            n_neg = float(len(targets) - n_pos)
            wp = (n_neg / n_pos) if n_pos > 0 else 1.0
            wn = 1.0
        elif cw:
            try:
                wp = float(cw)
            except ValueError:
                raise ValueError(
                    f"class_weight= must be 'balanced' or a float, "
                    f"got {cw!r}"
                )
            if wp <= 0:
                raise ValueError(f"class_weight= must be > 0, got {wp}")
            wn = 1.0
        else:
            wp, wn = cost_fn, cost_fp
        return wp, wn, cost_fp, cost_fn

    def _seizure_features(self, query_map, make_provider, slide_cfg,
                          fe_names):
        """The seizure ingest+featurize front half: ONE read pass
        (provider.prepare_run), a per-feature-config content-addressed
        cache lookup (the key folds the FULL extractor config —
        family/level/stats — plus the epoching geometry, so no entry
        can cross configs), sliding-window epoching plus extraction
        for the misses. Returns ``(feature_sets, targets)`` with
        ``feature_sets`` ordered like ``fe_names``."""
        from ..io import feature_cache

        odp = make_provider()
        extractors = [
            (name, fe_registry.create(name)) for name in fe_names
        ]

        def extractor_tuple(fe):
            return (
                "seizure", fe.cache_id(), slide_cfg.window,
                slide_cfg.stride, slide_cfg.label_overlap,
            )

        cache = (
            feature_cache.open_cache()
            if query_map.get("cache", "true") != "false"
            else None
        )
        prepared = None
        keys = {}
        hits = {}
        if cache is not None:
            try:
                with self._stage("ingest", phase="cache_lookup",
                                 task="seizure"):
                    prepared = odp.prepare_run(
                        extractor_tuple(extractors[0][1])
                    )
                    keys[fe_names[0]] = prepared.key
                    for name, fe in extractors[1:]:
                        keys[name] = odp.run_key_for(
                            prepared, extractor_tuple(fe)
                        )
                    for name, _ in extractors:
                        hit = cache.lookup(keys[name])
                        if hit is not None:
                            hits[name] = hit
            except Exception as e:
                logger.warning(
                    "feature cache unavailable (%s: %s); running "
                    "uncached", type(e).__name__, e,
                )
                cache = None
                prepared = None
                keys, hits = {}, {}

        targets = None
        missing = [nf for nf in extractors if nf[0] not in hits]
        if missing:
            with self._stage("ingest", task="seizure"):
                if prepared is not None:
                    # featurize the recordings the key pass already
                    # parsed — no second read
                    from ..epochs.extractor import EpochBatch

                    batch = EpochBatch.concatenate([
                        odp.sliding_batch_for(rec, slide_cfg)
                        for _rel, _guessed, rec in prepared.recordings
                    ])
                else:
                    batch = odp.load_sliding(slide_cfg)
            targets = np.asarray(batch.targets, dtype=np.float64)
            with self._stage("features", task="seizure"):
                for name, fe in missing:
                    hits[name] = (
                        np.asarray(fe.extract_batch(batch.epochs)),
                        targets,
                    )
                    if cache is not None and name in keys:
                        cache.store(keys[name], *hits[name])
        if targets is None:
            targets = np.asarray(hits[fe_names[0]][1], dtype=np.float64)
        feature_sets = [(name, hits[name][0]) for name, _ in extractors]
        return feature_sets, targets

    def _execute_seizure(self, query_map, make_provider, mesh=None,
                         plan=None):
        """``task=seizure``: sliding windows -> configurable subband
        features -> cost-sensitive training -> imbalanced-class
        statistics (docs/workloads.md). The first non-P300 path
        through the pipeline; it shares the split/population/fan-out
        machinery and the statistics seam with the reference path."""
        from ..epochs import sliding
        from ..models import population

        window = self._int_param(query_map, "window") or 512
        stride = self._int_param(query_map, "stride") or max(
            1, window // 2
        )
        overlap = float(query_map.get("label_overlap") or 0.5)
        slide_cfg = sliding.SlidingConfig(
            window=window, stride=stride, label_overlap=overlap
        )

        pop_spec = population.PopulationSpec.from_query_map(query_map)
        if pop_spec.active:
            # the P300 path's population conflict contract, kept: a
            # silently-ignored axis (fe_sweep= evaluating one config,
            # save_clf= saving nothing) is worse than an error
            if "load_clf" in query_map:
                raise ValueError(
                    "population axes (cv=/seeds=/sweep=/fe_sweep=) "
                    "train models; they cannot combine with load_clf="
                )
            if query_map.get("save_clf") == "true":
                raise ValueError(
                    "population runs train many members; save_clf= "
                    "has no single model to persist"
                )
            if query_map.get("elastic") == "true":
                raise ValueError(
                    "population training does not support elastic=true; "
                    "the stacked program has no per-member checkpoints"
                )
        fe_value = query_map.get("fe", "")
        if pop_spec.fe_configs:
            if "classifiers" in query_map:
                raise ValueError(
                    "fe_sweep= expands the train_clf= population; it "
                    "cannot combine with classifiers="
                )
            fe_names = list(pop_spec.fe_configs)
        else:
            if not fe_value:
                raise ValueError("Missing the feature extraction argument")
            fe_names = [fe_value]
        for name in fe_names:
            if "-fused" in name:
                raise ValueError(
                    "task=seizure extracts features on the host; fe= "
                    "must be a registry form (e.g. "
                    "dwt-4:level=4:stats=energy), not a -fused mode"
                )

        # cross-tenant plan-prefix dedup, seizure flavor: the sliding
        # epoching + per-config subband extraction IS this workload's
        # ingest+featurize prefix — two tenants sweeping costs over
        # the same session and feature configs share one build
        from ..scheduler import dedup as dedup_mod

        dedup_claim = None
        if plan is not None and dedup_mod.eligible(plan):
            with self._stage(
                "ingest", phase="prefix_dedup", task="seizure"
            ):
                dedup_claim = dedup_mod.acquire_for(plan)
        try:
            if dedup_claim is not None and dedup_claim.role == "follower":
                feature_sets, targets = dedup_claim.value
                feature_sets = list(feature_sets)
                self._note_dedup(dedup_claim, rows=len(targets))
                logger.info(
                    "prefix dedup hit (%d windows, leader %s): seizure "
                    "ingest + featurization skipped",
                    len(targets), dedup_claim.leader_plan,
                )
            else:
                feature_sets, targets = self._seizure_features(
                    query_map, make_provider, slide_cfg, fe_names
                )
                if dedup_claim is not None:
                    dedup_claim.publish((tuple(feature_sets), targets))
                    self._note_dedup(dedup_claim, rows=len(targets))
        finally:
            if dedup_claim is not None:
                dedup_claim.settle()
        features = feature_sets[0][1]
        n = len(targets)
        if n == 0:
            raise ValueError(
                f"no sliding windows: every recording is shorter than "
                f"window={window}"
            )
        obs.metrics.count("pipeline.epochs_loaded", n)
        n_pos = int(np.sum(targets == 1.0))

        wp, wn, cost_fp, cost_fn = self.seizure_weights(
            query_map, targets
        )
        if self.telemetry is not None:
            self.telemetry.workload = {
                "task": "seizure",
                "window": window,
                "stride": stride,
                "label_overlap": overlap,
                "windows": n,
                "positives": n_pos,
                "class_ratio": round(n_pos / n, 6),
                "weight_pos": round(wp, 6),
                "weight_neg": round(wn, 6),
                "cost_fp": cost_fp,
                "cost_fn": cost_fn,
                "fe": fe_names if len(fe_names) > 1 else fe_names[0],
            }

        config = {
            k: v for k, v in query_map.items() if k.startswith("config_")
        }
        if wp != 1.0 or wn != 1.0:
            config["config_weight_pos"] = repr(wp)
            config["config_weight_neg"] = repr(wn)

        if "classifiers" in query_map:
            # the fan-out derives its config_* map from the query map
            # itself — inject the RESOLVED class weights so every leg
            # trains with them (class_weight=balanced has no config_
            # spelling of its own)
            fanout_qm = dict(query_map)
            fanout_qm.update({
                k: v for k, v in config.items()
                if k.startswith("config_weight_")
            })
            statistics = self._execute_fanout(
                fanout_qm, n, features=features, targets=targets,
                batch=None, fe=None, pop_spec=pop_spec,
                classifier_factory=self._seizure_classifier,
                mesh=mesh,
            )
        elif "train_clf" in query_map and pop_spec.active:
            name = query_map["train_clf"]
            if name not in population.SGD_FAMILY:
                raise ValueError(
                    "population axes (cv=/seeds=/sweep=/fe_sweep=) "
                    f"apply to the SGD family "
                    f"({', '.join(population.SGD_FAMILY)}); {name!r} "
                    f"trains one model per run"
                )
            statistics, block = population.run_population(
                name,
                lambda: self._seizure_classifier(name),
                config,
                features,
                targets,
                pop_spec,
                stage=self._stage,
                feature_sets=(
                    feature_sets if pop_spec.fe_configs else None
                ),
                mesh=mesh,
            )
            self._note_population_mesh(block)
            if self.telemetry is not None:
                self.telemetry.population = block
        elif "train_clf" in query_map:
            classifier = self._seizure_classifier(query_map["train_clf"])
            classifier.set_config(config)
            train_idx, test_idx = java_compat.train_test_split_indices(
                n, seed=1
            )
            elastic_kwargs = self._elastic_kwargs(query_map)
            with self._stage(
                "train",
                classifier=query_map["train_clf"],
                task="seizure",
                elastic=elastic_kwargs is not None,
            ):
                if elastic_kwargs is None:
                    classifier.fit(features[train_idx], targets[train_idx])
                else:
                    classifier.fit_elastic(
                        features[train_idx], targets[train_idx],
                        **elastic_kwargs,
                    )
            if elastic_kwargs is not None:
                elastic_kwargs["manager"].clear()
            logger.info("trained %s (seizure)", query_map["train_clf"])
            if query_map.get("save_clf") == "true":
                if "save_name" not in query_map:
                    raise ValueError(
                        "Please provide a location to save a classifier "
                        "within the save_name query parameter"
                    )
                classifier.save(query_map["save_name"])
            with self._stage(
                "test", classifier=query_map["train_clf"], task="seizure"
            ):
                statistics = classifier.test_features(
                    features[test_idx], targets[test_idx]
                )
        elif "load_clf" in query_map:
            classifier = self._seizure_classifier(query_map["load_clf"])
            if "load_name" not in query_map:
                raise ValueError("Classifier location not provided")
            classifier.load(query_map["load_name"])
            perm = java_compat.java_shuffle_indices(n, seed=1)
            with self._stage(
                "test", classifier=query_map["load_clf"], task="seizure"
            ):
                statistics = classifier.test_features(
                    features[perm], targets[perm]
                )
        else:
            raise ValueError("Missing classifier argument")

        # every seizure report carries the imbalanced-class block: the
        # workload's headline is expected cost / recall, not accuracy
        stats.mark_extended(statistics, cost_fp=cost_fp, cost_fn=cost_fn)
        return statistics

    def _note_dedup(self, claim, rows: int) -> None:
        """Per-plan attribution of shared prefix work — who led, who
        drafted behind them, bytes/seconds saved — on the builder (the
        bench-attribution contract, like ``precision_resolved``) and
        in run_report.json's ``dedup`` block."""
        block = {
            "role": claim.role,
            "prefix_key": claim.key,
            "rows": int(rows),
        }
        if claim.role == "leader":
            block["build_seconds"] = round(claim.build_seconds, 6)
            if claim.leader_failed:
                # promoted after another tenant's abandoned build —
                # the fallback path, recorded so an operator can see
                # a flapping leader from the artifact alone
                block["promoted_after_leader_failure"] = True
        else:
            block["leader_plan"] = claim.leader_plan
            block["bytes_saved"] = int(claim.bytes_saved)
            block["seconds_saved"] = round(claim.build_seconds, 6)
        self.dedup_resolved = block
        if self.telemetry is not None:
            self.telemetry.dedup = block

    # -- population training -------------------------------------------

    def _host_features(self, batch, fe):
        """The host path's full feature matrix: one extraction pass
        over the whole epoch batch (per-epoch independent, so slicing
        rows afterwards equals extracting the slices)."""
        with self._stage("features"):
            features = np.asarray(
                fe.extract_batch(np.asarray(batch.epochs, np.float64))
            )
        return features, np.asarray(batch.targets, dtype=np.float64)

    def _execute_population(
        self, query_map, name, pop_spec, features, targets, batch, fe,
        mesh=None,
    ) -> stats.PopulationStatistics:
        """``train_clf=<sgd-family>`` with population axes: the member
        set (folds x seeds x grid) trains through
        ``models.population.run_population`` — one vmapped program by
        default — and the run reports per-member statistics plus the
        cross-member summary."""
        from ..models import population

        if features is None:
            features, targets = self._host_features(batch, fe)
        config = {
            k: v for k, v in query_map.items() if k.startswith("config_")
        }
        statistics, block = population.run_population(
            name,
            lambda: clf_registry.create(name),
            config,
            features,
            targets,
            pop_spec,
            stage=self._stage,
            mesh=mesh,
        )
        self._note_population_mesh(block)
        if self.telemetry is not None:
            self.telemetry.population = block
        logger.info(
            "trained population %s: %d members (%s)",
            name, block["members"], block["mode"],
        )
        return statistics

    # -- shared-feature fan-out ----------------------------------------

    def _execute_fanout(
        self, query_map, n, features, targets, batch, fe, pop_spec=None,
        classifier_factory=None, mesh=None,
    ) -> stats.FanOutStatistics:
        """``classifiers=a,b,c``: train + test every named classifier
        against the one feature matrix this run already produced.

        Same seed-1 70/30 split, same per-classifier fit/test calls as
        the single-classifier path, so ``classifiers=logreg`` and
        ``train_clf=logreg`` produce identical per-classifier
        statistics — only the ingest+featurization cost stops scaling
        with the classifier count. Duplicate names collapse (last
        wins, dict semantics); ``config_*`` passes to every classifier,
        each picking the keys it knows.
        """
        if "train_clf" in query_map or "load_clf" in query_map:
            raise ValueError(
                "classifiers= replaces train_clf=/load_clf=; "
                "pass exactly one of them"
            )
        if query_map.get("save_clf") == "true":
            raise ValueError(
                "classifiers= fan-out does not support save_clf; "
                "train the model to persist via train_clf="
            )
        if query_map.get("elastic") == "true":
            raise ValueError(
                "classifiers= fan-out does not support elastic=true; "
                "use train_clf= for elastic training"
            )
        names = [s for s in query_map["classifiers"].split(",") if s]
        if not names:
            raise ValueError(
                "classifiers= requires a comma-separated classifier list"
            )
        # the default factory is the registry itself; the seizure path
        # substitutes its true-confusion-matrix variant
        if classifier_factory is None:
            classifier_factory = clf_registry.create

        from ..models import population

        if features is None:
            features, targets = self._host_features(batch, fe)

        train_idx, test_idx = java_compat.train_test_split_indices(n, seed=1)
        # the split rows are gathered ONCE and shared by every plain
        # leg (the old loop re-gathered per leg) ...
        x_train, x_test = features[train_idx], features[test_idx]
        y_train, y_test = targets[train_idx], targets[test_idx]
        x_train_sgd, x_test_sgd = x_train, x_test
        if getattr(features, "dtype", None) == np.float32:
            # ... and for the fused float32 path, the SGD-family legs
            # (which all consume jnp float32) additionally share ONE
            # staged device buffer: their own jnp.asarray() becomes a
            # no-op instead of a fresh host->device transfer per leg.
            # Tree legs keep the numpy slices — handing them device
            # arrays would turn every numpy op into a tiny compiled
            # transfer program (measured: +16 XLA compiles on
            # fanout5) for no gain. Values are bit-identical either
            # way, pinned by the fanout-vs-single parity tests. The
            # host float64 path stays numpy throughout: jnp would
            # downcast it to f32 and change host-path statistics.
            import jax.numpy as jnp

            x_train_sgd = jnp.asarray(x_train)
            x_test_sgd = jnp.asarray(x_test)

        config = {
            k: v for k, v in query_map.items() if k.startswith("config_")
        }
        pop_blocks = {}
        statistics = stats.FanOutStatistics()
        for name in names:
            # each fan-out leg is one span (fanout.<name>) wrapping its
            # train+test stages, so a run report separates the shared
            # featurization from the per-classifier cost
            with events.span(f"fanout.{name}", classifier=name):
                if (
                    pop_spec is not None
                    and pop_spec.active
                    and name in population.SGD_FAMILY
                ):
                    # SGD-family legs expand into the population; the
                    # member axes don't apply to tree growers, whose
                    # legs keep the sequential plain-split path below
                    leg_stats, block = population.run_population(
                        name,
                        lambda name=name: classifier_factory(name),
                        config,
                        features,
                        targets,
                        pop_spec,
                        stage=self._stage,
                        mesh=mesh,
                    )
                    self._note_population_mesh(block)
                    pop_blocks[name] = block
                    statistics[name] = leg_stats
                    obs.metrics.count("pipeline.fanout.classifiers")
                    continue
                if pop_spec is not None and pop_spec.active:
                    logger.warning(
                        "population axes do not apply to %s; the leg "
                        "trains once on the plain split", name,
                    )
                    obs.metrics.count("population.sequential_legs")
                classifier = classifier_factory(name)
                classifier.set_config(config)
                sgd_leg = name in population.SGD_FAMILY
                with self._stage("train", classifier=name):
                    classifier.fit(
                        x_train_sgd if sgd_leg else x_train, y_train
                    )
                logger.info("trained %s", name)
                with self._stage("test", classifier=name):
                    statistics[name] = classifier.test_features(
                        x_test_sgd if sgd_leg else x_test, y_test
                    )
            obs.metrics.count("pipeline.fanout.classifiers")
        if pop_blocks and self.telemetry is not None:
            self.telemetry.population = {"legs": pop_blocks}
        return statistics

    @staticmethod
    def _int_param(query_map, name: str) -> Optional[int]:
        """An optional integer query parameter (None when absent or
        empty). Delegates to the IR's parser — one implementation of
        the contract, one message (PlanValidationError IS a
        ValueError, so legacy matchers hold)."""
        from .plan import _int_param
        return _int_param(query_map, name)

    # -- multi-device mesh resolution ----------------------------------

    def _resolve_mesh(self, request):
        """A grammar-validated :class:`~.plan.MeshRequest` (the IR is
        the single source of the ``devices=``/``mesh_axes=`` grammar
        and its errors — a typo'd axis raises at parse, never silently
        trains unmeshed) -> a built ``jax.sharding.Mesh`` or None.

        None in = no mesh requested — today's single-device path,
        byte-untouched. AVAILABILITY failures degrade: a mesh the
        machine cannot build (more devices than present, unhealthy
        backend) drops to the single-device rung with the evidence in
        the degradation history, the run-report ``mesh`` block, and
        ``pipeline.mesh_unavailable`` — the ladder's top rung.
        """
        if request is None:
            return None
        from ..parallel import mesh as pmesh

        axes = list(request.axes)
        sizes = list(request.shape or ())
        product = int(np.prod(sizes)) if sizes else None
        requested = {
            "devices": request.devices or product,
            "axes": list(axes),
            "shape": list(sizes) or None,
        }
        self.mesh_resolved = {
            "requested": requested,
            "rung": "single_device",
            "shape": None,
        }
        if self.telemetry is not None:
            self.telemetry.mesh = self.mesh_resolved
        leased = getattr(self, "placement_devices", None)
        if leased:
            self.mesh_resolved["leased"] = list(leased)
        try:
            import jax

            if leased:
                # the fleet's device pool granted these ordinals: the
                # mesh is built from exactly them, not a [:n] prefix
                # slice — this is what keeps concurrent plans on one
                # host on DISJOINT chips. An out-of-range ordinal or
                # an unbuildable subset degrades below, identically
                # to any other availability failure.
                host = jax.devices()
                subset = [host[i] for i in leased]
                mesh = pmesh.make_mesh(
                    len(subset),
                    axes=tuple(axes),
                    shape=tuple(sizes) if sizes else None,
                    devices=subset,
                )
            else:
                n = requested["devices"] or len(jax.devices())
                mesh = pmesh.make_mesh(
                    n,
                    axes=tuple(axes),
                    shape=tuple(sizes) if sizes else None,
                )
        except Exception as e:
            # the ladder's top rung: mesh unavailable -> single-device
            evidence = f"{type(e).__name__}: {e}"
            logger.warning(
                "pipeline.mesh unavailable (requested %s): %s; "
                "degrading to the single-device path",
                requested, evidence,
            )
            obs.metrics.count("pipeline.mesh_unavailable")
            events.event("pipeline.mesh_unavailable", error=evidence)
            self.degradation_history.append(
                {"from": "mesh", "error": evidence}
            )
            self.mesh_resolved["error"] = evidence
            return None
        self.mesh_resolved.update(
            rung="mesh",
            shape={k: int(v) for k, v in mesh.shape.items()},
            devices=int(mesh.devices.size),
        )
        events.event(
            "pipeline.mesh_built",
            devices=int(mesh.devices.size),
            axes=",".join(mesh.axis_names),
        )
        return mesh

    # -- multi-process (pod) resolution --------------------------------

    @staticmethod
    def _resolve_pod_knobs(request):
        """Query-over-env resolution of the pod family; returns
        ``(processes, coordinator, process_id)`` with Nones where
        nothing (query or environment) configured a value. The env
        half delegates to ``distributed.resolve_env_knobs`` — the one
        resolution the bootstrap itself uses, so the recorded
        'requested' block cannot diverge from what ran."""
        from ..parallel import distributed

        processes = coordinator = process_id = None
        if request is not None:
            processes = request.processes
            coordinator = request.coordinator
            process_id = request.process_id
        coordinator, processes, process_id = (
            distributed.resolve_env_knobs(
                coordinator, processes, process_id
            )
        )
        return processes, coordinator, process_id

    def _resolve_pod(self, request):
        """``processes=``/``coordinator=``/``process_id=`` (or their
        env twins) -> a live :class:`~..parallel.pod.PodRuntime` over
        the hybrid DCN x ICI mesh, or None.

        None in AND no env pod config = today's path, byte-untouched.
        ``processes=1`` records the request and runs the unchanged
        single-process path (pinned byte-identical). A bootstrap that
        cannot assemble the pod within its deadline (coordinator
        unreachable, peer host missing — distributed.initialize's
        preflight turns both into a catchable
        :class:`~..parallel.distributed.PodBootstrapError`) DEGRADES:
        pod -> single-host mesh -> single device -> host, with the
        evidence in the degradation history, the run report's mesh
        block, and ``pipeline.pod_unavailable``.
        """
        self._pod_block = None
        processes, coordinator, process_id = self._resolve_pod_knobs(
            request
        )
        if processes is None and coordinator is None:
            if process_id is not None:
                # the bootstrap's own partial-setup refusal, raised
                # here too — returning None would silently train
                # single-host on a pod whose launcher lost/typo'd the
                # count and coordinator exports
                raise ValueError(
                    "JAX_PROCESS_ID/process_id is set but neither a "
                    "coordinator address nor a process count is "
                    "configured — refusing to run as single-process "
                    "with a partial multi-host setup"
                )
            return None
        requested = {
            "processes": processes,
            "coordinator": coordinator,
            "process_id": process_id,
        }
        if processes is not None and processes <= 1:
            # the degenerate pod: exactly today's single-process path
            # (pinned byte-identical); only the record changes
            self._pod_block = dict(requested, rung="single_host")
            return None
        from ..parallel import distributed, pod as pod_mod

        try:
            coordinator_used, n_proc, pid = distributed.initialize(
                coordinator, processes, process_id
            )
            if n_proc <= 1:
                self._pod_block = dict(requested, rung="single_host")
                return None
            hmesh = distributed.hybrid_mesh()
        except Exception as e:
            evidence = f"{type(e).__name__}: {e}"
            logger.warning(
                "pipeline.pod unavailable (requested %s): %s; "
                "degrading to the single-host rung",
                requested, evidence,
            )
            obs.metrics.count("pipeline.pod_unavailable")
            events.event("pipeline.pod_unavailable", error=evidence)
            self.degradation_history.append(
                {"from": "pod", "error": evidence}
            )
            self._pod_block = dict(
                requested, rung="single_host", error=evidence
            )
            # a half-assembled bootstrap must not wedge the latch —
            # the next run (or the retry) gets a clean slate
            from ..parallel import distributed as _dist

            _dist.shutdown()
            return None
        dcn_shape = {distributed.DCN_AXIS: n_proc}
        self.mesh_resolved = {
            "requested": requested,
            "rung": "pod",
            "shape": {k: int(v) for k, v in hmesh.shape.items()},
            "devices": int(hmesh.devices.size),
            "processes": int(n_proc),
            "process_id": int(pid),
            "coordinator": coordinator_used,
            "dcn_shape": dcn_shape,
        }
        if self.telemetry is not None:
            self.telemetry.mesh = self.mesh_resolved
        events.event(
            "pipeline.pod_up",
            processes=int(n_proc),
            process_id=int(pid),
            devices=int(hmesh.devices.size),
        )
        obs.metrics.count("pipeline.pod_runs")
        return pod_mod.PodRuntime(
            mesh=hmesh,
            num_processes=int(n_proc),
            process_id=int(pid),
            coordinator=coordinator_used,
        )

    def _note_pod_block(self):
        """Fold a requested-but-not-live pod (``processes=1``, or a
        degraded bootstrap) into the run's mesh block so the report
        and the bench line carry the evidence — the same bookkeeping
        ``_note_population_mesh`` does for the population engine."""
        block = getattr(self, "_pod_block", None)
        if block is None:
            return
        if self.mesh_resolved is None:
            self.mesh_resolved = {
                "requested": {
                    "processes": block.get("processes"),
                    "coordinator": block.get("coordinator"),
                    "process_id": block.get("process_id"),
                },
                "rung": "single_device",
                "shape": None,
            }
        self.mesh_resolved["pod"] = block
        if self.telemetry is not None:
            self.telemetry.mesh = self.mesh_resolved

    def _note_population_mesh(self, block):
        """Fold the population engine's mesh outcome (the rung it
        actually trained on, per-device member counts, fallback
        evidence) into the run-level mesh block, so run_report.json
        and the bench line tell one story. An engine that degraded
        mid-run (population.mesh_fallback) drops the run's recorded
        rung to single_device with its evidence in the degradation
        history — the same bookkeeping the fused-backend ladder keeps.
        """
        mesh_block = (block or {}).get("mesh")
        if not mesh_block or self.mesh_resolved is None:
            return
        self.mesh_resolved["population"] = mesh_block
        if mesh_block.get("rung") != "mesh" and "error" in mesh_block:
            self.mesh_resolved["rung"] = "single_device"
            self.degradation_history.append(
                {"from": "mesh", "error": mesh_block["error"]}
            )

    # -- resilience plumbing -------------------------------------------

    @staticmethod
    def _devices_unhealthy() -> bool:
        """Active device probe after a fused-backend failure: True
        when any device fails the probe (the ladder then skips the
        remaining device rungs). Probe errors count as healthy — the
        ladder's own attempts are the better evidence."""
        try:
            from ..obs import failure

            return not failure.probe_devices(deadline_s=30.0).all_healthy
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("device probe itself failed: %s", e)
            return False

    @staticmethod
    def _elastic_kwargs(query_map) -> Optional[dict]:
        """``elastic=true`` query wiring -> fit_elastic kwargs, or
        None when elastic training is off (the default)."""
        if query_map.get("elastic") != "true":
            return None
        ckpt = query_map.get("checkpoint_path")
        if not ckpt:
            raise ValueError(
                "elastic=true requires a checkpoint_path query parameter"
            )
        from ..checkpoint.manager import CheckpointManager
        from ..obs import failure

        return {
            "manager": CheckpointManager(ckpt),
            "save_every": int(query_map.get("save_every", 1) or 1),
            "max_restarts": int(query_map.get("max_restarts", 3) or 3),
            "sentinel": failure.DivergenceSentinel(),
        }
