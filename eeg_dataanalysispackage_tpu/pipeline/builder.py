"""Query-string pipeline front end (reference: Pipeline/PipelineBuilder.java).

The reference's whole run-time configuration surface is one
``k=v&k=v`` string (README "Run-time configuration";
PipelineBuilder.java:94-295). This builder preserves that surface —
same reserved keys, same required/optional semantics, same error
messages, same seed-1 shuffle + 70/30 split, same ``config_*``
pass-through and ``result_path`` report file — over the TPU-native
data path: epochs load once into a dense batch, features are extracted
by one jitted program, classifiers consume whole batches.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, Optional


from .. import obs
from ..features import registry as fe_registry
from ..io import provider, sources
from ..models import registry as clf_registry
from ..models import stats
from ..utils import java_compat

logger = logging.getLogger(__name__)


def get_query_map(query: str) -> Dict[str, str]:
    """k=v&k=v parse; empty values tolerated (PipelineBuilder.java:49-68)."""
    out: Dict[str, str] = {}
    for param in query.split("&"):
        parts = param.split("=")
        name = parts[0]
        value = parts[1] if len(parts) > 1 else ""
        out[name] = value
    return out


class PipelineBuilder:
    def __init__(
        self,
        query: str,
        filesystem: Optional[sources.FileSystem] = None,
    ):
        self.query = query
        # None = route by the input URI scheme (http/gs/file/local) in
        # the provider; an explicit filesystem overrides routing.
        self._fs = filesystem
        self.statistics: Optional[stats.ClassificationStatistics] = None
        #: per-stage wall times for the run (obs.StageTimer)
        self.timers = obs.StageTimer()

    def execute(self) -> stats.ClassificationStatistics:
        query_map = get_query_map(self.query)
        logger.info("query: %s", query_map)

        # persistent XLA compilation cache before any device work:
        # fresh-chip compiles of the fused variants ran 10-14 min in
        # the r4 sweep, and a repeat run of the same query must read
        # the serialized executable instead (utils/compile_cache;
        # EEG_TPU_COMPILE_CACHE_DIR overrides, EEG_TPU_NO_COMPILE_CACHE
        # disables, failures degrade to plain compiles)
        from ..utils import compile_cache

        cache_dir = compile_cache.enable_persistent_cache()
        if cache_dir:
            logger.info("persistent compile cache: %s", cache_dir)

        # net-new observability: trace_path=<dir> wraps the run in a
        # jax.profiler trace (device + annotated host activity),
        # viewable in TensorBoard/Perfetto
        if "trace_path" in query_map and query_map["trace_path"]:
            with obs.trace(query_map["trace_path"]):
                return self._execute(query_map)
        return self._execute(query_map)

    def _execute(self, query_map) -> stats.ClassificationStatistics:

        # 1. input (PipelineBuilder.java:104-113)
        if "info_file" in query_map:
            files = [query_map["info_file"]]
        elif "eeg_file" in query_map and "guessed_num" in query_map:
            files = [query_map["eeg_file"], query_map["guessed_num"]]
        else:
            raise ValueError("Missing the input file argument")

        odp = provider.OfflineDataProvider(files, filesystem=self._fs)

        # 2. feature extraction (PipelineBuilder.java:128-139).
        # fe=dwt-8-fused is the TPU fast-path mode: ingest + DWT run as
        # one on-device program (provider.load_features_device), so no
        # host epoch batch ever exists and classifiers consume feature
        # rows directly. All other fe= values follow the reference
        # shape: epochs load first, the registry extractor maps them.
        # dwt-<i>-fused-pallas routes the same mode through the Pallas
        # ingest kernel (ops/ingest_pallas.py); dwt-<i>-fused-block
        # through the tile-row-gather + 128-variant-bank formulation
        # (device_ingest.make_block_ingest_featurizer). Any registry
        # wavelet index works, like the host fe= family.
        fused_match = re.fullmatch(
            r"dwt-(\d+)-fused(-pallas|-block|-xla)?",
            query_map.get("fe", ""),
        )
        fused = fused_match is not None
        if fused:
            from ..ops import device_ingest

            wavelet_index = int(fused_match.group(1))
            # bare -fused resolves per platform (block on
            # accelerators - 21x the element gather on the r4 chip -
            # xla on CPU); explicit suffixes always win
            backend = {
                None: device_ingest.default_fused_backend(),
                "-pallas": "pallas",
                "-block": "block",
                "-xla": "xla",
            }[fused_match.group(2)]
            with self.timers.stage("ingest"):
                features, targets = odp.load_features_device(
                    wavelet_index=wavelet_index, backend=backend
                )
            fe = None
            n = len(targets)
        else:
            with self.timers.stage("ingest"):
                batch = odp.load()
            if "fe" not in query_map:
                raise ValueError("Missing the feature extraction argument")
            fe = fe_registry.create(query_map["fe"])
            n = len(batch)
        obs.metrics.count("pipeline.epochs_loaded", n)

        # 3. classifier (PipelineBuilder.java:151-284)
        if "train_clf" in query_map:
            classifier = clf_registry.create(query_map["train_clf"])

            train_idx, test_idx = java_compat.train_test_split_indices(n, seed=1)
            config = {
                k: v for k, v in query_map.items() if k.startswith("config_")
            }
            classifier.set_config(config)
            with self.timers.stage("train"):
                if fused:
                    classifier.fit(features[train_idx], targets[train_idx])
                else:
                    classifier.train(
                        batch.epochs[train_idx], batch.targets[train_idx], fe
                    )
            logger.info("trained %s", query_map["train_clf"])

            if query_map.get("save_clf") == "true":
                if "save_name" not in query_map:
                    raise ValueError(
                        "Please provide a location to save a classifier "
                        "within the save_name query parameter"
                    )
                classifier.save(query_map["save_name"])

            with self.timers.stage("test"):
                statistics = (
                    classifier.test_features(
                        features[test_idx], targets[test_idx]
                    )
                    if fused
                    else classifier.test(
                        batch.epochs[test_idx], batch.targets[test_idx]
                    )
                )

        elif "load_clf" in query_map:
            classifier = clf_registry.create(query_map["load_clf"])
            if "load_name" not in query_map:
                raise ValueError("Classifier location not provided")

            # load mode tests on ALL shuffled data — no split
            # (PipelineBuilder.java:261-278)
            perm = java_compat.java_shuffle_indices(n, seed=1)
            if not fused:
                classifier.set_feature_extraction(fe)
            classifier.load(query_map["load_name"])
            with self.timers.stage("test"):
                statistics = (
                    classifier.test_features(features[perm], targets[perm])
                    if fused
                    else classifier.test(batch.epochs[perm], batch.targets[perm])
                )

        else:
            raise ValueError("Missing classifier argument")

        logger.info("statistics:\n%s", statistics)
        logger.info("stage timings:\n%s", self.timers.report())

        if "result_path" in query_map:
            with open(query_map["result_path"], "w") as f:
                # PrintWriter.println appends a newline to toString()
                f.write(str(statistics) + "\n")

        self.statistics = statistics
        return statistics
