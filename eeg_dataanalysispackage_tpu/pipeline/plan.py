"""The ExecutionPlan IR: one query string, parsed and validated, no
side effects.

``pipeline/builder.py`` grew ~190 lines per PR until parsing,
validation, caching, fan-out, populations, chaos, mesh, and telemetry
wiring all lived in one monolith (ROADMAP item 5). This module is the
parse/validate half of the split: :meth:`ExecutionPlan.parse` turns a
reference-shaped ``k=v&k=v`` query into a **typed, frozen plan** —
every run-time knob from ``task=`` to ``devices=`` becomes a field —
and raises every *statically decidable* conflict as a
:class:`PlanValidationError` with the exact message the monolithic
builder raised, so callers (and their tests) cannot tell the paths
apart. The execution half lives in ``scheduler/`` (a resident
:class:`~eeg_dataanalysispackage_tpu.scheduler.executor.PlanExecutor`
running N plans concurrently in per-plan fault domains); the old
``PipelineBuilder.execute`` entry point is a thin shim over both.

Purity contract: ``parse`` reads ONLY the query string. Environment
-resolved knobs (``EEG_TPU_PRECISION``, ``EEG_TPU_FAULTS``,
``EEG_TPU_OVERLAP``, report dirs …) are *execution-time* inputs — two
parses of the same query are equal in any process, which is what makes
a journaled plan replayable after a crash: the journal stores the
query, recovery re-parses it, and the plan is the same plan.

Validation division of labour: conflicts decidable from the query
alone (mutually exclusive parameters, grammar errors, missing required
arguments) raise HERE, before any I/O; conditions that need runtime
state (mesh availability, device health, the bf16 accuracy gate,
``class_weight=balanced`` ratios) stay in the executor/builder, which
keeps its own checks as defense in depth.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, Mapping, Optional, Tuple


class PlanValidationError(ValueError):
    """A query string fails IR validation. Subclasses ``ValueError``
    and reuses the legacy builder messages verbatim, so every caller
    (and every pinned test) that matched on the monolithic builder's
    errors keeps matching."""


def _raise(message: str) -> None:
    raise PlanValidationError(message)


def _int_param(query_map: Mapping[str, str], name: str) -> Optional[int]:
    """The builder's optional-integer parameter contract (None when
    absent or empty), message included."""
    value = query_map.get(name, "")
    if not value:
        return None
    try:
        return int(value)
    except ValueError:
        _raise(
            f"query parameter {name}= must be an integer, "
            f"got {value!r}"
        )


@dataclasses.dataclass(frozen=True)
class MeshRequest:
    """The ``devices=``/``mesh_axes=`` grammar, validated. Whether the
    machine can BUILD the mesh is an availability question the
    executor answers (mesh-unavailable is the degradation ladder's top
    rung, never a parse error)."""

    devices: Optional[int]
    axes: Tuple[str, ...]
    shape: Optional[Tuple[int, ...]]


@dataclasses.dataclass(frozen=True)
class PodRequest:
    """The ``processes=``/``coordinator=``/``process_id=`` grammar,
    validated — the multi-process (pod) knob family, env twins
    ``JAX_NUM_PROCESSES``/``JAX_COORDINATOR``(``_ADDRESS``)/
    ``JAX_PROCESS_ID``. Whether the pod can actually BOOTSTRAP
    (coordinator reachable, peers alive) is an availability question
    the executor answers: bootstrap failure is the ladder's pod rung
    degrading to single-host, never a parse error. Fields left None
    resolve from the environment at execution time (parse purity)."""

    processes: Optional[int]
    coordinator: Optional[str]
    process_id: Optional[int]


@dataclasses.dataclass(frozen=True)
class ExecutionPlan:
    """One validated pipeline run. Frozen: a plan is a value — the
    scheduler journals it, retries it, and replays it after a crash
    without re-deciding anything."""

    #: the verbatim query string (the journal's replay currency)
    query: str
    #: the parsed k=v map (first-'='-split; the execution engine's
    #: working form — every field below is derived from it)
    query_map: Mapping[str, str]

    # -- input -----------------------------------------------------------
    input_files: Tuple[str, ...]
    task: str  # "p300" | "seizure"
    serve: bool

    # -- serving lifecycle (serve/lifecycle.py) --------------------------
    #: ``adapt=true``: stream labeled feedback through the resident
    #: service's lifecycle manager (partial-fit + shadow swap + drift)
    adapt: bool
    #: the ``swap_gate=`` promotion policy string, grammar-validated
    #: ("off" | "cost[:<ratio>]"), or None (the default cost gate)
    swap_gate: Optional[str]
    #: windowed-statistics size for the gate/drift windows, or None
    #: (the lifecycle default)
    drift_window: Optional[int]

    # -- features --------------------------------------------------------
    fe: Optional[str]
    fused: bool
    fused_wavelet: Optional[int]
    #: explicit fused-backend suffix ("pallas"|"block"|"xla"|"decode")
    #: or None (platform default resolves at execution)
    fused_backend: Optional[str]
    #: query-requested numeric class, or None (env/default resolves at
    #: execution — parse purity)
    precision: Optional[str]
    overlap: Optional[bool]
    cache: bool
    degrade: bool
    #: whether this plan participates in cross-tenant plan-prefix
    #: dedup (scheduler/dedup.py); ``dedup=false`` opts one plan out
    dedup: bool

    # -- classifier action ----------------------------------------------
    train_clf: Optional[str]
    load_clf: Optional[str]
    classifiers: Tuple[str, ...]
    save_clf: bool
    save_name: Optional[str]
    load_name: Optional[str]
    elastic: bool
    checkpoint_path: Optional[str]
    #: the config_* pass-through surface, verbatim
    config: Mapping[str, str]

    # -- population axes -------------------------------------------------
    #: models.population.PopulationSpec, or None when the run never
    #: reaches population routing (serve mode parses no spec — the
    #: monolithic builder ignored the axes there, so the IR must too)
    population: Optional[object]

    # -- multi-device ----------------------------------------------------
    mesh: Optional[MeshRequest]

    # -- multi-process (pod) ---------------------------------------------
    pod: Optional[PodRequest]

    # -- seizure workload ------------------------------------------------
    window: Optional[int]
    stride: Optional[int]
    label_overlap: Optional[float]
    class_weight: Optional[str]
    cost_fp: float
    cost_fn: float

    # -- infrastructure --------------------------------------------------
    ingest_workers: Optional[int]
    prefetch: Optional[int]
    faults: Optional[str]
    faults_seed: int
    result_path: Optional[str]
    trace_path: Optional[str]
    report: Optional[str]

    @property
    def population_active(self) -> bool:
        return self.population is not None and self.population.active

    # -- canonicalization ------------------------------------------------
    #
    # The query-optimizer half of the IR: two queries that MEAN the
    # same run must canonicalize to the same key, whatever order their
    # parameters were spelled in. The keys are built from the TYPED
    # fields only — never the raw query string — so `a=1&b=2` and
    # `b=2&a=1` collapse, and they are env-knob-free by construction:
    # an environment-resolved knob (EEG_TPU_PRECISION, EEG_TPU_FAULTS,
    # report dirs) never reaches a typed field (the parse-purity
    # contract above), so the same key means the same plan in any
    # process with any environment.

    #: fields excluded from canonicalization — observability and
    #: scheduling knobs that are pinned to never change statistics
    #: (ingest_workers/prefetch/overlap are bit-identical at any
    #: value; faults are absorbed by the resilience machinery by
    #: contract; result/trace/report paths are artifact locations)
    _NON_SEMANTIC = (
        "query", "query_map", "ingest_workers", "prefetch", "overlap",
        "faults", "faults_seed", "result_path", "trace_path", "report",
    )

    def canonical_fields(self) -> Dict[str, Any]:
        """The plan's semantic fields in hashable canonical form,
        keyed by field name (sorted at hash time — parameter order
        cannot leak in)."""
        out: Dict[str, Any] = {}
        for field in dataclasses.fields(self):
            if field.name in self._NON_SEMANTIC:
                continue
            value = getattr(self, field.name)
            if field.name == "population":
                value = None if value is None else (
                    value.cv, value.cv_mode, value.seeds, value.sweep,
                    value.mode, value.fe_configs,
                )
            elif field.name == "mesh":
                value = None if value is None else (
                    value.devices, value.axes, value.shape,
                )
            elif field.name == "pod":
                value = None if value is None else (
                    value.processes, value.coordinator, value.process_id,
                )
            elif field.name == "config":
                value = tuple(sorted(value.items()))
            elif isinstance(value, tuple):
                value = tuple(value)
            out[field.name] = value
        return out

    def canonical_key(self) -> str:
        """Order-insensitive, env-knob-free digest of the whole plan:
        the identity a plan-level result cache or audit trail would
        key on."""
        return self._digest(
            b"eeg-tpu-plan-canonical-v1", self.canonical_fields()
        )

    def prefix_fields(self) -> Optional[Dict[str, Any]]:
        """The ingest+featurize half of the plan — the fields that
        determine the ``(features, targets)`` matrix BEFORE any
        classifier runs — or None when the plan has no dedupable
        prefix (serve mode streams requests; it never materializes
        the batch feature matrix).

        Deliberately excluded: the classifier action (train/load/
        fan-out/population grid/costs — the suffix), ``cache``/
        ``degrade``/``dedup`` (they change where features come from,
        never their bytes), and everything in ``_NON_SEMANTIC``. The
        fused backend IS included: rungs are only tolerance-identical
        across backends, and the prefix-dedup contract is
        byte-identity."""
        if self.serve:
            return None
        return {
            "input_files": tuple(self.input_files),
            "task": self.task,
            "fe": self.fe,
            "fe_configs": (
                tuple(self.population.fe_configs)
                if self.population is not None
                and self.population.fe_configs
                else ()
            ),
            "fused": self.fused,
            "fused_wavelet": self.fused_wavelet,
            "fused_backend": self.fused_backend,
            "precision": self.precision,
            "window": self.window,
            "stride": self.stride,
            "label_overlap": self.label_overlap,
        }

    def prefix_key(self) -> Optional[str]:
        """Digest of :meth:`prefix_fields` — the shared-work identity
        two tenants' plans are compared on (scheduler/dedup.py), or
        None when the plan has no dedupable prefix."""
        fields = self.prefix_fields()
        if fields is None:
            return None
        return self._digest(b"eeg-tpu-plan-prefix-v1", fields)

    @staticmethod
    def _digest(tag: bytes, fields: Mapping[str, Any]) -> str:
        h = hashlib.blake2b(digest_size=20)
        h.update(tag)
        for name in sorted(fields):
            h.update(repr((name, fields[name])).encode())
        return h.hexdigest()

    # -- placement -------------------------------------------------------

    def device_footprint(self) -> Dict[str, Any]:
        """The plan's static resource footprint for the fleet device
        pool (scheduler/placement.py): ``{"devices", "hosts",
        "memory_class"}``, derived purely from the already-parsed
        ``devices=``/``mesh_axes=``/``processes=``/population knobs.

        - ``devices`` — exclusive device ordinals the plan wants on
          its host. ``0`` means "every device present" (an axes-only
          mesh request sizes itself to the host at execution time);
          any positive count is the gang size the scheduler must
          satisfy all-or-nothing. A plan with no mesh request is one
          ordinal: a capacity token, since the single-device path runs
          on the default device.
        - ``hosts`` — ``processes=`` for pod plans, else 1. The fleet
          treats hosts > 1 as pod-assist work (peer replicas enlist as
          worker processes), not as extra local ordinals.
        - ``memory_class`` — ``"serve" | "light" | "standard" |
          "heavy"``: a coarse working-set class for operators and the
          backfill view. Heavy = a multi-device gang (4+, or
          whole-host), a pod, or a 32+-member population stack;
          standard = any smaller population/sweep; light = a plain
          single-model batch run; serve plans are their own class
          (resident service, admission-controlled elsewhere).

        Pure and side-effect-free: no environment, no backend, no
        ``jax`` import — and derived, so it is canonical-key-neutral
        by construction (two queries with one canonical key have one
        footprint).
        """
        devices = 1
        if self.mesh is not None:
            if self.mesh.shape:
                product = 1
                for extent in self.mesh.shape:
                    product *= int(extent)
                devices = product
            elif self.mesh.devices:
                devices = int(self.mesh.devices)
            else:
                devices = 0  # axes-only: the whole host, sized later
        if devices < 0:
            raise PlanValidationError(
                f"mesh request resolves to a negative device count "
                f"({devices}); the parse grammar should have refused it"
            )
        hosts = 1
        if self.pod is not None and self.pod.processes:
            hosts = max(1, int(self.pod.processes))
        members = 1
        if self.population_active:
            members = (
                self.population.cv
                * self.population.seeds
                * self.population.grid_points()
                * max(1, len(self.population.fe_configs))
            )
        if self.serve:
            memory_class = "serve"
        elif devices == 0 or devices >= 4 or hosts > 1 or members >= 32:
            memory_class = "heavy"
        elif members > 1:
            memory_class = "standard"
        else:
            memory_class = "light"
        return {
            "devices": devices,
            "hosts": hosts,
            "memory_class": memory_class,
        }

    @classmethod
    def parse(cls, query: str) -> "ExecutionPlan":
        """Query string -> validated plan; raises
        :class:`PlanValidationError` (a ``ValueError``) with the legacy
        builder messages on every statically decidable conflict."""
        from . import builder as _builder

        query_map: Dict[str, str] = _builder.get_query_map(query)

        # 1. input (PipelineBuilder.java:104-113)
        if "info_file" in query_map:
            input_files: Tuple[str, ...] = (query_map["info_file"],)
        elif "eeg_file" in query_map and "guessed_num" in query_map:
            input_files = (
                query_map["eeg_file"], query_map["guessed_num"]
            )
        else:
            _raise("Missing the input file argument")

        serve = query_map.get("serve") == "true"

        # 1b. the serving-lifecycle knob family (serve/lifecycle.py):
        # grammar here, behavior in the executor — a typo'd gate must
        # never silently promote (or silently fail to)
        adapt_value = query_map.get("adapt", "")
        if adapt_value not in ("", "true", "false"):
            _raise(
                f"adapt= must be true or false, got {adapt_value!r}"
            )
        adapt = adapt_value == "true"
        if adapt and not serve:
            _raise(
                "adapt=true streams labeled feedback through the "
                "resident serving service; it requires serve=true"
            )
        swap_gate = query_map.get("swap_gate") or None
        if swap_gate is not None:
            if not adapt:
                _raise(
                    "swap_gate= gates lifecycle promotions; it "
                    "requires adapt=true"
                )
            from ..serve import lifecycle as _lifecycle

            try:
                _lifecycle.parse_swap_gate(swap_gate)
            except ValueError as e:
                _raise(str(e))
        drift_window = _int_param(query_map, "drift_window")
        if drift_window is not None:
            if not adapt:
                _raise(
                    "drift_window= sizes the lifecycle's windowed "
                    "statistics; it requires adapt=true"
                )
            if drift_window < 1:
                _raise(
                    f"drift_window= must be >= 1, got {drift_window}"
                )
        for knob in ("adapt_batch", "adapt_iters"):
            if _int_param(query_map, knob) is not None and not adapt:
                # the whole knob family is loud without adapt=true —
                # a forgotten adapt= must never silently serve
                # without adaptation
                _raise(
                    f"{knob}= tunes the lifecycle's partial-fit "
                    "batches; it requires adapt=true"
                )

        # 2. mesh grammar (the availability half stays with the
        # executor; order matches the monolith — mesh grammar is
        # checked before the task routing), then the multi-process
        # (pod) grammar that sits above it on the ladder
        mesh = cls._parse_mesh(query_map, serve)
        pod = cls._parse_pod(query_map, serve)

        # 3. task
        task = query_map.get("task", "") or "p300"
        if task not in ("p300", "seizure"):
            _raise(
                f"unknown task {query_map.get('task')!r}; supported: "
                f"p300 (default), seizure"
            )
        if task != "seizure" and query_map.get("fe_sweep"):
            _raise(
                "fe_sweep= compares feature configs over the seizure "
                "workload; it requires task=seizure"
            )

        # 4. infrastructure knobs (typed; messages via _int_param)
        ingest_workers = _int_param(query_map, "ingest_workers")
        prefetch = _int_param(query_map, "prefetch")
        faults = query_map.get("faults") or None
        faults_seed = int(query_map.get("faults_seed", 0) or 0)
        if faults:
            # grammar check only — the plan is parsed again (fresh
            # call counters) by whoever executes; FaultSpecError is a
            # ValueError, same surface as before
            from ..obs import chaos

            chaos.parse_fault_spec(faults, seed=faults_seed)

        # 5. features
        fe = query_map.get("fe") or None
        fused_wavelet: Optional[int] = None
        fused_backend: Optional[str] = None
        fused = False
        precision = query_map.get("precision") or None
        overlap_value = query_map.get("overlap", "")
        overlap = (
            overlap_value == "true" if overlap_value in ("true", "false")
            else None
        )
        if task == "p300" and not serve:
            # the overlap=/precision= value checks live on the p300
            # batch branch ONLY, where the monolithic builder ran them
            # — the seizure and serve routes returned before reaching
            # them, so a stray value there was (and stays) ignored
            if overlap_value not in ("", "true", "false"):
                _raise(
                    f"overlap= must be true or false, "
                    f"got {overlap_value!r}"
                )
            if precision is not None and precision not in (
                "f32", "bf16", "int8", "int4"
            ):
                _raise(
                    f"precision= must be f32, bf16, int8, or int4, "
                    f"got {precision!r}"
                )
            import re

            fused_match = re.fullmatch(
                r"dwt-(\d+)-fused(-pallas|-block|-xla|-decode)?",
                query_map.get("fe", ""),
            )
            fused = fused_match is not None
            if fused:
                fused_wavelet = int(fused_match.group(1))
                suffix = fused_match.group(2)
                if suffix is not None:
                    fused_backend = suffix[1:]
            if precision in ("bf16", "int8", "int4"):
                if not fused:
                    _raise(
                        f"precision={precision} applies to the fused "
                        "fe= modes "
                        "(fe=dwt-<i>-fused[-decode]); host-path "
                        "features are the bit-parity reference and "
                        "stay f64"
                    )
                if fused_backend is not None and fused_backend != "decode":
                    _raise(
                        f"precision={precision} rides the decode rung; "
                        f"it cannot combine with the explicit "
                        f"fe=...-fused-{fused_backend} backend"
                    )
            if fe is None:
                _raise("Missing the feature extraction argument")

        # 6. population axes (never parsed in serve mode — the
        # monolith routed to serving before building the spec, so a
        # serve run with cv= is ignored, not an error)
        population = None
        if not serve:
            from ..models import population as population_mod

            population = population_mod.PopulationSpec.from_query_map(
                query_map
            )

        # 7. classifier action + conflicts
        train_clf = query_map.get("train_clf") if (
            "train_clf" in query_map
        ) else None
        load_clf = query_map.get("load_clf") if (
            "load_clf" in query_map
        ) else None
        classifiers: Tuple[str, ...] = ()
        save_clf = query_map.get("save_clf") == "true"
        elastic = query_map.get("elastic") == "true"
        checkpoint_path = query_map.get("checkpoint_path") or None
        if not serve:
            cls._validate_action(
                query_map, task, population, train_clf, load_clf,
                save_clf, elastic, checkpoint_path,
            )
            if "classifiers" in query_map:
                classifiers = tuple(
                    s for s in query_map["classifiers"].split(",") if s
                )

        # 8. the seizure workload's typed knobs (validated like
        # builder.seizure_weights, minus the balanced ratio that needs
        # the targets)
        window = stride = None
        label_overlap = None
        class_weight = None
        cost_fp = cost_fn = 1.0
        if task == "seizure":
            window = _int_param(query_map, "window")
            stride = _int_param(query_map, "stride")
            label_overlap = float(
                query_map.get("label_overlap") or 0.5
            )
            cost_fp = float(query_map.get("cost_fp") or 1.0)
            cost_fn = float(query_map.get("cost_fn") or 1.0)
            if cost_fp <= 0 or cost_fn <= 0:
                _raise(
                    f"cost_fp=/cost_fn= must be > 0, got "
                    f"{cost_fp}/{cost_fn}"
                )
            cw = query_map.get("class_weight", "")
            if cw and cw != "balanced":
                try:
                    wp = float(cw)
                except ValueError:
                    _raise(
                        f"class_weight= must be 'balanced' or a float, "
                        f"got {cw!r}"
                    )
                if wp <= 0:
                    _raise(
                        f"class_weight= must be > 0, got {wp}"
                    )
            class_weight = cw or None
            if not serve:
                fe_names = (
                    list(population.fe_configs)
                    if population is not None and population.fe_configs
                    else ([fe] if fe else [])
                )
                if not fe_names:
                    _raise("Missing the feature extraction argument")
                for name in fe_names:
                    if "-fused" in name:
                        _raise(
                            "task=seizure extracts features on the "
                            "host; fe= must be a registry form (e.g. "
                            "dwt-4:level=4:stats=energy), not a "
                            "-fused mode"
                        )

        return cls(
            query=query,
            query_map=query_map,
            input_files=input_files,
            task=task,
            serve=serve,
            adapt=adapt,
            swap_gate=swap_gate,
            drift_window=drift_window,
            fe=fe,
            fused=fused,
            fused_wavelet=fused_wavelet,
            fused_backend=fused_backend,
            precision=precision,
            overlap=overlap,
            cache=query_map.get("cache", "true") != "false",
            degrade=query_map.get("degrade", "true") != "false",
            dedup=query_map.get("dedup", "true") != "false",
            train_clf=train_clf,
            load_clf=load_clf,
            classifiers=classifiers,
            save_clf=save_clf,
            save_name=query_map.get("save_name") or None,
            load_name=query_map.get("load_name") or None,
            elastic=elastic,
            checkpoint_path=checkpoint_path,
            config={
                k: v for k, v in query_map.items()
                if k.startswith("config_")
            },
            population=population,
            mesh=mesh,
            pod=pod,
            window=window,
            stride=stride,
            label_overlap=label_overlap,
            class_weight=class_weight,
            cost_fp=cost_fp,
            cost_fn=cost_fn,
            ingest_workers=ingest_workers,
            prefetch=prefetch,
            faults=faults,
            faults_seed=faults_seed,
            result_path=query_map.get("result_path") or None,
            trace_path=query_map.get("trace_path") or None,
            report=query_map.get("report") or None,
        )

    # -- validation helpers ---------------------------------------------

    @staticmethod
    def _parse_mesh(
        query_map: Mapping[str, str], serve: bool
    ) -> Optional[MeshRequest]:
        """The grammar section of the builder's ``_resolve_mesh``,
        verbatim messages; returns the typed request or None."""
        import numpy as np

        devices_param = _int_param(query_map, "devices")
        axes_value = query_map.get("mesh_axes", "")
        if devices_param is None and not axes_value:
            return None
        if serve:
            _raise(
                "devices=/mesh_axes= shard the batch pipeline; they "
                "cannot combine with serve=true (the serving engine "
                "is resident single-device)"
            )
        axes = []
        sizes = []
        if axes_value:
            for part in axes_value.split(","):
                name, sep, size = part.partition(":")
                name = name.strip()
                if not name:
                    _raise(
                        f"mesh_axes= has an empty axis name in "
                        f"{axes_value!r}"
                    )
                axes.append(name)
                if sep:
                    try:
                        sizes.append(int(size))
                    except ValueError:
                        _raise(
                            f"mesh_axes= axis {name!r} has a "
                            f"non-integer extent {size!r}"
                        )
            if len(set(axes)) != len(axes):
                _raise("mesh_axes= repeats an axis name")
            if sizes and len(sizes) != len(axes):
                _raise(
                    "mesh_axes= extents must be given for every axis "
                    "or for none (e.g. mesh_axes=data:2,time:4)"
                )
            if len(axes) > 1 and not sizes:
                _raise(
                    "multi-axis mesh_axes= needs explicit extents "
                    "(e.g. mesh_axes=data:2,time:4)"
                )
        if not axes:
            from ..parallel import mesh as pmesh

            axes = [pmesh.DATA_AXIS]
        if devices_param is not None and devices_param < 1:
            _raise("devices= must be >= 1")
        product = int(np.prod(sizes)) if sizes else None
        if (
            product is not None
            and devices_param is not None
            and product != devices_param
        ):
            _raise(
                f"mesh_axes= extents cover {product} devices but "
                f"devices={devices_param}; drop one or make them agree"
            )
        return MeshRequest(
            devices=devices_param,
            axes=tuple(axes),
            shape=tuple(sizes) if sizes else None,
        )

    @staticmethod
    def _parse_pod(
        query_map: Mapping[str, str], serve: bool
    ) -> Optional[PodRequest]:
        """The ``processes=``/``coordinator=``/``process_id=`` grammar.
        Statically decidable errors only — reachability degrades at
        execution; a typo'd knob must never silently train
        single-host."""
        processes = _int_param(query_map, "processes")
        process_id = _int_param(query_map, "process_id")
        coordinator = query_map.get("coordinator") or None
        if processes is None and process_id is None and coordinator is None:
            return None
        if serve:
            _raise(
                "processes=/coordinator=/process_id= configure the "
                "multi-process batch pipeline; they cannot combine "
                "with serve=true (the serving engine is resident "
                "single-process)"
            )
        if (query_map.get("task", "") or "p300") == "seizure":
            # the seizure ingest (sliding windows over host-extracted
            # subband features) has no partitioned pod path yet —
            # every process would redo 100% of the work while the
            # mesh block claimed the pod rung; refuse loudly
            _raise(
                "processes=/coordinator=/process_id= partition the "
                "fused P300 ingest; task=seizure has no pod path yet "
                "— run it single-host (devices= still shards the "
                "member axis)"
            )
        if query_map.get("precision") in ("bf16", "int8", "int4"):
            # statically decidable half of the builder's runtime
            # check (an env-resolved EEG_TPU_PRECISION still lands on
            # the execution-time guard): the reduced-precision gate
            # needs the f32 reference recording in memory, which the
            # partitioned ingest deliberately never stages
            _raise(
                f"precision={query_map.get('precision')} runs behind "
                "a per-run f32 reference gate the pod-partitioned "
                "ingest cannot stage; pod runs compute f32"
            )
        if processes is not None and processes < 1:
            _raise("processes= must be >= 1")
        if coordinator is not None:
            host, sep, port = coordinator.rpartition(":")
            if not sep or not host:
                _raise(
                    f"coordinator= must be host:port, "
                    f"got {coordinator!r}"
                )
            try:
                port_n = int(port)
            except ValueError:
                _raise(
                    f"coordinator= port must be an integer, "
                    f"got {port!r}"
                )
            if not 0 < port_n < 65536:
                _raise(
                    f"coordinator= port must be in (0, 65536), "
                    f"got {port_n}"
                )
        if process_id is not None:
            if process_id < 0:
                _raise("process_id= must be >= 0")
            if processes is None:
                _raise(
                    "process_id= identifies this process within "
                    "processes=N; pass both"
                )
            if process_id >= processes:
                _raise(
                    f"process_id= must be < processes="
                    f"{processes}, got {process_id}"
                )
        return PodRequest(
            processes=processes,
            coordinator=coordinator,
            process_id=process_id,
        )

    @staticmethod
    def _validate_action(
        query_map, task, population, train_clf, load_clf, save_clf,
        elastic, checkpoint_path,
    ) -> None:
        """The classifier-action conflict rules, lifted verbatim from
        the monolithic builder's three routing branches."""
        from ..models import population as population_mod

        pop_active = population is not None and population.active
        axes_label = (
            "cv=/seeds=/sweep=/fe_sweep=" if task == "seizure"
            else "cv=/seeds=/sweep="
        )
        if pop_active:
            if load_clf is not None:
                _raise(
                    f"population axes ({axes_label}) train models; "
                    f"they cannot combine with load_clf="
                )
            if save_clf:
                _raise(
                    "population runs train many members; save_clf= "
                    "has no single model to persist"
                )
            if elastic:
                _raise(
                    "population training does not support elastic=true; "
                    "the stacked program has no per-member checkpoints"
                )
        if population is not None and population.fe_configs:
            if "classifiers" in query_map:
                _raise(
                    "fe_sweep= expands the train_clf= population; it "
                    "cannot combine with classifiers="
                )
        if "classifiers" in query_map:
            if train_clf is not None or load_clf is not None:
                _raise(
                    "classifiers= replaces train_clf=/load_clf=; "
                    "pass exactly one of them"
                )
            if save_clf:
                _raise(
                    "classifiers= fan-out does not support save_clf; "
                    "train the model to persist via train_clf="
                )
            if elastic:
                _raise(
                    "classifiers= fan-out does not support elastic=true; "
                    "use train_clf= for elastic training"
                )
            if not [
                s for s in query_map["classifiers"].split(",") if s
            ]:
                _raise(
                    "classifiers= requires a comma-separated "
                    "classifier list"
                )
            return
        if train_clf is not None:
            if pop_active and train_clf not in population_mod.SGD_FAMILY:
                sgd = ", ".join(population_mod.SGD_FAMILY)
                _raise(
                    f"population axes ({axes_label}) apply to the SGD "
                    f"family ({sgd}); {train_clf!r} trains one model "
                    f"per run"
                )
            if elastic and not pop_active and not checkpoint_path:
                _raise(
                    "elastic=true requires a checkpoint_path query "
                    "parameter"
                )
            if save_clf and "save_name" not in query_map:
                _raise(
                    "Please provide a location to save a classifier "
                    "within the save_name query parameter"
                )
            return
        if load_clf is not None:
            if "load_name" not in query_map:
                _raise("Classifier location not provided")
            return
        _raise("Missing classifier argument")
