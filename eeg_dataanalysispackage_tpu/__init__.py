"""TPU-native EEG data-analysis framework.

A ground-up JAX/XLA re-design of the capabilities of
``NEUROINFORMATICS-GROUP-FAV-KIV-ZCU/EEG_DataAnalysisPackage`` (the
"Spark_EEG_Analysis" P300 guess-the-number BCI pipeline): BrainVision
ingest -> stimulus-locked epoching -> Daubechies-8 DWT features ->
target/non-target classification, rebuilt TPU-first.

Layer map (mirrors SURVEY.md section 7):

- ``io``        BrainVision vhdr/vmrk/eeg parsing, info.txt sources,
                host staging (native C++ demux when built).
- ``epochs``    marker -> window gather, baseline correction, the
                order-dependent target/non-target balance scan.
- ``ops``       numeric kernels: db8 DWT (host-parity and batched XLA
                variants), baseline, normalization, FFT band-pass.
- ``features``  the ``fe=`` plugin registry (dwt-8, dwt-8-tpu).
- ``models``    the ``train_clf=`` plugin registry (logreg, svm, dt,
                rf, nn) + classification statistics.
- ``parallel``  jax.sharding Mesh construction, data-parallel batch
                sharding, collective-based SGD.
- ``pipeline``  query-string DSL front end (parity with the reference
                run-time configuration surface) + CLI.
- ``utils``     Java interop shims (java.util.Random / shuffle for
                split parity), config handling.
- ``checkpoint`` model/optimizer persistence.
- ``obs``       profiling hooks, stage timers, metrics.
"""

__version__ = "0.1.0"
