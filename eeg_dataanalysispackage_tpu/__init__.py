"""TPU-native EEG data-analysis framework.

A ground-up JAX/XLA re-design of the capabilities of
``NEUROINFORMATICS-GROUP-FAV-KIV-ZCU/EEG_DataAnalysisPackage`` (the
"Spark_EEG_Analysis" P300 guess-the-number BCI pipeline): BrainVision
ingest -> stimulus-locked epoching -> Daubechies-8 DWT features ->
target/non-target classification, rebuilt TPU-first.

Layer map (mirrors SURVEY.md section 7):

- ``io``        BrainVision vhdr/vmrk/eeg parsing (C++ parsers/demux
                when built), info.txt sources, pluggable filesystem,
                host->device prefetch staging, CSV/text export.
- ``epochs``    marker -> window gather, baseline correction, the
                order-dependent target/non-target balance scan.
- ``ops``       numeric kernels: eegdsp-parity DWT (host f64, batched
                XLA einsum, Pallas), signal utils, and the fused
                on-device ingest (``device_ingest``).
- ``features``  the ``fe=`` plugin registry (dwt-<0..17>, -tpu,
                -pallas backends).
- ``models``    the ``train_clf=`` plugin registry (logreg, svm, dt,
                rf, nn, gbt, dt/rf-tpu on-device growth) +
                classification statistics.
- ``parallel``  jax.sharding Mesh construction, data-parallel train
                step, multi-host DCN x ICI runtime (``distributed``),
                sequence-parallel + bounded-memory streaming
                (``streaming``).
- ``pipeline``  query-string DSL front end (parity with the reference
                run-time configuration surface; ``fe=dwt-8-fused``
                fast path) + CLI.
- ``utils``     Java interop shims (java.util.Random / shuffle for
                split parity), constants.
- ``checkpoint`` step-numbered pytree checkpoints + model persistence.
- ``obs``       profiling/trace hooks, stage timers, metrics, failure
                detection + elastic recovery.
"""

__version__ = "0.1.0"
