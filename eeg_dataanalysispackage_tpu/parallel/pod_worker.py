"""One pod-member process for fleet pod-assist (``python -m
eeg_dataanalysispackage_tpu.parallel.pod_worker --query=...``).

The fleet's pod routing (gateway/fleet.py) cannot run
``jax.distributed.initialize`` inside a gateway replica — the
replica's JAX backend initialized long ago, and jax forbids a
bootstrap after that point — so every pod member, INCLUDING the
coordinator's own process 0, is a fresh subprocess running this
module. The query alone decides pod membership (``processes=``/
``coordinator=``/``process_id=`` ride in it); the builder's existing
``_resolve_pod`` ladder does the bootstrap, which is what makes the
degradation story free: a member whose preflight cannot assemble the
pod drops to the single-host rung and still produces the
byte-identical statistics (the PR 14 parity pin).

The last stdout line is one JSON object ``{"sha", "statistics"}`` —
the coordinator reaps its process-0 child for the statistics it
journals; worker ranks' outputs are discarded.

``--parent-pid=N`` arms a watchdog: when the spawning process dies
(SIGKILL included — this process is reparented and ``os.getppid()``
changes), the member self-exits instead of orphan-running a
multi-minute plan nobody will read. This is what bounds the blast
radius of a SIGKILLed coordinator to "the pod degrades", never "CPUs
burn on abandoned ranks".
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time

#: exit code for a watchdog self-exit, distinct from plan failures so
#: a reaper can tell "orphaned" from "broken"
ORPHANED_EXIT = 70


def _watch_parent(parent_pid: int, poll_s: float = 0.5) -> None:
    def _loop():
        while True:
            if os.getppid() != parent_pid:
                os._exit(ORPHANED_EXIT)
            time.sleep(poll_s)

    threading.Thread(
        target=_loop, name="pod-worker-parent-watchdog", daemon=True
    ).start()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    query = None
    parent_pid = None
    for arg in argv:
        if arg.startswith("--query="):
            query = arg.split("=", 1)[1]
        elif arg.startswith("--parent-pid="):
            parent_pid = int(arg.split("=", 1)[1])
        else:
            raise SystemExit(f"unknown argument {arg!r}")
    if not query:
        raise SystemExit("--query= is required")
    if parent_pid is not None:
        _watch_parent(parent_pid)

    from ..pipeline.builder import PipelineBuilder
    from ..pipeline.plan import ExecutionPlan
    from ..scheduler import runtime

    plan = ExecutionPlan.parse(query)
    builder = PipelineBuilder(plan.query)
    statistics = runtime.execute_plan(plan, builder)
    text = str(statistics)
    print(json.dumps({
        "sha": hashlib.sha256(text.encode()).hexdigest(),
        "statistics": text,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
