"""Multi-host runtime: process bootstrap, hybrid DCN x ICI meshes,
per-process global-batch staging.

The reference's cluster story is Spark's akka control plane + netty
data plane, latent behind ``master=local[*]`` (SparkInitializer.java:
40-49; SURVEY.md section 2.3 — every shipped config is one process).
The TPU-native equivalent is first-class here:

- :func:`initialize` — ``jax.distributed.initialize`` bootstrap (the
  control plane: coordinator discovery, process ids), idempotent and
  a no-op for single-process runs, so the same program runs unchanged
  from a laptop to a multi-host pod slice;
- :func:`hybrid_mesh` — a mesh whose outer axis spans hosts/slices
  over DCN and whose inner axes span chips over ICI, so gradient
  all-reduces ride ICI within a slice and only the slice-level
  reduction crosses DCN (the bandwidth hierarchy the scaling-book
  recipe prescribes, replacing Spark's flat driver<->executor TCP);
- :func:`stage_global_batch` — each process materializes only its own
  shard of a logically global batch
  (``jax.make_array_from_process_local_data``), the multi-host form of
  ``mesh.shard_batch``'s host->device staging (and of the reference's
  ``sc.parallelize`` driver->executor scatter);
- :func:`replicate_across_hosts` — host-local array -> globally
  replicated device array (broadcast of model parameters).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as pmesh

logger = logging.getLogger(__name__)

DCN_AXIS = "hosts"

_initialized = False


def _env_int(name: str) -> Optional[int]:
    value = os.environ.get(name)
    return int(value) if value is not None else None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bootstrap the multi-process JAX runtime (idempotent).

    Single-process runs (no coordinator configured anywhere) are a
    no-op. Arguments default to the ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` env vars, falling back
    to the cluster auto-detection built into
    ``jax.distributed.initialize`` (SLURM/OMPI/TPU metadata).

    Must run before anything touches a JAX backend — this function
    deliberately makes no backend-initializing JAX calls on the way to
    the bootstrap.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None:
        num_processes = _env_int("JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = _env_int("JAX_PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        if process_id is not None:
            raise ValueError(
                "JAX_PROCESS_ID/process_id is set but neither a "
                "coordinator address nor a process count is configured "
                "— refusing to run as single-process with a partial "
                "multi-host setup"
            )
        return  # single process; nothing to bootstrap
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized = True
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def hybrid_mesh(
    ici_axes: Tuple[str, ...] = (pmesh.DATA_AXIS,),
    ici_shape: Optional[Sequence[int]] = None,
    dcn_axis: str = DCN_AXIS,
) -> Mesh:
    """Mesh with ``dcn_axis`` (outermost) spanning processes over DCN
    and ``ici_axes`` spanning each process's local chips over ICI.

    Single-process: the DCN axis has size 1 and the result degenerates
    to a plain local mesh — callers write one sharding
    (``P((DCN_AXIS, DATA_AXIS))`` for batch axes) for both worlds.
    Collectives over a batch sharded this way reduce over ICI first
    and cross DCN once per slice, never per chip.
    """
    n_local = jax.local_device_count()
    n_proc = jax.process_count()
    if ici_shape is None:
        if len(ici_axes) != 1:
            raise ValueError("ici_shape required for multi-axis ICI layouts")
        ici_shape = (n_local,)
    if int(np.prod(ici_shape)) != n_local:
        raise ValueError(
            f"ici_shape {tuple(ici_shape)} must cover the {n_local} "
            "local devices"
        )
    if n_proc > 1:
        from jax.experimental import mesh_utils

        # rank = 1 + len(ici_shape); per-granule ICI extent is 1 on the
        # DCN axis and n_proc is 1 on every ICI axis, so the result has
        # shape (n_proc, *ici_shape). The shapes above are derived from
        # process_count/local_device_count, so the granule is the
        # process (also the only option for devices without a
        # slice_index attribute, e.g. GPU/CPU clusters).
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) + tuple(ici_shape),
            dcn_mesh_shape=(n_proc,) + tuple(1 for _ in ici_shape),
            process_is_granule=True,
        )
        return Mesh(devices, (dcn_axis,) + tuple(ici_axes))
    devices = np.array(jax.devices()).reshape((1,) + tuple(ici_shape))
    return Mesh(devices, (dcn_axis,) + tuple(ici_axes))


def batch_spec(mesh: Mesh, dcn_axis: str = DCN_AXIS) -> P:
    """PartitionSpec sharding the leading batch axis over every
    data-parallel mesh axis present (DCN outer, ICI inner). Pass the
    same ``dcn_axis`` given to :func:`hybrid_mesh` if overridden."""
    axes = tuple(
        a for a in (dcn_axis, pmesh.DATA_AXIS) if a in mesh.axis_names
    )
    if not axes:
        raise ValueError(
            f"mesh {mesh.axis_names} has no data-parallel axis"
        )
    return P(axes if len(axes) > 1 else axes[0])


def stage_local(sharding: NamedSharding, local: np.ndarray) -> jax.Array:
    """Per-process host data -> one global array under ``sharding``.

    The single dispatch point for multi-host staging: single-process
    runs are a plain ``device_put`` (no intermediate default-device
    commit), multi-process runs assemble the global array from each
    process's addressable shards.
    """
    local = np.asarray(local)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def stage_global_batch(
    local_batch: np.ndarray, mesh: Mesh, dcn_axis: str = DCN_AXIS
) -> jax.Array:
    """Per-process host shard -> one global device array.

    ``local_batch`` is this process's slice of the global batch (the
    data loader reads only its own files); the returned array's global
    leading dimension is ``sum over processes`` and is sharded by
    :func:`batch_spec`. Single-process this is exactly
    ``device_put`` + batch sharding.
    """
    return stage_local(
        NamedSharding(mesh, batch_spec(mesh, dcn_axis)), local_batch
    )


def replicate_across_hosts(tree, mesh: Mesh):
    """Host-local pytree -> globally replicated device arrays (the
    parameter broadcast; every process must pass identical values)."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)
    from jax.experimental import multihost_utils

    return jax.tree_util.tree_map(
        lambda x: multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, P()
        ),
        tree,
    )
