"""Multi-host runtime: process bootstrap, hybrid DCN x ICI meshes,
per-process global-batch staging.

The reference's cluster story is Spark's akka control plane + netty
data plane, latent behind ``master=local[*]`` (SparkInitializer.java:
40-49; SURVEY.md section 2.3 — every shipped config is one process).
The TPU-native equivalent is first-class here:

- :func:`initialize` — ``jax.distributed.initialize`` bootstrap (the
  control plane: coordinator discovery, process ids), idempotent and
  a no-op for single-process runs, so the same program runs unchanged
  from a laptop to a multi-host pod slice;
- :func:`hybrid_mesh` — a mesh whose outer axis spans hosts/slices
  over DCN and whose inner axes span chips over ICI, so gradient
  all-reduces ride ICI within a slice and only the slice-level
  reduction crosses DCN (the bandwidth hierarchy the scaling-book
  recipe prescribes, replacing Spark's flat driver<->executor TCP);
- :func:`stage_global_batch` — each process materializes only its own
  shard of a logically global batch
  (``jax.make_array_from_process_local_data``), the multi-host form of
  ``mesh.shard_batch``'s host->device staging (and of the reference's
  ``sc.parallelize`` driver->executor scatter);
- :func:`replicate_across_hosts` — host-local array -> globally
  replicated device array (broadcast of model parameters).
"""

from __future__ import annotations

import logging
import os
import socket
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as pmesh

logger = logging.getLogger(__name__)

DCN_AXIS = "hosts"

#: bootstrap deadline (seconds) for the preflight rendezvous; the
#: query/env resolution in pipeline/builder passes it through. XLA's
#: own ``initialization_timeout`` is NOT a substitute — past it the
#: coordination client calls LOG(FATAL) and aborts the process, which
#: is exactly what the degradation ladder must never let happen.
ENV_BOOTSTRAP_TIMEOUT = "EEG_TPU_POD_TIMEOUT_S"
_DEFAULT_BOOTSTRAP_TIMEOUT_S = 60.0

#: set to "1" to skip the preflight rendezvous (real pods whose
#: launcher already guarantees the cluster, or whose coordinator
#: port + 1 is not usable)
ENV_NO_PREFLIGHT = "EEG_TPU_POD_NO_PREFLIGHT"

_initialized = False
#: the (coordinator, num_processes, process_id) actually used by the
#: live bootstrap — what :func:`initialize` returns on repeat calls,
#: so the run report records what ran, not what was asked for
_resolution: Optional[Tuple[Optional[str], int, int]] = None


class PodBootstrapError(ConnectionError):
    """The multi-process bootstrap could not assemble the pod within
    its deadline (coordinator unreachable, a peer host missing).
    Raised BEFORE ``jax.distributed.initialize`` ever runs — past that
    point a bootstrap failure is a fatal abort inside XLA's
    coordination client, not an exception — so the pipeline's
    degradation ladder can catch it and drop pod -> single host."""


def default_bootstrap_timeout() -> float:
    value = os.environ.get(ENV_BOOTSTRAP_TIMEOUT)
    if not value:
        return _DEFAULT_BOOTSTRAP_TIMEOUT_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.0fs",
            ENV_BOOTSTRAP_TIMEOUT, value, _DEFAULT_BOOTSTRAP_TIMEOUT_S,
        )
        return _DEFAULT_BOOTSTRAP_TIMEOUT_S


def _env_int(name: str) -> Optional[int]:
    value = os.environ.get(name)
    return int(value) if value is not None else None


def resolve_env_knobs(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Tuple[Optional[str], Optional[int], Optional[int]]:
    """Fill None knobs from the env twins ``JAX_COORDINATOR_ADDRESS``
    (or ``JAX_COORDINATOR``) / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` — the ONE query-over-env resolution shared by
    :func:`initialize` and the pipeline's ``_resolve_pod``, so what
    the builder records as requested can never diverge from what the
    bootstrap resolves."""
    if coordinator_address is None:
        coordinator_address = (
            os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("JAX_COORDINATOR")
            or None
        )
    if num_processes is None:
        num_processes = _env_int("JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = _env_int("JAX_PROCESS_ID")
    return coordinator_address, num_processes, process_id


def _split_host_port(coordinator_address: str) -> Tuple[str, int]:
    host, sep, port = coordinator_address.rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"coordinator address {coordinator_address!r} is not "
            f"host:port"
        )
    return host, int(port)


def _preflight_rendezvous(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    timeout_s: float,
) -> None:
    """Plain-TCP barrier on ``coordinator port + 1`` before the real
    bootstrap.

    ``jax.distributed.initialize`` past its timeout does not raise —
    XLA's coordination client LOG(FATAL)s the process — so the
    degradable failure modes (coordinator host down, a peer host that
    never arrives) must be detected *before* it runs. Process 0
    listens; every other process connects, sends its id, and blocks on
    the ack process 0 sends only once all peers have arrived. Success
    means every process is alive and about to enter the real bootstrap
    together; failure raises :class:`PodBootstrapError` within
    ``timeout_s`` on every process, so the whole pod degrades to
    single-host rather than half of it aborting.
    """
    host, port = _split_host_port(coordinator_address)
    deadline = time.monotonic() + timeout_s
    rendezvous_port = port + 1
    if process_id == 0:
        try:
            server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            server.bind(("", rendezvous_port))
            server.listen(num_processes)
        except OSError as e:
            raise PodBootstrapError(
                f"preflight rendezvous could not listen on port "
                f"{rendezvous_port}: {e}"
            )
        peers: dict = {}  # peer process id -> live connection
        stray = []
        try:
            while len(peers) < num_processes - 1:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PodBootstrapError(
                        f"preflight rendezvous timed out after "
                        f"{timeout_s:.0f}s with {len(peers)}/"
                        f"{num_processes - 1} peer processes arrived"
                    )
                server.settimeout(min(remaining, 1.0))
                try:
                    conn, _addr = server.accept()
                except socket.timeout:
                    continue
                conn.settimeout(max(deadline - time.monotonic(), 0.1))
                # an arrived PEER sends its decimal process id; a port
                # scanner / health probe connecting and closing sends
                # nothing (recv -> b"", not an OSError) and must not
                # count toward the barrier. Duplicate ids (a peer's
                # retry after a dropped ack wait) replace the stale
                # connection rather than double-counting.
                try:
                    data = conn.recv(16)
                except OSError:
                    conn.close()
                    continue
                text = data.decode("ascii", errors="replace").strip()
                if not text.isdigit() or not (
                    1 <= int(text) <= num_processes - 1
                ):
                    conn.close()
                    continue
                pid = int(text)
                if pid in peers:
                    stray.append(peers.pop(pid))
                peers[pid] = conn
            for conn in peers.values():
                try:
                    conn.sendall(b"ok")
                except OSError:
                    pass
        finally:
            for conn in list(peers.values()) + stray:
                conn.close()
            server.close()
        return
    # non-coordinator processes: connect-with-retry until the deadline
    last_error: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(
                (host, rendezvous_port),
                timeout=max(min(deadline - time.monotonic(), 2.0), 0.1),
            ) as conn:
                conn.sendall(str(process_id).encode())
                conn.settimeout(max(deadline - time.monotonic(), 0.1))
                # read until the 2-byte ack or EOF — a TCP short read
                # is not a failed rendezvous
                ack = b""
                while len(ack) < 2:
                    chunk = conn.recv(2 - len(ack))
                    if not chunk:
                        break
                    ack += chunk
                if ack == b"ok":
                    return
                last_error = ConnectionError("rendezvous closed early")
        except OSError as e:
            last_error = e
            time.sleep(min(0.2, max(deadline - time.monotonic(), 0.0)))
    raise PodBootstrapError(
        f"coordinator {coordinator_address} unreachable within "
        f"{timeout_s:.0f}s (preflight): {last_error}"
    )


def free_port_pair(attempts: int = 16) -> int:
    """A loopback port whose NEIGHBOR is also bindable — the preflight
    rendezvous listens on coordinator port + 1, so a coordinator
    address is only usable when both are free. (Still a close-then-use
    window, but probing the pair removes the common collision: an
    ephemeral port whose neighbor is a listening service.) The fleet's
    pod-assist coordinator picks its ``coordinator=`` address here."""
    for _ in range(attempts):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        try:
            s2 = socket.socket()
            try:
                s2.bind(("", port + 1))
            except OSError:
                continue
            s2.close()
            return port
        finally:
            s.close()
    raise RuntimeError("no free coordinator port pair found")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: Optional[float] = None,
) -> Tuple[Optional[str], int, int]:
    """Bootstrap the multi-process JAX runtime (idempotent).

    Single-process runs (no coordinator configured anywhere) are a
    no-op. Arguments default to the ``JAX_COORDINATOR_ADDRESS`` (or
    its ``JAX_COORDINATOR`` twin) / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` env vars, falling back to the cluster
    auto-detection built into ``jax.distributed.initialize``
    (SLURM/OMPI/TPU metadata).

    Returns the RESOLVED ``(coordinator, num_processes, process_id)``
    — what the bootstrap actually used, which is what the run report
    records (``(None, 1, 0)`` for the single-process no-op; repeat
    calls return the live bootstrap's resolution unchanged).

    Failure modes that must degrade rather than kill — coordinator
    unreachable, a peer host missing at bootstrap — raise
    :class:`PodBootstrapError` within ``timeout_s`` (default
    ``EEG_TPU_POD_TIMEOUT_S``, 60s) from the plain-TCP preflight
    rendezvous that runs before ``jax.distributed.initialize`` (which
    on timeout aborts the process instead of raising).

    Must run before anything touches a JAX backend — this function
    deliberately makes no backend-initializing JAX calls on the way to
    the bootstrap.
    """
    global _initialized, _resolution
    if _initialized:
        assert _resolution is not None
        return _resolution
    coordinator_address, num_processes, process_id = resolve_env_knobs(
        coordinator_address, num_processes, process_id
    )
    if coordinator_address is None and num_processes is None:
        if process_id is not None:
            raise ValueError(
                "JAX_PROCESS_ID/process_id is set but neither a "
                "coordinator address nor a process count is configured "
                "— refusing to run as single-process with a partial "
                "multi-host setup"
            )
        _resolution = (None, 1, 0)
        return _resolution  # single process; nothing to bootstrap
    if timeout_s is None:
        timeout_s = default_bootstrap_timeout()
    if (
        coordinator_address is not None
        and num_processes is not None
        and num_processes > 1
        and os.environ.get(ENV_NO_PREFLIGHT) != "1"
    ):
        if process_id is None:
            # without a rank the preflight cannot run, and past it
            # jax's bootstrap failure mode is a process abort — raise
            # the catchable error here so the ladder degrades (real
            # cluster launchers that auto-detect ranks don't pass an
            # explicit coordinator+count pair, or set
            # EEG_TPU_POD_NO_PREFLIGHT=1)
            raise PodBootstrapError(
                "process_id unresolved for an explicit "
                f"coordinator={coordinator_address} num_processes="
                f"{num_processes} bootstrap; set process_id=/"
                "JAX_PROCESS_ID (or EEG_TPU_POD_NO_PREFLIGHT=1 for a "
                "launcher-managed cluster)"
            )
        _preflight_rendezvous(
            coordinator_address, num_processes, process_id, timeout_s
        )
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    # CPU pods (the CI/loopback harness, CPU clusters) need the gloo
    # collectives implementation, and the flag must be set BEFORE the
    # backend initializes — but only once a distributed client will
    # actually exist: with the flag set and no client, CPU backend
    # creation itself fails, which is why this lives after the
    # preflight (a degraded bootstrap leaves the config untouched and
    # the single-host run initializes normally).
    collectives_set = False
    prev_collectives = None
    if (num_processes or 0) > 1 or num_processes is None:
        try:
            prev_collectives = jax.config.read(
                "jax_cpu_collectives_implementation"
            )
            if prev_collectives in (None, "none"):
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
                collectives_set = True
        except Exception:  # pragma: no cover - config surface drift
            pass
    try:
        try:
            jax.distributed.initialize(
                initialization_timeout=max(int(timeout_s), 1), **kwargs
            )
        except TypeError:  # pragma: no cover - older jax without kwarg
            jax.distributed.initialize(**kwargs)
    except Exception:
        if collectives_set:
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation",
                    prev_collectives,
                )
            except Exception:  # pragma: no cover
                pass
        raise
    _initialized = True
    _resolution = (
        coordinator_address,
        int(jax.process_count()),
        int(jax.process_index()),
    )
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return _resolution


def shutdown() -> None:
    """Tear down the multi-process runtime and reset the bootstrap
    latch, so :func:`initialize` can run again in this process.

    The latch used to be one-way: a test harness (or a resident
    gateway restarted in-process) that shut the cluster down could
    never re-bootstrap, because ``_initialized`` stayed True forever.
    Safe to call when nothing was ever initialized (no-op)."""
    global _initialized, _resolution
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # pragma: no cover - defensive teardown
            logger.warning("jax.distributed.shutdown failed: %s", e)
    _initialized = False
    _resolution = None


def is_initialized() -> bool:
    """True while a multi-process bootstrap from :func:`initialize`
    is live (the latch :func:`shutdown` resets)."""
    return _initialized


def hybrid_mesh(
    ici_axes: Tuple[str, ...] = (pmesh.DATA_AXIS,),
    ici_shape: Optional[Sequence[int]] = None,
    dcn_axis: str = DCN_AXIS,
) -> Mesh:
    """Mesh with ``dcn_axis`` (outermost) spanning processes over DCN
    and ``ici_axes`` spanning each process's local chips over ICI.

    Single-process: the DCN axis has size 1 and the result degenerates
    to a plain local mesh — callers write one sharding
    (``P((DCN_AXIS, DATA_AXIS))`` for batch axes) for both worlds.
    Collectives over a batch sharded this way reduce over ICI first
    and cross DCN once per slice, never per chip.
    """
    n_local = jax.local_device_count()
    n_proc = jax.process_count()
    if ici_shape is None:
        if len(ici_axes) != 1:
            raise ValueError("ici_shape required for multi-axis ICI layouts")
        ici_shape = (n_local,)
    if int(np.prod(ici_shape)) != n_local:
        raise ValueError(
            f"ici_shape {tuple(ici_shape)} must cover the {n_local} "
            "local devices"
        )
    if n_proc > 1:
        from jax.experimental import mesh_utils

        # rank = 1 + len(ici_shape); per-granule ICI extent is 1 on the
        # DCN axis and n_proc is 1 on every ICI axis, so the result has
        # shape (n_proc, *ici_shape). The shapes above are derived from
        # process_count/local_device_count, so the granule is the
        # process (also the only option for devices without a
        # slice_index attribute, e.g. GPU/CPU clusters).
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) + tuple(ici_shape),
            dcn_mesh_shape=(n_proc,) + tuple(1 for _ in ici_shape),
            process_is_granule=True,
        )
        return Mesh(devices, (dcn_axis,) + tuple(ici_axes))
    devices = np.array(jax.devices()).reshape((1,) + tuple(ici_shape))
    return Mesh(devices, (dcn_axis,) + tuple(ici_axes))


def batch_spec(mesh: Mesh, dcn_axis: str = DCN_AXIS) -> P:
    """PartitionSpec sharding the leading batch axis over every
    data-parallel mesh axis present (DCN outer, ICI inner). Pass the
    same ``dcn_axis`` given to :func:`hybrid_mesh` if overridden."""
    axes = tuple(
        a for a in (dcn_axis, pmesh.DATA_AXIS) if a in mesh.axis_names
    )
    if not axes:
        raise ValueError(
            f"mesh {mesh.axis_names} has no data-parallel axis"
        )
    return P(axes if len(axes) > 1 else axes[0])


def stage_local(sharding: NamedSharding, local: np.ndarray) -> jax.Array:
    """Per-process host data -> one global array under ``sharding``.

    The single dispatch point for multi-host staging: fully
    addressable shardings — every single-process run, and host-LOCAL
    meshes on a pod (each host's ICI submesh doing per-host work) —
    are a plain ``device_put`` (no intermediate default-device
    commit); shardings spanning other processes' devices assemble the
    global array from each process's addressable shards.
    """
    local = np.asarray(local)
    if jax.process_count() == 1 or sharding.is_fully_addressable:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def stage_global_batch(
    local_batch: np.ndarray, mesh: Mesh, dcn_axis: str = DCN_AXIS
) -> jax.Array:
    """Per-process host shard -> one global device array.

    ``local_batch`` is this process's slice of the global batch (the
    data loader reads only its own files); the returned array's global
    leading dimension is ``sum over processes`` and is sharded by
    :func:`batch_spec`. Single-process this is exactly
    ``device_put`` + batch sharding.
    """
    return stage_local(
        NamedSharding(mesh, batch_spec(mesh, dcn_axis)), local_batch
    )


def replicate_across_hosts(tree, mesh: Mesh):
    """Host-local pytree -> globally replicated device arrays (the
    parameter broadcast; every process must pass identical values)."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)
    from jax.experimental import multihost_utils

    return jax.tree_util.tree_map(
        lambda x: multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, P()
        ),
        tree,
    )
