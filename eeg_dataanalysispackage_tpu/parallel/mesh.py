"""Device mesh construction + batch sharding helpers.

The TPU-native replacement for the reference's Spark runtime layer
(``Utils/SparkInitializer.java`` — lazy singleton SparkContext over
``local[*]`` threads, akka control plane + netty data plane per
SURVEY.md section 2.3): parallel resources are a
``jax.sharding.Mesh``; data parallelism is a ``NamedSharding`` over
the batch axis; collectives ride ICI within a slice and DCN across
hosts, inserted by XLA from sharding annotations rather than by
explicit RPC.

Axes:
- ``data``  — epoch-batch data parallelism (the reference's only
  strategy: RDD partitions of epochs);
- ``time``  — sequence/context parallelism for continuous-EEG
  streaming (see ``parallel/streaming.py``), net-new vs the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
TIME_AXIS = "time"


def make_mesh(
    n_devices: Optional[int] = None,
    axes: Tuple[str, ...] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh over the first ``n_devices`` available devices.

    1-D data mesh by default; pass ``axes``/``shape`` for 2-D layouts
    (e.g. ``axes=('data','time'), shape=(2,4)``).

    ``devices`` — an explicit device subset (e.g. the ordinals a
    fleet device lease granted) instead of the ``[:n]`` prefix slice;
    ``n_devices`` must match its length when both are given.
    """
    if devices is None:
        devices = jax.devices()
        n = n_devices or len(devices)
        if n > len(devices):
            raise ValueError(
                f"requested {n} devices, only {len(devices)} present"
            )
    else:
        devices = list(devices)
        n = n_devices or len(devices)
        if n != len(devices):
            raise ValueError(
                f"requested {n} devices but an explicit subset of "
                f"{len(devices)} was given; they must agree"
            )
    devs = np.array(devices[:n])
    if shape is None:
        shape = (n,) if len(axes) == 1 else None
    if shape is None:
        raise ValueError("shape required for multi-axis meshes")
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axes):
        raise ValueError(
            f"mesh shape {shape} names {len(shape)} axes but "
            f"axes={axes} names {len(axes)}; give one extent per axis"
        )
    product = int(np.prod(shape)) if shape else 1
    if product != n:
        # a bare numpy reshape ValueError here read as an internal
        # bug; the real error is the caller's axis arithmetic
        raise ValueError(
            f"mesh shape {shape} covers {product} devices but "
            f"{n} device(s) were requested; the axis extents must "
            f"multiply to the device count"
        )
    return Mesh(devs.reshape(shape), axes)


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(array: np.ndarray, multiple: int, axis: int = 0):
    """Pad ``axis`` up to a multiple (XLA needs evenly divisible shards).

    Returns (padded, original_length). Padding rows are zeros; callers
    mask them out of reductions via the returned length.
    """
    n = array.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return array, n
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, rem)
    return np.pad(array, widths), n


def shard_batch(array: np.ndarray, mesh: Mesh, axis_name: str = DATA_AXIS):
    """Pad + device_put a host batch across the mesh's data axis.

    The host->device staging boundary (replaces the reference's
    ``sc.parallelize`` driver->executor serialization,
    LogisticRegressionClassifier.java:87-88).
    """
    padded, n = pad_to_multiple(np.asarray(array), mesh.shape[axis_name])
    return jax.device_put(padded, batch_sharding(mesh, axis_name)), n


def shard_batch_with_mask(mesh: Mesh, *arrays, axis_name: str = DATA_AXIS):
    """Pad + shard float32 batch arrays, plus a 1/0 validity mask over
    the padded rows. Single source of truth for the padding/masking
    convention used by distributed SGD and the flagship train step."""
    out = []
    n = None
    padded_len = None
    for a in arrays:
        sharded, n = shard_batch(np.asarray(a, np.float32), mesh, axis_name)
        padded_len = sharded.shape[0]
        out.append(sharded)
    mask_np = np.zeros(padded_len, dtype=np.float32)
    mask_np[:n] = 1.0
    out.append(jax.device_put(mask_np, batch_sharding(mesh, axis_name)))
    return tuple(out)
