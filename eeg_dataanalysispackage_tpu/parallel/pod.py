"""Pod-scale partitioned ingest: per-host recording subsets feeding a
global feature matrix over DCN.

ROADMAP item 2's missing half. ``parallel/distributed.py`` has carried
the multi-host runtime (bootstrap, hybrid DCN x ICI meshes, per-process
staging) since the seed, called by nothing in the pipeline; this module
is the bridge that puts it under ``pipeline/builder``'s fused ingest
and the population engine:

- each process ingests a **disjoint recording subset** — a
  deterministic contiguous partition by recording index
  (:func:`partition`), so the expensive work (waveform bytes off disk,
  staging, the fused DWT programs) scales ~1/N per host;
- semantics stay GLOBAL: the reference's cross-recording state (the
  order-dependent balance scan, the stale-channel-index reuse) is a
  function of marker/header metadata only, so every process runs the
  same metadata pass over every recording (:func:`plan_pod_ingest` —
  .vhdr/.vmrk text plus the .eeg byte count; the multi-MB waveforms
  are read only by their owner) and the per-recording ingest plans are
  byte-identical to the single-process run's;
- each feature row is computed by exactly one host with the exact
  per-recording program the single-process rung runs (the plans above
  make staging and window cuts independent across recordings), so the
  assembled global matrix is bit-identical to the unpartitioned run;
- assembly is ONE collective: per-host row blocks are padded to a
  common shard, staged with each process contributing only its local
  shard (``distributed.stage_local`` — the
  ``make_array_from_process_local_data`` path), and replicated by an
  all-gather whose outermost hop crosses DCN
  (:func:`exchange_features`; the compiled HLO is inspectable via
  :func:`exchange_collective_hlo`, the PR 9 assert-the-collective
  pattern).

Downstream, the hybrid mesh's member axis spans every device of every
host, so ``train_linear_population_sharded`` trains ~P/(hosts*chips)
members per device and its final weight all-gather crosses DCN — the
scaling-book shape: heavy traffic rides ICI inside a host, one small
collective per phase crosses DCN.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def spawn_pod_member(
    query: str,
    coordinator: str,
    num_processes: int,
    process_id: int,
    parent_pid: Optional[int] = None,
    timeout_s: Optional[str] = None,
):
    """One fresh ``parallel.pod_worker`` subprocess for rank
    ``process_id`` of a ``num_processes`` pod at ``coordinator`` —
    the fleet's pod-assist spawn point (both the coordinator's own
    process 0 and every enlisted peer's worker ranks go through
    here, so their environments cannot diverge).

    The pod knobs ride the QUERY, not env twins — the spawner's own
    ``JAX_*`` pod env (if any) is popped so the child's membership is
    exactly what the query says. ``parent_pid`` defaults to the
    calling process: the child self-exits when its spawner dies,
    which bounds a SIGKILLed coordinator to a degraded pod instead
    of orphaned ranks. Returns the ``subprocess.Popen`` (stdout
    piped; the last line is the worker's JSON result).
    """
    import os
    import subprocess
    import sys as _sys

    base = query
    if "process_id=" in base:
        raise ValueError(
            "query already carries process_id; pod-assist must not "
            "re-route an explicitly placed member"
        )
    member_query = base
    if "coordinator=" not in base:
        member_query += f"&coordinator={coordinator}"
    if "processes=" not in base:
        member_query += f"&processes={num_processes}"
    member_query += f"&process_id={process_id}"
    env = dict(os.environ)
    for var in (
        "JAX_NUM_PROCESSES", "JAX_COORDINATOR",
        "JAX_COORDINATOR_ADDRESS", "JAX_PROCESS_ID",
    ):
        env.pop(var, None)
    if timeout_s is not None:
        # distributed.ENV_BOOTSTRAP_TIMEOUT, spelled out: importing
        # parallel.distributed pulls jax into the spawner, and this
        # helper must stay importable from jax-free tooling
        env["EEG_TPU_POD_TIMEOUT_S"] = str(timeout_s)
    if parent_pid is None:
        parent_pid = os.getpid()
    return subprocess.Popen(
        [
            _sys.executable, "-m",
            "eeg_dataanalysispackage_tpu.parallel.pod_worker",
            f"--query={member_query}",
            f"--parent-pid={parent_pid}",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def partition(n_items: int, num_processes: int) -> List[Tuple[int, int]]:
    """Deterministic contiguous partition of ``range(n_items)`` into
    ``num_processes`` blocks: ``[start, stop)`` per process.

    ``np.array_split`` semantics — the first ``n_items %
    num_processes`` blocks get one extra item — chosen over
    round-robin because each process's global feature rows are then
    one contiguous slice (what the one-collective exchange shards
    on). Properties the tests pin: disjoint, exhaustive, order-stable
    (concatenating the blocks reproduces the input order), and
    well-defined when ``num_processes > n_items`` (trailing processes
    own empty ranges and simply contribute zero rows).
    """
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    base, extra = divmod(int(n_items), int(num_processes))
    bounds = [0]
    for p in range(num_processes):
        bounds.append(bounds[-1] + base + (1 if p < extra else 0))
    return [(bounds[p], bounds[p + 1]) for p in range(num_processes)]


@dataclasses.dataclass(frozen=True)
class PodRuntime:
    """One live multi-process bootstrap, as the pipeline threads it:
    the hybrid DCN x ICI mesh plus the resolved process coordinates
    (``distributed.initialize``'s return — what actually ran, not
    what was requested)."""

    mesh: object  # jax.sharding.Mesh (hosts x local-device axes)
    num_processes: int
    process_id: int
    coordinator: Optional[str] = None


@dataclasses.dataclass
class PodRecording:
    """One recording's metadata-pass products: everything the owner
    needs to featurize it (and everything every OTHER process needs
    to stay in global lockstep) without anyone else reading the
    waveform."""

    rel_path: str
    guessed: int
    eeg_path: str
    header: object  # brainvision.Header
    markers: list
    n_samples: int
    channel_indices: List[int]
    plan: object  # ops.device_ingest.IngestPlan


@dataclasses.dataclass
class PodIngestPlan:
    """The global metadata pass: per-recording plans in load order,
    plus the run-level products every process derives identically —
    the global targets and each recording's kept-row count (the
    exchange geometry)."""

    entries: List[PodRecording]
    targets: np.ndarray  # (n,) float64, global row order

    def row_counts(self) -> List[int]:
        return [int(e.plan.n_kept) for e in self.entries]

    def host_row_counts(self, num_processes: int) -> List[int]:
        """Kept feature rows per process under :func:`partition` —
        known to every process (the metadata pass is global), which is
        what lets the exchange use one static shard size."""
        counts = self.row_counts()
        return [
            int(sum(counts[lo:hi]))
            for lo, hi in partition(len(counts), num_processes)
        ]


def file_size(fs, path: str) -> int:
    """Byte length of ``path`` without materializing it when the
    filesystem can stat (``size()`` — local/in-memory); falls back to
    reading the bytes for filesystems that cannot."""
    sizer = getattr(fs, "size", None)
    if sizer is not None:
        return int(sizer(path))
    return len(fs.read_bytes(path))


def plan_pod_ingest(provider) -> PodIngestPlan:
    """The global metadata pass, run identically on every process.

    Reads every recording's .vhdr/.vmrk text (tiny) and the .eeg BYTE
    COUNT (a stat, not a read), then advances the run's global state
    in load order exactly as ``load_features_device`` does: channel
    indices with the reference's stale-index reuse, window validity
    against the true sample count, the cross-recording balance scan.
    The resulting per-recording ``IngestPlan``s are byte-identical to
    the single-process run's — which is the whole parity argument:
    given the plan, featurizing a recording touches no cross-recording
    state, so the owner's rows are the single-process run's rows.

    Missing-sibling files are skipped with the same log line as
    ``load()``, so the partition fingerprints the run that would
    actually happen.
    """
    import os as _os

    from .. import obs
    from ..io import brainvision
    from ..ops import device_ingest
    from ..epochs.extractor import BalanceState

    prefix, files = provider._resolve_files()
    fs = provider._fs
    balance = BalanceState()
    entries: List[PodRecording] = []
    for rel_path, guessed in files.items():
        eeg_path = prefix + rel_path
        base = _os.path.splitext(eeg_path)[0]
        triplet = (base + ".vhdr", base + ".vmrk", eeg_path)
        missing = next((p for p in triplet if not fs.exists(p)), None)
        if missing is not None:
            logger.warning(
                "Did not load %s: No related file found: %s",
                rel_path, missing,
            )
            continue
        header = brainvision.parse_vhdr(fs.read_text(triplet[0]))
        markers = brainvision.parse_vmrk(fs.read_text(triplet[1]))
        obs.metrics.count("ingest.file_reads", 2)
        dtype = brainvision._BINARY_DTYPES.get(header.binary_format)
        if dtype is None:
            # the single-host parse raises this exact ValueError from
            # _recording_from_blob; the metadata pass keeps the
            # contract instead of a bare KeyError
            raise ValueError(
                f"Unsupported BinaryFormat: {header.binary_format}"
            )
        itemsize = dtype.itemsize
        n_samples = (
            file_size(fs, eeg_path) // itemsize
        ) // max(1, header.num_channels)
        indices = provider._channel_indices_for_header(header)
        plan = device_ingest.plan_ingest(
            markers, guessed, n_samples,
            pre=provider.pre, post=provider.post, balance=balance,
        )
        entries.append(
            PodRecording(
                rel_path=rel_path,
                guessed=guessed,
                eeg_path=eeg_path,
                header=header,
                markers=markers,
                n_samples=n_samples,
                channel_indices=indices,
                plan=plan,
            )
        )
    targets = (
        np.concatenate([e.plan.targets for e in entries])
        if entries
        else np.zeros((0,), dtype=np.float64)
    )
    return PodIngestPlan(entries=entries, targets=targets)


def local_features(
    provider,
    plan: PodIngestPlan,
    num_processes: int,
    process_id: int,
    featurize_entry: Callable[[PodRecording], np.ndarray],
    n_feat: int,
) -> np.ndarray:
    """This process's feature rows: read + featurize the OWNED
    contiguous recording block only, in load order. ``featurize_entry``
    is the provider's rung closure (``planned_featurizer``) — the
    per-recording program the single-process run dispatches, driven by
    the globally planned positions/mask instead of a re-plan."""
    lo, hi = partition(len(plan.entries), num_processes)[process_id]
    rows: List[np.ndarray] = []
    for entry in plan.entries[lo:hi]:
        rows.append(np.asarray(featurize_entry(entry), dtype=np.float32))
    if not rows:
        return np.zeros((0, n_feat), dtype=np.float32)
    return np.concatenate(rows)


@functools.lru_cache(maxsize=None)
def _replicate_program(mesh):
    """jitted identity -> fully replicated: the one collective of the
    feature exchange (an all-gather whose outer hop crosses DCN on
    real pods). lru-cached per mesh so repeat runs re-jit nothing."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))


def _exchange_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import distributed

    return NamedSharding(mesh, P(distributed.DCN_AXIS))


def exchange_features(
    mesh,
    local_rows: np.ndarray,
    host_counts: Sequence[int],
    n_feat: int,
    process_id: int,
) -> np.ndarray:
    """Assemble the global feature matrix from per-host row blocks.

    Each process pads its block to the common per-host shard (the max
    host row count — every process derives the same number from the
    global metadata pass), stages ONLY its own shard
    (``distributed.stage_local`` over the mesh's DCN axis), and the
    replicate program all-gathers the stack to every host; the padding
    rows are sliced off per host and the blocks concatenated in
    process order — which, with the contiguous partition, IS global
    row order. Returns the full (n, n_feat) float32 matrix, identical
    on every process, bit-identical to the unpartitioned run's.
    """
    import jax

    from . import distributed

    host_counts = [int(c) for c in host_counts]
    n_local = int(local_rows.shape[0])
    if n_local != host_counts[process_id]:
        raise ValueError(
            f"process {process_id} produced {n_local} rows but the "
            f"global plan expected {host_counts[process_id]}; the "
            f"metadata pass and the featurize pass disagree"
        )
    maxn = max(host_counts) if host_counts else 0
    if maxn == 0:
        return np.zeros((0, n_feat), dtype=np.float32)
    padded = np.zeros((maxn, n_feat), dtype=np.float32)
    padded[:n_local] = np.asarray(local_rows, dtype=np.float32)
    staged = distributed.stage_local(_exchange_sharding(mesh), padded)
    replicated = _replicate_program(mesh)(staged)
    full = np.asarray(replicated)
    parts = [
        full[h * maxn : h * maxn + host_counts[h]]
        for h in range(len(host_counts))
    ]
    from .. import obs

    # this process's wire bytes: its own padded shard out to each of
    # the N-1 peers (and symmetrically in) — maxn x n_feat x 4 per
    # hop, NOT the global stacked array
    obs.metrics.count(
        "pod.exchange_bytes", int(padded.nbytes) * (len(host_counts) - 1)
    )
    return np.concatenate(parts)


def exchange_collective_hlo(mesh, maxn: int, n_feat: int) -> str:
    """Compiled HLO of the exchange's replicate program for a given
    geometry — the inspectable seam tests assert the cross-process
    all-gather on (the PR 9 pattern: prove the collective exists in
    the compiled program, not just in intent)."""
    import jax
    import jax.numpy as jnp

    from . import distributed

    n_hosts = int(mesh.shape[distributed.DCN_AXIS])
    return (
        _replicate_program(mesh)
        .lower(
            jax.ShapeDtypeStruct(
                (n_hosts * int(maxn), int(n_feat)),
                jnp.float32,
                sharding=_exchange_sharding(mesh),
            )
        )
        .compile()
        .as_text()
    )


def pod_features(
    runtime: PodRuntime,
    provider,
    featurize_entry: Callable[[PodRecording], np.ndarray],
    n_feat: int,
    plan: Optional[PodIngestPlan] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """The whole partitioned ingest for one run: global metadata pass
    -> owned-subset featurize -> DCN exchange. Returns the global
    ``(features, targets)`` pair, identical on every process — the
    drop-in replacement for ``load_features_device``'s return on pod
    runs."""
    from .. import obs

    if plan is None:
        plan = plan_pod_ingest(provider)
    local = local_features(
        provider, plan, runtime.num_processes, runtime.process_id,
        featurize_entry, n_feat,
    )
    lo, hi = partition(len(plan.entries), runtime.num_processes)[
        runtime.process_id
    ]
    obs.metrics.count("pod.recordings_owned", hi - lo)
    obs.metrics.count("pod.recordings_total", len(plan.entries))
    features = exchange_features(
        runtime.mesh, local, plan.host_row_counts(runtime.num_processes),
        n_feat, runtime.process_id,
    )
    return features, plan.targets
