"""End-to-end data-parallel training step for the flagship P300 model.

The flagship program fuses the whole reference pipeline into one XLA
computation per step: raw epochs -> eegdsp DWT filter-bank cascade ->
48-dim normalized features -> MLP -> loss -> backward -> optimizer
update. Parallelism is the workload's natural pair of axes
(SURVEY.md section 2.3: the reference's only strategy is data
parallelism over epochs; the time axis is this build's net-new
sequence-parallel dimension, exercised in ``parallel/streaming.py``):

- batch (epochs) sharded over the mesh's ``data`` axis;
- parameters replicated; XLA inserts the psum all-reduce for the
  gradient contraction over the sharded batch dimension — the ICI
  equivalent of MLlib's treeAggregate (minus the driver round trip).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs import chaos
from ..ops import dwt as dwt_xla
from . import mesh as pmesh


def _chaos_step(step):
    """Host-side ``device.step`` injection point around a train step.

    Applied to the step each factory RETURNS, never to the inner
    function another factory embeds in its own jit (firing during a
    trace would inject once at compile time instead of per call) —
    factories unwrap via ``__wrapped__`` before composing.

    ``functools.wraps`` copies the jit wrapper's ``__dict__``, which
    is where jax attaches the AOT surface (``lower`` /
    ``eval_shape``), so inspectors like ``__graft_entry__``'s
    collective-structure dryrun keep lowering the underlying jitted
    program through the wrapper (chaos never fires on the AOT path —
    correct: nothing executes).
    """

    @functools.wraps(step)
    def wrapped(state, *args, **kwargs):
        # no per-step telemetry event here: thousands of steps would
        # flood the flight-recorder ring and evict the diagnostic
        # events a crash report exists for — the sgd/nn elastic chunk
        # events already record training progress at sane granularity
        chaos.maybe_fire("device.step")
        return step(state, *args, **kwargs)

    return wrapped


def _raw_step(step):
    """The unwrapped (jit-composable) form of a factory-returned step."""
    return getattr(step, "__wrapped__", step)


def init_mlp_params(
    key, sizes=(48, 64, 2), dtype=jnp.float32
) -> Dict[str, jnp.ndarray]:
    params = {}
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = (
            jax.random.normal(sub, (n_in, n_out), dtype) * jnp.sqrt(2.0 / n_in)
        )
        params[f"b{i}"] = jnp.zeros((n_out,), dtype)
    return params


def forward(params: Dict[str, jnp.ndarray], features: jnp.ndarray) -> jnp.ndarray:
    """(B, 48) features -> (B, 2) class probabilities."""
    x = features
    n_layers = len(params) // 2
    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return jax.nn.softmax(x, axis=-1)


def extract_features(epochs: jnp.ndarray) -> jnp.ndarray:
    """(B, C, T) raw epochs -> (B, C*16) normalized DWT features
    (the shared composed-cascade einsum — ops/dwt.epoch_features)."""
    return dwt_xla.epoch_features(epochs)


def forward_step(params: Dict[str, jnp.ndarray], epochs: jnp.ndarray) -> jnp.ndarray:
    """The flagship jittable forward: raw epochs -> P(target)."""
    return forward(params, extract_features(epochs))[:, 0]


def make_train_step(
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    donate_state: bool = True,
    donate_epochs: bool = False,
):
    """Build (init_state, train_step) for the full pipeline.

    ``train_step(state, epochs, labels, mask) -> (state, loss)`` is one
    jitted program; with a mesh, ``epochs``/``labels``/``mask`` are
    expected sharded over the data axis and params replicated.

    ``donate_state`` (default on) donates the incoming state's buffers
    to the update — params/optimizer memory is reused in place instead
    of sitting double-resident in HBM for the step. Callers must
    rebind (``state, loss = train_step(state, ...)``), which every
    consumer of this functional-update contract already does; pass
    ``False`` to keep the old state alive (e.g. for A/B comparisons).
    ``donate_epochs`` (opt-in) additionally donates the epoch batch —
    at (B, C, 1000) f32 the single biggest buffer of a step — correct
    only when each step consumes a fresh batch (the streaming case),
    never when the caller re-feeds the same staged batch.
    """
    init_state, feat_step = make_feature_train_step(
        mesh, learning_rate, momentum, donate_state=donate_state
    )
    feat_step = _raw_step(feat_step)
    donate = (0,) if donate_state else ()
    if donate_epochs:
        donate = donate + (1,)

    @functools.partial(jax.jit, donate_argnums=donate)
    def train_step(state, epochs, labels, mask):
        # features are constant w.r.t. params, so extracting before
        # the grad is exactly the fused-in-loss formulation; one jit
        # still traces extraction + fwd/bwd/update as one program
        return feat_step(state, extract_features(epochs), labels, mask)

    return init_state, _chaos_step(train_step)


def make_compact_train_step(
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    feature_size: int = 16,
    n_channels: int = 3,
    donate_state: bool = True,
    donate_epochs: bool = False,
):
    """(init_state, step) over COMPACT-RESIDENT epochs: ``step(state,
    epochs_512, labels, mask)`` with ``epochs_512`` of shape
    (B, C, epoch_size) — the analysis window only, no dead columns.

    The training twin of ``fe=dwt-8-tpu-compact`` (ops/dwt
    .make_compact_extractor): :func:`make_train_step` reads the full
    (B, C, 1000) layout to consume 512 columns
    (WaveletTransform.java:127-130); storing epochs pre-sliced halves
    the step's dominant HBM read (12000 -> 6144 B/epoch f32).
    ``donate_state``/``donate_epochs`` follow
    :func:`make_train_step`'s buffer-donation contract."""
    init_state, feat_step = make_feature_train_step(
        mesh, learning_rate, momentum,
        feature_dim=n_channels * feature_size,
        donate_state=donate_state,
    )
    feat_step = _raw_step(feat_step)
    donate = (0,) if donate_state else ()
    if donate_epochs:
        donate = donate + (1,)

    @functools.partial(jax.jit, donate_argnums=donate)
    def step(state, epochs_512, labels, mask):
        feats = dwt_xla.compact_epoch_features(
            epochs_512, wavelet_index, epoch_size, feature_size
        )
        return feat_step(state, feats, labels, mask)

    return init_state, _chaos_step(step)


def make_feature_train_step(
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    feature_dim: int = 48,
    donate_state: bool = True,
):
    """(init_state, step) on precomputed (B, feature_dim) features —
    the MLP half of :func:`make_train_step`, for callers that produce
    features by other fused paths (e.g. the raw-stream step below).
    ``feature_dim`` sizes the MLP input (default 48 = 3 channels x
    16 DWT features). ``donate_state`` (default on) donates the
    incoming state to the update — the params/optimizer buffers are
    reused in place; callers rebind the returned state (the
    functional-update contract every consumer already follows)."""
    tx = optax.sgd(learning_rate, momentum=momentum, nesterov=True)

    def init_state(key):
        params = init_mlp_params(key, sizes=(feature_dim, 64, 2))
        if mesh is not None:
            params = jax.device_put(params, NamedSharding(mesh, P()))
        return {"params": params, "opt": tx.init(params)}

    def loss_fn(params, features, labels, mask):
        probs = forward(params, features)
        y = jnp.stack([labels, 1.0 - labels], axis=1)
        p = jnp.clip(probs, 1e-7, 1.0)
        per_example = -jnp.sum(y * jnp.log(p), axis=1) * mask
        return per_example.sum() / jnp.maximum(mask.sum(), 1.0)

    @functools.partial(
        jax.jit, donate_argnums=(0,) if donate_state else ()
    )
    def step(state, features, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], features, labels, mask
        )
        updates, opt = tx.update(grads, state["opt"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt": opt,
        }, loss

    return init_state, _chaos_step(step)


def make_raw_train_step(
    stride: int,
    n_epochs: int,
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    formulation: str = "auto",
    donate_state: bool = True,
):
    """Train straight from the int16 stream: one step =
    fused regular-SOA ingest (ops/device_ingest, ~4.8 KB HBM/epoch vs
    the 12 KB of f32-resident epochs) -> features -> MLP fwd/bwd ->
    update. ``step(state, raw_i16, resolutions, labels, mask,
    first_position)``; ``first_position`` is a host int (the
    featurizer's phase planning is host-side). ``donate_state``
    follows :func:`make_feature_train_step`'s donation contract (the
    raw stream itself is never donated — it is reused every step)."""
    from ..ops import device_ingest

    ing = device_ingest.make_regular_ingest_featurizer(
        stride, n_epochs, formulation=formulation
    )
    init_state, feat_step = make_feature_train_step(
        mesh, learning_rate, momentum, donate_state=donate_state
    )
    feat_step = _raw_step(feat_step)

    def step(state, raw_i16, resolutions, labels, mask, first_position):
        feats = ing(raw_i16, resolutions, int(first_position))
        return feat_step(state, feats, labels, mask)

    return init_state, _chaos_step(step)


def make_irregular_train_step(
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    chunk_epochs: int = 32768,
    donate_state: bool = True,
):
    """Train straight from the int16 stream with IRREGULAR markers:
    one step = block-gather fused ingest (the gather-free irregular
    formulation, ops/device_ingest.make_block_ingest_featurizer) ->
    features -> MLP fwd/bwd -> update.

    Completes the raw-stream training family: ``make_train_step``
    consumes staged f32 epochs, ``make_raw_train_step`` a regular
    stimulus train, and this the general irregular-marker case the
    reference's per-marker host loop handles
    (OffLineDataProvider.java:200-265) — at int16 bytes/epoch with no
    host epochs and no element gather.

    ``step(state, raw_i16, resolutions, positions, mask, labels)``:
    ``positions``/``mask`` are an IngestPlan's static-capacity arrays
    (device_ingest.plan_ingest), ``labels`` padded to the same
    capacity. Padded rows contribute nothing: the featurizer zeroes
    their rows and the loss masks them out.
    """
    from ..ops import device_ingest

    featurize = device_ingest.make_block_ingest_featurizer(
        chunk_epochs=chunk_epochs
    )
    init_state, feat_step = make_feature_train_step(
        mesh, learning_rate, momentum, donate_state=donate_state
    )
    feat_step = _raw_step(feat_step)

    @functools.partial(
        jax.jit, donate_argnums=(0,) if donate_state else ()
    )
    def step(state, raw_i16, resolutions, positions, mask, labels):
        feats = featurize(raw_i16, resolutions, positions, mask)
        return feat_step(state, feats, labels, mask.astype(feats.dtype))

    return init_state, _chaos_step(step)


def make_irregular_bank_train_step(
    positions,
    mesh=None,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
    n_channels: int = 3,
    chunk: int = 65536,
    tile_b: int = 32,
    mode: str = "bank128",
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int | None = None,
    donate_state: bool = True,
):
    """Irregular raw-stream training through the bank128 Pallas
    featurizer (``ops/ingest_pallas.py``): windows cut in VMEM, none
    of the block formulation's HBM intermediates (measured 120.8
    KB/epoch on the r4 chip vs the 4.5 KB stream bytes).

    Unlike :func:`make_irregular_train_step` (positions traced,
    block-gather featurizer), marker ``positions`` are CONCRETE at
    build time — the usual case: an IngestPlan is host metadata — so
    the VMEM tile planning runs once here and the returned
    ``step(state, raw_i16, resolutions, labels)`` is fully jitted
    with the plan baked in. ``labels`` are in marker order (len ==
    len(positions)); no capacity padding is involved (the plan's
    internal tile padding never leaves the kernel).

    The DWT geometry (``wavelet_index``/``epoch_size``/
    ``skip_samples``/``feature_size``/``pre``) is plumbed through to
    the kernel-window and operator-bank constructors, so a caller
    with non-default geometry gets a bank built for it rather than
    silently-wrong default-geometry features.
    """
    from functools import partial as _partial

    from ..ops import ingest_pallas as ip
    from ..ops import pallas_support as ps
    from ..utils import constants as _const

    if mode not in ip.BANK_MODES:
        raise ValueError(
            f"make_irregular_bank_train_step supports {ip.BANK_MODES}; "
            f"got {mode!r}"
        )
    if pre is None:
        pre = _const.PRESTIMULUS_SAMPLES
    positions = np.asarray(positions)
    n = positions.shape[0]
    window = ip.kernel_window(
        mode, pre=pre, skip_samples=skip_samples, epoch_size=epoch_size
    )
    # cached host planning (ops/plan_cache): rebuilding a step for the
    # same marker layout — checkpoint restore, repeated experiment —
    # reuses the tile plan instead of re-running the sort/pack
    plan = ip.cached_plan_pallas_tiles(
        positions, pre=pre, window=window, chunk=chunk, tile_b=tile_b
    )
    half = chunk // 2
    needed = (int(plan.half_idx.max(initial=0)) + 2) * half
    sample_bucket = 8 * chunk
    blocks_np, shifts_rows_np, inv_np = ip.bank_plan_arrays(
        plan, n_channels
    )
    Wvm_np, fold_np, slab_rows = ip.bank128_banks(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        pre=pre,
    )
    bank_bf16 = mode == "bank128_bf16"
    # the MLP input follows the bank geometry (review finding: a
    # non-default feature_size produced (n, C*K) features against a
    # fixed 48-input network)
    init_state, feat_step = make_feature_train_step(
        mesh, learning_rate, momentum,
        feature_dim=n_channels * feature_size,
        donate_state=donate_state,
    )
    feat_step = _raw_step(feat_step)

    @_partial(
        jax.jit,
        static_argnames=("interpret",),
        donate_argnums=(0,) if donate_state else (),
    )
    def _bank_step(state, raw_i16, resolutions, labels, *, interpret):
        C, S = raw_i16.shape
        if C != n_channels:
            raise ValueError(
                f"bank train step built for {n_channels} channels; "
                f"got raw with {C}"
            )
        pad_to = ((max(S, needed) + sample_bucket - 1)
                  // sample_bucket) * sample_bucket
        if pad_to != S:
            raw_i16 = jnp.pad(raw_i16, ((0, 0), (0, pad_to - S)))
        rows = ip.bank_ingest_rows(
            raw_i16.reshape(C, -1, ip._BANK_BLK),
            jnp.asarray(plan.half_idx),
            jnp.asarray(blocks_np),
            jnp.asarray(shifts_rows_np),
            jnp.asarray(Wvm_np, ip.bank_wvm_dtype(mode)),
            jnp.asarray(fold_np),
            tile_b=tile_b, chunk=chunk, feature_size=feature_size,
            slab_rows=slab_rows, interpret=interpret,
            bank_bf16=bank_bf16,
        )
        feats = ip.bank_finish(rows, resolutions, inv_np)
        mask = jnp.ones((n,), feats.dtype)
        return feat_step(state, feats, labels, mask)

    def step(state, raw_i16, resolutions, labels):
        # interpret resolved per CALL, not at build: the step object
        # may outlive a platform switch (CPU test mesh -> chip), and
        # baking the first caller's platform in is the
        # 'auto'-resolution staleness class device_ingest._run_bank
        # names; as a static arg it costs one retrace on change
        return _bank_step(
            state, raw_i16, resolutions, labels,
            interpret=ps.default_interpret(),
        )

    return init_state, _chaos_step(step)


def make_decode_feature_stage(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    donate_stream: bool = True,
):
    """The overlap path's per-recording staging function: ``(raw_i16,
    resolutions, positions, mask, labels) -> (features, labels,
    mask)`` with the decode+featurize program dispatched inside the
    call — the ``stage_fn`` handed to ``io.staging.prefetch`` so
    recording K+1's decode+featurize runs on the producer thread while
    recording K's train step runs on the consumer.

    ``donate_stream`` (default on) donates the freshly staged int16
    stream buffer to the fused program — with the prefetch buffer
    bounded at 2 (classic double buffering) the staged streams become
    ping/pong buffers reused in place instead of accumulating one HBM
    block per in-flight recording. Donation is skipped on CPU, where
    XLA cannot alias the buffer (ops/decode_ingest.py). ``labels``
    must be padded to the plan's capacity, like
    :func:`make_irregular_train_step`'s.
    """
    from ..ops import decode_ingest

    featurize = decode_ingest.make_decode_ingest_featurizer(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        donate_stream=donate_stream,
    )

    def stage_one(item):
        raw, resolutions, positions, mask, labels = item
        # explicit staging first, so the featurizer's donation has a
        # committed device buffer to consume (a numpy argument would
        # transfer inside the call and leave nothing to donate)
        staged = jax.device_put(np.asarray(raw))
        feats = featurize(staged, resolutions, positions, mask)
        return (
            feats,
            jnp.asarray(np.asarray(labels, np.float32)),
            jnp.asarray(np.asarray(mask, np.float32)),
        )

    return stage_one


def train_over_recordings(
    state,
    step,
    recordings,
    wavelet_index: int = 8,
    feature_size: int = 16,
    buffer_size=None,
    overlap: bool = True,
    donate_stream: bool = True,
):
    """Double-buffered ingest/compute overlap for irregular-marker
    raw-stream training: recording K+1's decode+featurize executes on
    the staging producer thread (``io.staging.prefetch`` with a
    featurize ``stage_fn``) while recording K's train step runs here.

    ``recordings`` yields host tuples ``(raw_i16 (C, S), resolutions,
    positions, mask, labels)`` — an IngestPlan's static-capacity
    metadata plus capacity-padded labels. ``step`` is a
    ``make_feature_train_step`` step. Returns ``(state, losses)``.

    ``overlap=False`` runs the identical staging function serially —
    the parity twin the tests pin (same epochs, same order, same
    losses at any ``buffer_size``). Poison/stop semantics, the
    consumer watchdog (``ProducerDiedError``), and the
    ``staging.producer`` chaos point ride along from ``prefetch``
    unchanged.
    """
    from ..io import staging

    stage_one = make_decode_feature_stage(
        wavelet_index=wavelet_index,
        feature_size=feature_size,
        donate_stream=donate_stream and overlap,
    )
    source = iter(recordings)
    stream = (
        staging.prefetch(
            source, stage_fn=stage_one, buffer_size=buffer_size
        )
        if overlap
        else (stage_one(item) for item in source)
    )
    losses = []
    for feats, labels, mask_f in stream:
        state, loss = step(state, feats, labels, mask_f)
        losses.append(float(loss))
    return state, losses


def stage_batch(
    epochs: np.ndarray, labels: np.ndarray, mesh
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pad + shard a host batch over the data axis; returns mask too."""
    ep, lb, mask = pmesh.shard_batch_with_mask(mesh, epochs, labels)
    return ep, lb, mask
