"""Sequence-parallel irregular-marker ingest: epoch a time-sharded
recording.

Completes the long-context story for the *marker-driven* pipeline the
reference actually runs (OffLineDataProvider.java:200-265): a
recording too long for one chip's HBM is sharded over the mesh's time
axis, and each device cuts + featurizes the epochs whose windows start
in its block — windows straddling a block boundary read their tail
from the right neighbor via a ``ppermute`` ring halo, exactly like
``parallel/streaming.py``'s regular-window extractor. Window
formation on each shard is the block-gather formulation
(``ops/device_ingest.make_block_ingest_featurizer``): tile-row
gathers + the 128-variant operator bank, no element gather.

Division of labor mirrors ``ops/device_ingest``: the host plans
(marker validity, the order-dependent balance scan, shard assignment,
per-shard padding); devices touch the waveform.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .shardmap_compat import shard_map

from ..epochs.extractor import BalanceState
from ..ops import device_ingest
from ..utils import constants
from . import mesh as pmesh

# the block-ingest slab: 8 x 128-lane rows per window (>= 787 live
# samples + 127 max shift) — also the halo length a shard needs from
# its right neighbor
_SLAB = 1024


@dataclasses.dataclass
class ShardedIngestPlan:
    """Host-side shard assignment for one recording's markers."""

    local_positions: np.ndarray  # (n_shards, cap) int32 positions - shard base
    mask: np.ndarray  # (n_shards, cap) bool
    unsort: np.ndarray  # (n_kept,) row index into the flat (S*cap) output
    targets: np.ndarray  # (n_kept,) float64
    stimulus_indices: np.ndarray  # (n_kept,) int
    # geometry the plan was computed against — extract() verifies it
    # so a plan built for a different sharding cannot silently
    # produce wrong features
    block: int = 0
    n_samples: int = 0
    pre: int = constants.PRESTIMULUS_SAMPLES


def plan_sharded_ingest(
    markers,
    guessed_number: int,
    n_samples: int,
    n_shards: int,
    block: int,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    balance: Optional[BalanceState] = None,
    capacity_multiple: int = 8,
    valid_n_samples: Optional[int] = None,
) -> ShardedIngestPlan:
    """Assign each kept marker to the shard containing its window
    start; reference validity + balance semantics come from
    :func:`device_ingest.plan_ingest` (same host scan).

    ``valid_n_samples`` decouples window VALIDITY from the staged
    geometry: the provider pads a recording's sample axis up to the
    shard grid (``n_shards * block``), and the padding must stay
    semantically free — marker validity is judged against the true
    recording length, exactly like ``device_ingest.stage_raw``'s
    bucketing — while shard assignment and the extract-time geometry
    check use the padded length actually staged.
    """
    base = device_ingest.plan_ingest(
        markers,
        guessed_number,
        valid_n_samples if valid_n_samples is not None else n_samples,
        pre=pre,
        balance=balance,
        capacity_multiple=1,
    )
    kept = base.positions[base.mask].astype(np.int64)
    shard_of = np.clip((kept - pre) // block, 0, n_shards - 1)
    counts = np.bincount(shard_of, minlength=n_shards)
    cap = max(
        capacity_multiple,
        int(-(-max(1, counts.max()) // capacity_multiple)) * capacity_multiple,
    )
    local = np.zeros((n_shards, cap), np.int32)
    mask = np.zeros((n_shards, cap), bool)
    unsort = np.empty(kept.shape[0], np.int64)
    fill = np.zeros(n_shards, np.int64)
    for row, (pos, s) in enumerate(zip(kept, shard_of)):
        j = fill[s]
        local[s, j] = pos - s * block
        mask[s, j] = True
        unsort[row] = s * cap + j
        fill[s] += 1
    return ShardedIngestPlan(
        local_positions=local,
        mask=mask,
        unsort=unsort,
        targets=base.targets,
        stimulus_indices=base.stimulus_indices,
        block=block,
        n_samples=n_samples,
        pre=pre,
    )


def make_sharded_ingest(
    mesh: Mesh,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    axis: str = pmesh.TIME_AXIS,
    donate_stream: bool = False,
):
    """Build ``extract(raw_sharded, resolutions, plan) -> features``.

    ``raw_sharded`` is the (C, T) int16 recording sharded over
    ``axis`` (T divisible by the mesh axis size; per-shard block must
    be >= the 1024-sample halo). Returns the (n_kept, C*K) float32
    feature rows in original kept-marker order.

    ``donate_stream`` donates the staged recording buffer to the
    program — each shard's int16 block is dead after the on-device
    scale, so the pipeline's per-recording staging (one fresh buffer
    per file) frees it at dispatch instead of at the next GC. Skipped
    on CPU by the caller (io/provider.py), where XLA cannot alias it
    and would warn per call — the decode rung's ``donate_stream``
    policy.
    """
    n_shards = mesh.shape[axis]
    featurize = device_ingest.make_block_ingest_featurizer(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        pre=pre,
    )

    def block_fn(x_block, res, pos_block, mask_block):
        # right halo: receive the next shard's leading _SLAB samples;
        # the LAST shard gets zeros (windows overhanging the global
        # end zero-pad — Java copyOfRange semantics), not the ring
        # wrap of shard 0's head.
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        head = x_block[:, :_SLAB]
        incoming = jax.lax.ppermute(head, axis, perm)
        shard = jax.lax.axis_index(axis)
        incoming = jnp.where(shard == n_shards - 1, 0, incoming)
        ext = jnp.concatenate([x_block, incoming], axis=1)
        # marker position local to this shard; window start =
        # position - pre lies inside [0, block) by the plan
        return featurize(ext, res, pos_block[0], mask_block[0])[None]

    sharded = jax.jit(
        shard_map(
            block_fn,
            mesh=mesh,
            in_specs=(P(None, axis), P(), P(axis, None), P(axis, None)),
            out_specs=P(axis, None, None),
        ),
        donate_argnums=(0,) if donate_stream else (),
    )
    # feature rows are tiny; allgather them to every host (a sharded
    # global array spans non-addressable devices on multi-host runs,
    # so the host fetches a replicated copy instead)
    replicate = jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P())
    )

    def extract(raw_sharded, resolutions, plan: ShardedIngestPlan):
        T = raw_sharded.shape[1]
        if T % n_shards != 0:
            raise ValueError(
                f"recording length {T} not divisible by {n_shards} shards"
            )
        if T // n_shards < _SLAB:
            raise ValueError(
                f"per-shard block {T // n_shards} smaller than the "
                f"{_SLAB}-sample halo; use fewer shards"
            )
        if (
            plan.block != T // n_shards
            or plan.n_samples != T
            or plan.local_positions.shape[0] != n_shards
            or plan.pre != pre
        ):
            raise ValueError(
                f"plan geometry (block {plan.block}, T {plan.n_samples}, "
                f"{plan.local_positions.shape[0]} shards, pre {plan.pre}) "
                f"does not match this extractor/recording "
                f"(block {T // n_shards}, T {T}, {n_shards} shards, "
                f"pre {pre}); re-plan with plan_sharded_ingest"
            )
        feats = sharded(
            raw_sharded,
            jnp.asarray(resolutions, jnp.float32),
            jnp.asarray(plan.local_positions),
            jnp.asarray(plan.mask),
        )
        rep = replicate(feats)
        flat = np.asarray(rep).reshape(-1, feats.shape[-1])
        return flat[plan.unsort]

    # inner jitted shard_map program, exposed for compiled-HLO
    # inspection (driver dryrun asserts the ring halo lowers to a
    # collective-permute)
    extract._sharded_jit = sharded
    return extract


def shard_block_for(n_samples: int, n_shards: int,
                    quantum: int = 2048) -> int:
    """Per-shard block length for staging an ``n_samples`` recording
    over ``n_shards`` devices: at least the halo slab, covers the
    whole recording, and bucketed up to a ``quantum`` multiple so
    recordings of similar length land on one compiled shard shape
    (``device_ingest.stage_raw``'s bucketing policy, applied to the
    shard grid). The staged length is ``n_shards * block``; padding
    beyond the true length is semantically free (see
    :func:`plan_sharded_ingest`'s ``valid_n_samples``)."""
    block = max(_SLAB, -(-int(n_samples) // int(n_shards)))
    return -(-block // int(quantum)) * int(quantum)


def stage_recording_int16(
    signal: np.ndarray, mesh: Mesh, axis: str = pmesh.TIME_AXIS
):
    """Host->device staging of a (C, T) int16 recording, time-sharded
    (raw int16 bytes on the wire — half the f32 transfer)."""
    from . import streaming

    return streaming.stage_recording(signal, mesh, axis, dtype=jnp.int16)


def stage_recording_local_int16(
    local_block: np.ndarray, mesh: Mesh, axis: str = pmesh.TIME_AXIS
):
    """Multi-host twin of :func:`stage_recording_int16`: each process
    stages only its contiguous time block, raw int16 on the wire."""
    from . import streaming

    return streaming.stage_recording_local(
        local_block, mesh, axis, dtype=np.int16
    )
