"""Version-compat import for ``shard_map``.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export (jax >= 0.6); importing it from
``jax`` directly on the older line (0.4.x/0.5.x — the installed
toolchain) raises ImportError at module import time, which kills test
COLLECTION for every module in the dependency chain, not just the
sharded paths. Both homes accept the same ``(f, mesh=..., in_specs=...,
out_specs=...)`` keyword call shape used throughout ``parallel/``, so
one try/except covers every jax this package supports. Import it from
here, never from jax directly.
"""

from __future__ import annotations

try:  # jax >= 0.6: the graduated top-level export
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax <= 0.5: the experimental home
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map"]
