"""Sequence-parallel streaming front end for continuous EEG.

Net-new vs the reference (which fixes the time axis at 750 samples per
epoch — Const.java:62): continuous multi-channel recordings longer
than one chip's HBM are processed blockwise with the *time axis
sharded over the mesh*. Each device holds a contiguous block of the
recording; windows that straddle a block boundary read their tail from
the right neighbor via a ``ppermute`` halo exchange inside
``shard_map`` — the ring-style pattern of sequence/context
parallelism, applied to a streaming filter bank instead of attention
(BASELINE.json config 5: "Streaming FFT bandpass + DWT on 256ch@1kHz
continuous EEG").

Per window the pipeline is: band-passed eegdsp DWT coefficient prefix
-> L2 normalize. The zero-phase FFT band-pass is folded into the DWT
cascade matrix at build time (:func:`filtered_cascade_kernel`), so at
runtime each window is ONE matmul on the MXU — no FFTs. Windows are
independent after the halo, so everything vectorizes over
(windows x channels) with no cross-device traffic beyond the single
halo hop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import dwt as dwt_xla
from ..ops.signal import bandpass_mask
from . import mesh as pmesh
from .shardmap_compat import shard_map


def _window_starts(block_len: int, stride: int) -> np.ndarray:
    return np.arange(0, block_len, stride)


@functools.lru_cache(maxsize=None)
def filtered_cascade_kernel(
    window: int,
    wavelet_index: int,
    feature_count: int,
    fs: float,
    band: tuple,
) -> np.ndarray:
    """(window, feature_count) float64 kernel with the band-pass
    folded in.

    The zero-phase FFT band-pass (real mask => even circular kernel)
    is a *symmetric* circulant operator B, and the DWT coefficient
    prefix is the matrix K (ops/dwt.cascade_matrix), so
    ``irfft(rfft(w) * mask) @ K == w @ (B @ K)``. Composing B into K
    once in float64 removes every runtime FFT from the streaming
    path — per window the whole filter+DWT chain is one matmul on the
    MXU (measured ~15x faster than the rfft/irfft formulation on a
    256-channel stream).
    """
    mask = np.asarray(
        bandpass_mask(window, fs, *band), dtype=np.float64
    )
    kernel = dwt_xla.cascade_matrix(wavelet_index, window, feature_count)
    return np.fft.irfft(
        np.fft.rfft(kernel, axis=0) * mask[:, None], n=window, axis=0
    )


def _windowed_pipeline(
    ext: jnp.ndarray,
    window: int,
    stride: int,
    kernel: jnp.ndarray,
) -> jnp.ndarray:
    """(C, B+halo) extended block -> (B//stride, C*feature_count).

    The one implementation of the per-window pipeline — windows every
    ``stride`` samples, band-passed DWT prefix via the composed
    kernel, L2 normalize — shared by the mesh-sharded extractor and
    the single-device blocked iterator so the two paths cannot
    diverge.

    When the stride is lane-tile aligned (multiple of 128) and divides
    the window — the default 512/256 geometry — windows are never
    *gathered*: the block reshapes into aligned stride-slabs (a free
    relayout on TPU) and each window is the sum of ``window//stride``
    slab matmuls against the matching kernel rows — the same
    block-operator decomposition as ``device_ingest``'s phase
    formulation. Other geometries fall back to the index gather.
    """
    C, total = ext.shape
    B = total - (window - stride)
    starts = _window_starts(B, stride)
    W = starts.shape[0]
    feature_count = kernel.shape[1]
    k = kernel.astype(ext.dtype)
    if stride % 128 == 0 and window % stride == 0 and B % stride == 0:
        m = window // stride
        slabs = ext[:, : (W + m - 1) * stride].reshape(
            C, W + m - 1, stride
        )
        coeffs = None
        for i in range(m):
            part = jnp.einsum(
                "cws,sk->wck",
                slabs[:, i : i + W, :],
                k[i * stride : (i + 1) * stride],
                precision=jax.lax.Precision.HIGHEST,
            )
            coeffs = part if coeffs is None else coeffs + part
        return dwt_xla.safe_l2_normalize(
            coeffs.reshape(W, C * feature_count)
        )
    idx = starts[:, None] + np.arange(window)[None, :]  # (W, window)
    wins = ext[:, idx]  # (C, W, window)
    flat = wins.transpose(1, 0, 2).reshape(W * C, window)
    coeffs = jnp.dot(flat, k, precision=jax.lax.Precision.HIGHEST)
    return dwt_xla.safe_l2_normalize(coeffs.reshape(W, C * feature_count))


def make_streaming_extractor(
    mesh: Mesh,
    window: int = 512,
    stride: int = 256,
    fs: float = 1000.0,
    band: tuple = (0.5, 40.0),
    wavelet_index: int = 8,
    feature_count: int = 16,
    axis: str = pmesh.TIME_AXIS,
    resolutions=None,
):
    """Build a jitted (C, T)->(n_windows, C*feature_count) extractor
    with T sharded over ``axis`` of ``mesh``.

    Requirements: T divisible by mesh size, block length divisible by
    ``stride``. Windows whose tail would run past the end of the
    recording wrap into the first block (periodic over the ring) —
    callers either arrange T as a multiple of the window or drop the
    last ``window//stride`` rows.

    int16 recordings may be staged raw (``stage_recording(...,
    dtype=jnp.int16)`` / ``stage_recording_local(..., dtype=
    np.int16)`` — half the host->device and DCN staging bytes); the
    scale to physical units happens on device via per-channel
    ``resolutions`` (default 1.0), exactly like the single-device
    ``iter_blocked_features`` path.
    """
    if not 0 < stride <= window:
        raise ValueError(f"stride {stride} must be in (0, window={window}]")
    kernel_np = filtered_cascade_kernel(
        window, wavelet_index, feature_count, fs, tuple(band)
    )
    n_shards = mesh.shape[axis]
    res_np = (
        None
        if resolutions is None
        else np.asarray(resolutions, dtype=np.float32)
    )

    def block_fn(x_block):  # (C, B) on each device
        x_block = _scale_block(x_block, res_np)
        # windows start at 0, stride, ..., B-stride; the last one ends
        # at B - stride + window, so only window - stride halo samples
        # are ever read from the right neighbor
        halo = window - stride
        # right-halo exchange: receive the *next* device's leading
        # samples; device i sends its head to device i-1 (ring).
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        head = x_block[:, :halo]
        incoming = jax.lax.ppermute(head, axis, perm)
        ext = jnp.concatenate([x_block, incoming], axis=1)  # (C, B+halo)
        return _windowed_pipeline(ext, window, stride, jnp.asarray(kernel_np))

    sharded = jax.jit(
        shard_map(
            block_fn,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=P(axis),
        )
    )

    def extract(signal: jnp.ndarray) -> jnp.ndarray:
        # Shapes are static under jit, so the layout contract is
        # enforced at trace time — JAX's clamped out-of-bounds gather
        # would otherwise return silently wrong windows.
        T = signal.shape[-1]
        if T % n_shards != 0:
            raise ValueError(
                f"recording length {T} not divisible by mesh axis "
                f"{axis!r} size {n_shards}"
            )
        block = T // n_shards
        if block % stride != 0:
            raise ValueError(
                f"per-shard block length {block} not a multiple of "
                f"stride {stride}"
            )
        if window - stride > block:
            raise ValueError(
                f"halo {window - stride} exceeds block length {block}; "
                f"use fewer shards or a smaller window"
            )
        return sharded(signal)

    # the inner jitted shard_map program, exposed so callers (the
    # driver dryrun, tests) can inspect its compiled HLO — e.g. assert
    # the ppermute halo really lowers to a collective-permute instead
    # of XLA silently replicating
    extract._sharded_jit = sharded
    return extract


def _scale_block(x, resolutions):
    """The ONE cast+scale step every streaming path runs: float32
    compute dtype (int16 ships raw, f64 does not silently upcast the
    pipeline), optional per-channel resolutions."""
    x = x.astype(jnp.float32)
    if resolutions is not None:
        x = x * jnp.asarray(resolutions, jnp.float32)[:, None]
    return x


@functools.partial(jax.jit, static_argnums=(1, 2))
def _chunk_features(chunk, window, stride, kernel, resolutions):
    """(C, block+halo) chunk -> (block//stride, C*feature_count).

    ``chunk`` may be int16 (shipped raw to halve host->device bytes,
    as in ops/device_ingest) or float; per-channel ``resolutions``
    scale on device.
    """
    return _windowed_pipeline(
        _scale_block(chunk, resolutions), window, stride, kernel
    )


def iter_blocked_features(
    signal: np.ndarray,
    window: int = 512,
    stride: int = 256,
    block: int = 8192,
    fs: float = 1000.0,
    band: tuple = (0.5, 40.0),
    wavelet_index: int = 8,
    feature_count: int = 16,
    resolutions=None,
):
    """Bounded-memory streaming on ONE device: yield feature blocks.

    The mesh version above shards a whole recording across devices; a
    recording too long even for that streams here instead — the host
    feeds ``block``-sample chunks (plus the ``window - stride`` halo
    read from the next chunk) to a fixed-shape jitted program, so
    device memory is O(block), independent of T. Windows are every
    ``stride`` samples with the whole window in-bounds:
    ``(T - window)//stride + 1`` rows total, no periodic wrap.

    Per-channel ``resolutions`` (default 1.0) always scale on device,
    whatever the input dtype — pass them only for unscaled sources.
    int16 inputs additionally ship raw (half the transfer bytes, the
    ops/device_ingest pattern); other dtypes are cast to float32 per
    chunk. Dispatch is pipelined one chunk ahead so chunk i+1's
    host slice + transfer overlaps chunk i's device compute.

    Yields (n_rows, C*feature_count) float32 arrays; concatenate for
    the full matrix (:func:`blocked_features`).
    """
    if not 0 < stride <= window:
        raise ValueError(f"stride {stride} must be in (0, window={window}]")
    if block % stride != 0:
        raise ValueError(f"block {block} must be a multiple of stride {stride}")
    signal = np.asarray(signal)  # no copy/cast: may be a memmap view
    C, T = signal.shape
    if T < window:
        return
    halo = window - stride
    kernel = jnp.asarray(
        filtered_cascade_kernel(
            window, wavelet_index, feature_count, fs, tuple(band)
        ),
        dtype=jnp.float32,
    )
    ship_raw = signal.dtype == np.int16
    res = jnp.asarray(
        np.ones(C, np.float32) if resolutions is None
        else np.asarray(resolutions, dtype=np.float32)
    )
    n_windows = (T - window) // stride + 1
    emitted = 0
    pending = None  # (device feats, take) — one-chunk lookahead
    for start in range(0, T, block):
        take = min(block // stride, n_windows - emitted)
        if take <= 0:
            break
        # per-chunk slice keeps host memory O(block) even for
        # memmapped sources; non-int16 dtypes cast here
        chunk = signal[:, start : start + block + halo]
        if not ship_raw:
            chunk = np.asarray(chunk, dtype=np.float32)
        if chunk.shape[1] < block + halo:  # final chunk: zero-pad
            chunk = np.pad(
                chunk, ((0, 0), (0, block + halo - chunk.shape[1]))
            )
        feats = _chunk_features(
            jnp.asarray(chunk), window, stride, kernel, res
        )
        emitted += take
        if pending is not None:
            yield np.asarray(pending[0])[: pending[1]]
        pending = (feats, take)
    if pending is not None:
        yield np.asarray(pending[0])[: pending[1]]


def blocked_features(signal: np.ndarray, **kwargs) -> np.ndarray:
    """Concatenated :func:`iter_blocked_features` output:
    ((T-window)//stride + 1, C*feature_count) float32."""
    parts = list(iter_blocked_features(signal, **kwargs))
    if not parts:
        C = np.asarray(signal).shape[0]
        f = kwargs.get("feature_count", 16)
        return np.zeros((0, C * f), dtype=np.float32)
    return np.concatenate(parts)


def stage_recording(
    signal: np.ndarray,
    mesh: Mesh,
    axis: str = pmesh.TIME_AXIS,
    dtype=jnp.float32,
):
    """Host->device staging of a (C, T) recording, time-sharded.

    Pass ``dtype=jnp.int16`` to ship raw int16 bytes (half the
    transfer; the sharded-ingest path scales on device)."""
    sharding = NamedSharding(mesh, P(None, axis))
    return jax.device_put(jnp.asarray(signal, dtype=dtype), sharding)


def stage_recording_local(
    local_block: np.ndarray,
    mesh: Mesh,
    axis: str = pmesh.TIME_AXIS,
    dtype=np.float32,
):
    """Multi-host staging: per-process time block -> global recording.

    Each process passes only its contiguous (C, T_local) chunk of the
    recording (its slice of the stream); the result is the global
    (C, T_total) array time-sharded over ``axis``, with the halo
    exchange of :func:`make_streaming_extractor` crossing process
    boundaries over DCN. Single-process this degenerates to
    :func:`stage_recording`. ``dtype=np.int16`` ships raw recording
    bytes (half the wire traffic; the sharded-ingest path scales on
    device).
    """
    from . import distributed

    return distributed.stage_local(
        NamedSharding(mesh, P(None, axis)),
        np.asarray(local_block, dtype=dtype),
    )
