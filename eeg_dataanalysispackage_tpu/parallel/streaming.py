"""Sequence-parallel streaming front end for continuous EEG.

Net-new vs the reference (which fixes the time axis at 750 samples per
epoch — Const.java:62): continuous multi-channel recordings longer
than one chip's HBM are processed blockwise with the *time axis
sharded over the mesh*. Each device holds a contiguous block of the
recording; windows that straddle a block boundary read their tail from
the right neighbor via a ``ppermute`` halo exchange inside
``shard_map`` — the ring-style pattern of sequence/context
parallelism, applied to a streaming filter bank instead of attention
(BASELINE.json config 5: "Streaming FFT bandpass + DWT on 256ch@1kHz
continuous EEG").

Per window the pipeline is: FFT band-pass (rfft mask -> irfft) ->
eegdsp DWT cascade -> first-k coefficients -> L2 normalize; windows
are independent after the halo, so everything vectorizes over
(windows x channels) with no cross-device traffic beyond the single
halo hop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import shard_map

from ..ops import dwt as dwt_xla
from ..ops.signal import bandpass_mask
from . import mesh as pmesh


def _window_starts(block_len: int, stride: int) -> np.ndarray:
    return np.arange(0, block_len, stride)


def make_streaming_extractor(
    mesh: Mesh,
    window: int = 512,
    stride: int = 256,
    fs: float = 1000.0,
    band: tuple = (0.5, 40.0),
    wavelet_index: int = 8,
    feature_count: int = 16,
    axis: str = pmesh.TIME_AXIS,
):
    """Build a jitted (C, T)->(n_windows, C*feature_count) extractor
    with T sharded over ``axis`` of ``mesh``.

    Requirements: T divisible by mesh size, block length divisible by
    ``stride``. Windows whose tail would run past the end of the
    recording wrap into the first block (periodic over the ring) —
    callers either arrange T as a multiple of the window or drop the
    last ``window//stride`` rows.
    """
    if not 0 < stride <= window:
        raise ValueError(f"stride {stride} must be in (0, window={window}]")
    fmask_np = bandpass_mask(window, fs, *band)
    n_shards = mesh.shape[axis]

    def block_fn(x_block):  # (C, B) on each device
        C, B = x_block.shape
        # windows start at 0, stride, ..., B-stride; the last one ends
        # at B - stride + window, so only window - stride halo samples
        # are ever read from the right neighbor
        halo = window - stride
        # right-halo exchange: receive the *next* device's leading
        # samples; device i sends its head to device i-1 (ring).
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        head = x_block[:, :halo]
        incoming = jax.lax.ppermute(head, axis, perm)
        ext = jnp.concatenate([x_block, incoming], axis=1)  # (C, B+halo)

        starts = _window_starts(B, stride)
        idx = starts[:, None] + np.arange(window)[None, :]  # (W, window)
        wins = ext[:, idx]  # (C, W, window)
        W = starts.shape[0]

        # FFT band-pass per window
        fmask = jnp.asarray(fmask_np)
        spec = jnp.fft.rfft(wins, axis=-1)
        filtered = jnp.fft.irfft(spec * fmask, n=window, axis=-1).astype(
            x_block.dtype
        )

        flat = filtered.transpose(1, 0, 2).reshape(W * C, window)
        coeffs = dwt_xla.windowed_features(flat, wavelet_index, feature_count)
        feats = coeffs.reshape(W, C * feature_count)
        return dwt_xla.safe_l2_normalize(feats)

    sharded = jax.jit(
        shard_map(
            block_fn,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=P(axis),
        )
    )

    def extract(signal: jnp.ndarray) -> jnp.ndarray:
        # Shapes are static under jit, so the layout contract is
        # enforced at trace time — JAX's clamped out-of-bounds gather
        # would otherwise return silently wrong windows.
        T = signal.shape[-1]
        if T % n_shards != 0:
            raise ValueError(
                f"recording length {T} not divisible by mesh axis "
                f"{axis!r} size {n_shards}"
            )
        block = T // n_shards
        if block % stride != 0:
            raise ValueError(
                f"per-shard block length {block} not a multiple of "
                f"stride {stride}"
            )
        if window - stride > block:
            raise ValueError(
                f"halo {window - stride} exceeds block length {block}; "
                f"use fewer shards or a smaller window"
            )
        return sharded(signal)

    return extract


def stage_recording(signal: np.ndarray, mesh: Mesh, axis: str = pmesh.TIME_AXIS):
    """Host->device staging of a (C, T) recording, time-sharded."""
    sharding = NamedSharding(mesh, P(None, axis))
    return jax.device_put(jnp.asarray(signal, dtype=jnp.float32), sharding)


def stage_recording_local(
    local_block: np.ndarray, mesh: Mesh, axis: str = pmesh.TIME_AXIS
):
    """Multi-host staging: per-process time block -> global recording.

    Each process passes only its contiguous (C, T_local) chunk of the
    recording (its slice of the stream); the result is the global
    (C, T_total) array time-sharded over ``axis``, with the halo
    exchange of :func:`make_streaming_extractor` crossing process
    boundaries over DCN. Single-process this degenerates to
    :func:`stage_recording`.
    """
    from . import distributed

    return distributed.stage_local(
        NamedSharding(mesh, P(None, axis)),
        np.asarray(local_block, dtype=np.float32),
    )
