"""Stacked-population training programs: one compile, P members.

The pipeline's ``classifiers=`` fan-out (PR 3) amortized *ingest*
across models but still trained strictly one model at a time — the
BENCH_pr3 ``pipeline_e2e_fanout5`` line pays one XLA dispatch (and,
across processes, one compile) per member. This module is the
canonical JAX answer for the SGD family: stack the population onto a
leading axis with ``jax.vmap`` and train every member inside one
jitted program. Dynamic axes (learning rate, L2 reg, seed, the
fold's sample mask) ride as batched *array* inputs, so a new grid
point or fold never retriggers a compile; static axes (iteration
count, loss, architecture) are shared by construction.

Two engines, both built on the exact per-member programs the
sequential paths run — ``models/sgd._run_sgd`` and the shared
backprop step ``models/nn._make_backprop_step`` — so a population
member's trajectory is the sequential trajectory, just batched:

- :func:`train_linear_population` — logreg/SVM (MLlib-SGD
  semantics). Single-fold populations share one gathered train
  matrix (bit-identical invocation to ``train_clf=``); multi-fold
  populations keep the full feature matrix and carry one ``(n,)``
  train mask per member (``_run_sgd``'s ``sample_mask`` seam, the
  same mechanism mesh sharding uses for padding).
- :func:`train_nn_population` — the flax/optax backprop loop, vmapped
  over init seeds and learning rates. Init, dropout keys, and the
  optimizer update all trace with the member axis; first-order
  updaters only (L-BFGS/line-search carry value_fn closures, and
  greedy pretraining is a host-driven walk — those members raise
  :class:`PopulationVmapUnsupported` and the orchestrator falls back
  to the looped path).

Numerics: vmap batches the member matvecs into matmuls, which XLA may
reduce in a different lane order — member weights agree with the
sequential run to float32 roundoff (~1e-7 relative, measured), not
bit-for-bit. Thresholded *predictions* (and therefore the confusion
matrices behind ``ClassificationStatistics``) are pinned bit-identical
to the sequential equivalents in tests/test_population.py; the margin
safety band on real feature rows is ~3 orders of magnitude wider than
the roundoff drift.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class PopulationVmapUnsupported(ValueError):
    """This member set cannot train as one vmapped program (NN
    pretraining, a value_fn-carrying optimizer, multi-fold NN);
    callers degrade to the looped engine — same members, same
    statistics, dispatches not amortized."""


def _weight_arrays(config, n_members, weight_pos, weight_neg):
    """Per-member class-weight lists + the engine-wide ``weighted``
    static. One cost axis anywhere makes EVERY member run the
    weighted program (weights ride as traced member-axis scalars, so
    new cost sweep values recompile nothing); an all-unit population
    keeps the exact pre-knob program."""
    wp = (
        [float(w) for w in weight_pos]
        if weight_pos is not None
        else [float(config.weight_pos)] * n_members
    )
    wn = (
        [float(w) for w in weight_neg]
        if weight_neg is not None
        else [float(config.weight_neg)] * n_members
    )
    weighted = any(w != 1.0 for w in wp + wn)
    return wp, wn, weighted


def train_linear_population(
    features: np.ndarray,
    labels: np.ndarray,
    config,
    step_sizes: Sequence[float],
    reg_params: Sequence[float],
    seeds: Sequence[int],
    masks: Optional[np.ndarray],
    weight_pos: Optional[Sequence[float]] = None,
    weight_neg: Optional[Sequence[float]] = None,
    stacked_features: bool = False,
) -> np.ndarray:
    """Train P MLlib-SGD members in one vmapped program.

    ``features``/``labels`` are the shared rows: the gathered train
    split when ``masks`` is None (single-fold population), else the
    full matrix with ``masks`` ``(P, n)`` selecting each member's
    train rows. ``config`` contributes the static/shared scalars
    (iterations, loss, mini-batch fraction, convergence tol).
    Returns ``(P, d)`` float32 weights, member order preserved.

    Seizure-workload axes: ``weight_pos``/``weight_neg`` are
    per-member cost-sensitive class weights (the ``cost_fp``/
    ``cost_fn`` sweep axes — traced scalars on the member axis);
    ``stacked_features=True`` marks ``features`` as carrying a
    LEADING member axis ``(P, n, d)`` — one feature matrix per member,
    the ``fe_sweep=`` feature-config comparison. Both ride as batched
    array inputs, so new sweep points (costs or feature configs of
    the same cardinality) retrigger zero compiles.
    """
    from ..models import sgd

    x = jnp.asarray(features, dtype=jnp.float32)
    y = jnp.asarray(labels, dtype=jnp.float32)
    full_batch = config.mini_batch_fraction >= 1.0
    statics = dict(
        num_iterations=int(config.num_iterations),
        loss=config.loss,
        full_batch=full_batch,
    )
    frac = float(config.mini_batch_fraction)
    tol = float(config.convergence_tol)
    wp, wn, weighted = _weight_arrays(
        config, len(list(seeds)), weight_pos, weight_neg
    )

    def member(xm, step, reg, seed, mask, w_pos, w_neg):
        kwargs = (
            dict(weighted=True, weight_pos=w_pos, weight_neg=w_neg)
            if weighted
            else {}
        )
        return sgd._run_sgd(
            xm, y, step, frac, reg, seed, tol,
            sample_mask=mask, **statics, **kwargs,
        )

    steps_a = jnp.asarray(list(step_sizes), jnp.float32)
    regs_a = jnp.asarray(list(reg_params), jnp.float32)
    seeds_a = jnp.asarray(list(seeds), jnp.int32)
    wp_a = jnp.asarray(wp, jnp.float32)
    wn_a = jnp.asarray(wn, jnp.float32)
    x_axis = 0 if stacked_features else None
    if masks is None:
        masks_a = None
        in_axes = (x_axis, 0, 0, 0, None, 0, 0)
    else:
        masks_a = jnp.asarray(masks, jnp.float32)
        in_axes = (x_axis, 0, 0, 0, 0, 0, 0)
    weights = jax.vmap(member, in_axes=in_axes)(
        x, steps_a, regs_a, seeds_a, masks_a, wp_a, wn_a
    )
    return np.asarray(weights)


def train_linear_population_looped(
    features: np.ndarray,
    labels: np.ndarray,
    config,
    step_sizes: Sequence[float],
    reg_params: Sequence[float],
    seeds: Sequence[int],
    masks: Optional[np.ndarray],
    weight_pos: Optional[Sequence[float]] = None,
    weight_neg: Optional[Sequence[float]] = None,
    stacked_features: bool = False,
) -> np.ndarray:
    """The sequential twin of :func:`train_linear_population`: the
    identical per-member invocation, dispatched one member at a time
    (the bench's ``population_looped`` baseline and the engine's
    fallback). Scalars pass as Python weak types, exactly like
    ``sgd.train_linear`` — a single-fold member here is bit-identical
    to a ``train_clf=`` run with the same hyperparameters. The
    ``weighted`` static follows the same any-member rule as the
    vmapped engine, so the two dispatch the same per-member program
    even at unit weights inside a costed population."""
    from ..models import sgd

    y = jnp.asarray(labels, dtype=jnp.float32)
    statics = dict(
        num_iterations=int(config.num_iterations),
        loss=config.loss,
        full_batch=config.mini_batch_fraction >= 1.0,
    )
    frac = float(config.mini_batch_fraction)
    tol = float(config.convergence_tol)
    wp, wn, weighted = _weight_arrays(
        config, len(list(seeds)), weight_pos, weight_neg
    )
    if not stacked_features:
        x_shared = jnp.asarray(features, dtype=jnp.float32)
    out = []
    for i in range(len(seeds)):
        x = (
            jnp.asarray(features[i], jnp.float32)
            if stacked_features
            else x_shared
        )
        mask = None if masks is None else jnp.asarray(masks[i], jnp.float32)
        kwargs = (
            dict(weighted=True, weight_pos=wp[i], weight_neg=wn[i])
            if weighted
            else {}
        )
        out.append(
            sgd._run_sgd(
                x, y, float(step_sizes[i]), frac, float(reg_params[i]),
                int(seeds[i]), tol, sample_mask=mask, **statics, **kwargs,
            )
        )
    return np.asarray(jnp.stack(out))


def pad_members(n_members: int, n_shards: int) -> int:
    """Member-axis padding for an ``n_shards``-way mesh: the smallest
    multiple of ``n_shards`` >= ``n_members``. The single source for
    the padded cardinality, shared by the engine and its telemetry
    (per-device member counts in the run report / bench lines)."""
    return -(-int(n_members) // int(n_shards)) * int(n_shards)


def _member_axes_tuple(mesh, axis) -> tuple:
    """Normalize the member-sharding ``axis`` argument — a single
    mesh-axis name, a tuple of names (the pod's ``(hosts, data)``
    spec), or None (the mesh's first axis) — to a tuple of names."""
    if axis is None:
        return (mesh.axis_names[0],)
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def _member_spec_entry(axes: tuple):
    """The PartitionSpec entry sharding one array dimension over
    ``axes``: the bare name for one axis, the tuple for several
    (``P(("hosts", "data"))`` splits the member axis over hosts
    outermost, then each host's devices — contiguous per host, which
    is what the multi-process staging slices on)."""
    return axes[0] if len(axes) == 1 else axes


@functools.lru_cache(maxsize=None)
def _sharded_linear_program(
    mesh, axes, num_iterations, loss, full_batch, frac, tol, weighted,
    stacked,
):
    """(train, replicate) jitted pair for one mesh/config geometry.

    ``train`` is the vmapped per-member program of
    :func:`train_linear_population` wrapped in ``shard_map`` over the
    mesh's member ``axes`` (one name on a single-host mesh; the
    ``(hosts, data)`` pair on a pod's hybrid mesh, so the member axis
    spans every device of every host): each device runs the SAME
    member invocation on its local member block, so the program
    contains no cross-device traffic at all — member training is
    embarrassingly parallel. ``replicate`` gathers the tiny (P, d)
    weight block back to every device (the one collective of the path
    — an all-gather for real meshes, asserted in the MULTICHIP dryrun
    and, for the DCN-crossing pod form, in tests/_pod_worker.py), so
    the host fetch works on multi-host runs where the sharded array
    spans non-addressable devices. lru-cached per (mesh, statics):
    repeat runs over the same mesh re-jit nothing.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import sgd
    from .shardmap_compat import shard_map

    def member(xm, y, step, reg, seed, mask, w_pos, w_neg):
        kwargs = (
            dict(weighted=True, weight_pos=w_pos, weight_neg=w_neg)
            if weighted
            else {}
        )
        return sgd._run_sgd(
            xm, y, step, frac, reg, seed, tol,
            sample_mask=mask, num_iterations=num_iterations, loss=loss,
            full_batch=full_batch, **kwargs,
        )

    vmapped = jax.vmap(member, in_axes=(0 if stacked else None, None,
                                        0, 0, 0, 0, 0, 0))
    entry = _member_spec_entry(axes)
    x_spec = P(entry, None, None) if stacked else P()
    member_spec = P(entry)
    train = jax.jit(
        shard_map(
            vmapped,
            mesh=mesh,
            in_specs=(
                x_spec, P(), member_spec, member_spec, member_spec,
                P(entry, None), member_spec, member_spec,
            ),
            out_specs=P(entry, None),
        )
    )
    replicate = jax.jit(lambda w: w, out_shardings=NamedSharding(mesh, P()))
    return train, replicate


def train_linear_population_sharded(
    features: np.ndarray,
    labels: np.ndarray,
    config,
    step_sizes: Sequence[float],
    reg_params: Sequence[float],
    seeds: Sequence[int],
    masks: Optional[np.ndarray],
    mesh,
    weight_pos: Optional[Sequence[float]] = None,
    weight_neg: Optional[Sequence[float]] = None,
    stacked_features: bool = False,
    axis: Optional[str] = None,
) -> np.ndarray:
    """:func:`train_linear_population` with the MEMBER axis sharded
    over ``mesh`` — P members train on N devices in ~P/N-member local
    blocks, one device-parallel program (the ROADMAP item-2 shape:
    a 16-member CV x sweep population on N chips in ~1/N wall time).

    Same argument contract as the vmapped engine. ``axis`` names the
    mesh axis (or, on a pod's hybrid mesh, the tuple of axes — hosts
    outermost) the member axis shards over; on multi-process meshes
    every input is staged globally — the shared rows replicate across
    hosts once (``distributed.replicate_across_hosts``) and each
    process stages only its own contiguous member shard of the
    per-member arrays (``distributed.stage_local``, the
    ``stage_global_batch`` path), so no host materializes device
    arrays for members it does not own and the final weight
    all-gather is the run's one cross-DCN collective.
    Members are padded
    up to a mesh multiple (:func:`pad_members`) with INERT members:
    an all-zero sample mask makes ``_run_sgd``'s per-iteration sampled
    count 0, so every padded member's update is skipped and its
    weights stay exactly zero — the identical masking seam
    ``shard_map``'s batch padding (:func:`shard_batch_with_mask`)
    already uses. Padded rows are sliced off before returning, so the
    caller sees (P, d) weights in member order, like the other
    engines. Real members therefore run the same per-member program
    as the vmapped engine (an explicit all-ones mask equals the
    engine's implicit one value-for-value), and the 1-device mesh is
    the degenerate case: statistics downstream are pinned byte-equal
    to the vmapped engine's (tests/test_sharded_population.py), the
    same margin-band contract that pins vmap==looped.
    """
    axes = _member_axes_tuple(mesh, axis)
    n_shards = 1
    for a in axes:
        n_shards *= int(mesh.shape[a])
    n_members = len(list(seeds))
    padded = pad_members(n_members, n_shards)
    pad = padded - n_members

    y = np.asarray(labels, np.float32)
    n = y.shape[0]
    wp, wn, weighted = _weight_arrays(config, n_members, weight_pos,
                                      weight_neg)

    def member_axis(values, dtype):
        a = np.asarray(list(values), dtype)
        if pad:
            # padded members reuse member 0's traced hyperparameters
            # (any finite value works — their zero mask makes the
            # program inert); what matters is the shape
            a = np.concatenate([a, np.repeat(a[:1], pad, axis=0)])
        return a

    masks_arr = (
        np.ones((n_members, n), np.float32)
        if masks is None
        else np.asarray(masks, np.float32)
    )
    if pad:
        masks_arr = np.concatenate(
            [masks_arr, np.zeros((pad, n), np.float32)]
        )
    if stacked_features:
        x = np.asarray(features, np.float32)
        if pad:
            x = np.concatenate([x, np.repeat(x[:1], pad, axis=0)])
    else:
        x = np.asarray(features, np.float32)

    train, replicate = _sharded_linear_program(
        mesh, axes,
        int(config.num_iterations), config.loss,
        config.mini_batch_fraction >= 1.0,
        float(config.mini_batch_fraction),
        float(config.convergence_tol),
        weighted, bool(stacked_features),
    )

    from jax.sharding import NamedSharding, PartitionSpec as P

    # single-host meshes stage as before (plain host arrays; jit
    # commits them); a mesh spanning other processes' devices needs
    # GLOBAL arrays — shared rows replicate across hosts, per-member
    # arrays stage each process's contiguous member shard only
    multiproc = not NamedSharding(mesh, P()).is_fully_addressable
    if multiproc:
        from . import distributed as _dist

        if axes[0] != _dist.DCN_AXIS:
            # the per-host member slice below is contiguous only when
            # hosts shard the member axis outermost (hybrid_mesh's
            # layout); anything else would stage the wrong members
            raise ValueError(
                f"multi-process member sharding needs the "
                f"{_dist.DCN_AXIS!r} axis outermost, got {axes}"
            )

    def stage_member(a):
        if not multiproc:
            return jnp.asarray(a)
        from . import distributed

        pid = jax.process_index()
        per_host = padded // jax.process_count()
        spec = P(
            _member_spec_entry(axes), *([None] * (a.ndim - 1))
        )
        return distributed.stage_local(
            NamedSharding(mesh, spec),
            a[pid * per_host : (pid + 1) * per_host],
        )

    def stage_shared(a):
        if not multiproc:
            return jnp.asarray(a)
        from . import distributed

        return distributed.replicate_across_hosts(np.asarray(a), mesh)

    w_sharded = train(
        stage_member(x) if stacked_features else stage_shared(x),
        stage_shared(y),
        stage_member(member_axis(step_sizes, np.float32)),
        stage_member(member_axis(reg_params, np.float32)),
        stage_member(member_axis([int(s) for s in seeds], np.int32)),
        stage_member(masks_arr),
        stage_member(member_axis(wp, np.float32)),
        stage_member(member_axis(wn, np.float32)),
    )
    weights = np.asarray(replicate(w_sharded))
    return weights[:n_members]


def train_nn_population(
    model,
    make_optimizer,
    loss_fn,
    features: np.ndarray,
    onehot_labels: np.ndarray,
    seeds: Sequence[int],
    learning_rates: Sequence[float],
    iterations: int,
) -> List:
    """Train P flax members in one vmapped program.

    ``model`` is the configured ``models.nn._Net``; ``make_optimizer``
    maps a (possibly traced) learning rate to a first-order optax
    transformation; ``loss_fn`` the configured loss. Each member
    inits from its own ``PRNGKey(seed)`` (init AND dropout stream,
    matching ``fit``) and runs ``iterations`` steps of the shared
    backprop scan body. Returns a list of P per-member param pytrees.
    """
    x = jnp.asarray(features, dtype=jnp.float32)
    y = jnp.asarray(onehot_labels, dtype=jnp.float32)

    from ..models.nn import _make_backprop_step

    def member(seed, lr):
        rng = jax.random.PRNGKey(seed)
        params = model.init(
            {"params": rng, "dropout": rng}, x[:1], train=False
        )
        tx = make_optimizer(lr)
        opt_state = tx.init(params)
        step = _make_backprop_step(model, tx, False, loss_fn, rng, x, y)
        (params, _), _ = jax.lax.scan(
            step, (params, opt_state), jnp.arange(int(iterations))
        )
        return params

    seeds_a = jnp.asarray(list(seeds), jnp.int32)
    lrs_a = jnp.asarray(list(learning_rates), jnp.float32)
    stacked = jax.jit(jax.vmap(member))(seeds_a, lrs_a)
    return [
        jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
        for i in range(len(seeds_a))
    ]
