"""The replicated gateway fleet: N front doors over ONE shared journal.

ROADMAP item 4. A single :class:`~eeg_dataanalysispackage_tpu.gateway.server.GatewayServer`
is both the throughput ceiling and the single point of failure of the
whole plan service. This module removes both without inventing any new
durability machinery: the write-ahead journal (one atomic file per
plan) is already the source of truth, recovery and idempotent replay
already exist per process — what a fleet needs on top is exactly one
primitive, *who executes this record*, and that is
``scheduler/lease.py``'s ``plan-<id>.lease`` file (the feature cache's
cross-process ``O_EXCL`` single-flight, hardened with heartbeats and
the break-only-the-provably-dead rule).

One :class:`FleetReplica` wraps one gateway over the shared
``journal_dir``:

- **accept anywhere** — a submission to any replica lease-claims its
  plan *before* the write-ahead record lands (scheduler/executor.py),
  so peers scanning the journal never see an unleased record for work
  a live replica owns;
- **finish anywhere** — the scan loop polls ``PlanJournal.unfinished()``
  for submitted-but-unleased (or stale-leased) records and claims them
  through :meth:`PlanExecutor.claim_and_run`: the journaled query
  re-parses, idempotency keys and report dirs ride the record's meta,
  and the completion record lands under the ORIGINAL plan id — a
  SIGKILLed replica's in-flight plans complete on a surviving peer
  with byte-identical statistics (the deterministic pipeline is what
  makes takeover invisible to the caller);
- **leave gracefully** — :meth:`drain` (the SIGTERM path in
  ``gateway/__main__.py``) flips the replica to 503/not-ready,
  releases every still-queued plan's lease so peers take over
  immediately, finishes what is already running, then exits.

The scan loop doubles as fleet-scope recovery: a replica starting over
a journal with unfinished records claims and resumes them exactly as
it claims a dead peer's — so :class:`FleetReplica` runs its gateway
with ``recover=False`` and there is ONE takeover code path, not two.

Split-brain non-goals (docs/architecture.md): replicas share one
journal *directory* (one filesystem), and holder-death is checked by
pid + start token — this is a same-host/shared-mount fleet, not a
consensus protocol. A partitioned filesystem is outside the contract.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..scheduler import lease as lease_mod
from .server import GatewayServer

logger = logging.getLogger(__name__)

#: how often a replica scans the shared journal for claimable records
ENV_SCAN_INTERVAL = "EEG_TPU_FLEET_SCAN_INTERVAL_S"
_DEFAULT_SCAN_INTERVAL_S = 0.25


def scan_interval() -> float:
    value = os.environ.get(ENV_SCAN_INTERVAL)
    if not value:
        return _DEFAULT_SCAN_INTERVAL_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.2fs",
            ENV_SCAN_INTERVAL, value, _DEFAULT_SCAN_INTERVAL_S,
        )
        return _DEFAULT_SCAN_INTERVAL_S


class FleetReplica:
    """One gateway replica participating in a shared-journal fleet.

    Wraps (and owns the fleet lifecycle of) a :class:`GatewayServer`
    whose executor has a ``journal_dir`` — pass an existing server, or
    let the replica build one from the keyword knobs. ``start()``
    attaches the lease directory, starts the HTTP front door WITHOUT
    the single-process ``recover()`` (the scan loop IS recovery at
    fleet scope), and spawns the scan + heartbeat threads.
    """

    def __init__(
        self,
        server: Optional[GatewayServer] = None,
        replica_id: Optional[str] = None,
        scan_interval_s: Optional[float] = None,
        heartbeat_interval_s: Optional[float] = None,
        **gateway_kwargs: Any,
    ):
        if server is None:
            gateway_kwargs.setdefault("recover", False)
            server = GatewayServer(
                replica_id=replica_id, **gateway_kwargs
            )
        self.server = server
        self.executor = server.executor
        if self.executor.journal is None:
            raise ValueError(
                "a fleet replica needs a journal_dir — the shared "
                "journal directory IS the fleet"
            )
        if replica_id:
            server.replica_id = replica_id
        self.replica_id = server.replica_id
        # fleet-scope recovery is the scan loop (one takeover path);
        # the single-process recover() would race peers for unleased
        # records without the lease claim
        server._recover = False
        self.leases = lease_mod.LeaseDir(
            self.executor.journal.directory, holder=self.replica_id
        )
        self.executor.leases = self.leases
        # the crash flight recorder (obs/report.py) reads the held
        # leases off this registration when a fleet plan dies
        lease_mod.set_active(self.leases)
        self._scan_interval_s = (
            scan_interval_s if scan_interval_s is not None
            else scan_interval()
        )
        self._heartbeat_interval_s = (
            heartbeat_interval_s if heartbeat_interval_s is not None
            else min(2.0, max(0.05, lease_mod.lease_timeout() / 4.0))
        )
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        #: plan ids this replica claimed from the scan loop (takeovers
        #: + fleet-scope recovery), for the operator surface
        self.claimed: List[str] = []
        self._claimed_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        """Start the gateway and the fleet threads; returns
        (host, port)."""
        if self._started:
            return self.server.host, self.server.port
        self._started = True
        addr = self.server.start()
        for name, target in (
            ("scan", self._scan_loop),
            ("heartbeat", self._heartbeat_loop),
        ):
            t = threading.Thread(
                target=target,
                name=f"eeg-tpu-fleet-{name}-{self.replica_id}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "fleet replica %s serving on %s:%d over journal %s",
            self.replica_id, addr[0], addr[1],
            self.executor.journal.directory,
        )
        return addr

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Hard stop (the crash-adjacent path): stop the fleet
        threads, close the gateway, release our leases. Queued
        journaled plans stay 'submitted' — peers take over."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=join_timeout_s)
        self._threads = []
        self.server.close(join_timeout_s=join_timeout_s)
        self.leases.release_all()

    def drain(
        self, timeout_s: float = 60.0, poll_s: float = 0.05,
    ) -> Dict[str, Any]:
        """Graceful SIGTERM drain: stop accepting (503 + not-ready),
        release every still-queued plan's lease so peers take over
        immediately, finish what is already running, then stop.
        Returns {released, finished, abandoned} plan-id lists —
        ``abandoned`` is nonempty only when ``timeout_s`` expired with
        plans still running (their journal records stay 'submitted';
        a peer breaks our stale lease once we exit)."""
        from .. import obs

        self.server.draining = True
        obs.metrics.count("fleet.drains")
        # claimable the instant the lease vanishes — no timeout wait
        released = self.executor.drain_queued()
        # snapshot what is still ours to finish NOW: a completed plan's
        # ticket is evicted once its journal record lands, so a later
        # live_ids() delta would under-report — status() falls back to
        # the journal and keeps reading the terminal state
        tracked = list(self.executor.live_ids())
        deadline = time.monotonic() + timeout_s
        finished: List[str] = []
        while True:
            states = {
                plan_id: (
                    self.executor.status(plan_id) or {}
                ).get("state")
                for plan_id in tracked
            }
            running = [
                plan_id for plan_id, state in states.items()
                if state in ("queued", "running")
            ]
            finished = sorted(
                plan_id for plan_id, state in states.items()
                if state in ("completed", "failed", "cancelled")
            )
            if not running:
                break
            if time.monotonic() >= deadline:
                logger.warning(
                    "drain timeout with %d plans still running: %s "
                    "(their journal records stay 'submitted')",
                    len(running), running,
                )
                self.close()
                return {
                    "released": released,
                    "finished": finished,
                    "abandoned": running,
                }
            time.sleep(poll_s)
        self.close()
        return {
            "released": released, "finished": finished, "abandoned": [],
        }

    def __enter__(self) -> "FleetReplica":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the scan loop (takeover + fleet-scope recovery) -----------------

    def scan_once(self) -> List[str]:
        """One pass over the shared journal: claim every unfinished
        record nobody (live) holds. Returns the plan ids claimed this
        pass. Public for tests and for the admin tooling — the loop
        just calls it on an interval."""
        claimed: List[str] = []
        for entry in self.executor.journal.unfinished():
            if self._stop.is_set() or self.server.draining:
                break
            plan_id = entry.get("plan_id")
            if not plan_id:
                continue
            try:
                handle = self.executor.claim_and_run(entry)
            except Exception as e:
                # one bad record (or a transient claim error) must not
                # wedge the scan — the whole fleet runs this loop
                logger.error(
                    "fleet claim of %s failed (%s: %s); will rescan",
                    plan_id, type(e).__name__, e,
                )
                continue
            if handle is not None:
                claimed.append(plan_id)
                logger.info(
                    "replica %s claimed %s (takeover)",
                    self.replica_id, plan_id,
                )
        if claimed:
            with self._claimed_lock:
                self.claimed.extend(claimed)
        return claimed

    def _scan_loop(self) -> None:
        while not self._stop.wait(self._scan_interval_s):
            if self.server.draining:
                continue
            try:
                self.scan_once()
            except Exception as e:  # pragma: no cover - defensive
                logger.error(
                    "fleet scan pass failed (%s: %s); continuing",
                    type(e).__name__, e,
                )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._heartbeat_interval_s):
            try:
                self.leases.heartbeat_all()
            except Exception as e:  # pragma: no cover - defensive
                logger.error(
                    "fleet heartbeat pass failed (%s: %s); continuing",
                    type(e).__name__, e,
                )

    # -- the operator surface --------------------------------------------

    def fleet_view(self) -> Dict[str, Any]:
        """The replica's own fleet snapshot (plan_admin's ``fleet``
        subcommand renders the same shape straight off the shared
        directory for out-of-process observers)."""
        with self._claimed_lock:
            claimed = list(self.claimed)
        return {
            "replica": self.replica_id,
            "draining": self.server.draining,
            "journal_dir": self.executor.journal.directory,
            "held": [
                lease.plan_id for lease in self.leases.held_leases()
            ],
            "claimed": claimed,
            "leases": self.leases.scan(),
            "counters": lease_mod.stats(),
        }
