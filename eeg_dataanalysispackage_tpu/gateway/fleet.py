"""The replicated gateway fleet: N front doors over ONE shared journal.

ROADMAP item 4. A single :class:`~eeg_dataanalysispackage_tpu.gateway.server.GatewayServer`
is both the throughput ceiling and the single point of failure of the
whole plan service. This module removes both without inventing any new
durability machinery: the write-ahead journal (one atomic file per
plan) is already the source of truth, recovery and idempotent replay
already exist per process — what a fleet needs on top is exactly one
primitive, *who executes this record*, and that is
``scheduler/lease.py``'s ``plan-<id>.lease`` file (the feature cache's
cross-process ``O_EXCL`` single-flight, hardened with heartbeats and
the break-only-the-provably-dead rule).

One :class:`FleetReplica` wraps one gateway over the shared
``journal_dir``:

- **accept anywhere** — a submission to any replica lease-claims its
  plan *before* the write-ahead record lands (scheduler/executor.py),
  so peers scanning the journal never see an unleased record for work
  a live replica owns;
- **finish anywhere** — the scan loop polls ``PlanJournal.unfinished()``
  for submitted-but-unleased (or stale-leased) records and claims them
  through :meth:`PlanExecutor.claim_and_run`: the journaled query
  re-parses, idempotency keys and report dirs ride the record's meta,
  and the completion record lands under the ORIGINAL plan id — a
  SIGKILLed replica's in-flight plans complete on a surviving peer
  with byte-identical statistics (the deterministic pipeline is what
  makes takeover invisible to the caller);
- **leave gracefully** — :meth:`drain` (the SIGTERM path in
  ``gateway/__main__.py``) flips the replica to 503/not-ready,
  releases every still-queued plan's lease so peers take over
  immediately, finishes what is already running, then exits.

The scan loop doubles as fleet-scope recovery: a replica starting over
a journal with unfinished records claims and resumes them exactly as
it claims a dead peer's — so :class:`FleetReplica` runs its gateway
with ``recover=False`` and there is ONE takeover code path, not two.

Split-brain non-goals (docs/architecture.md): replicas share one
journal *directory* (one filesystem), and holder-death is checked by
pid + start token — this is a same-host/shared-mount fleet, not a
consensus protocol. A partitioned filesystem is outside the contract.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..scheduler import lease as lease_mod
from ..scheduler import placement as placement_mod
from .server import GatewayServer

logger = logging.getLogger(__name__)

#: how often a replica scans the shared journal for claimable records
ENV_SCAN_INTERVAL = "EEG_TPU_FLEET_SCAN_INTERVAL_S"
_DEFAULT_SCAN_INTERVAL_S = 0.25

#: set to "0" to disable the per-replica scan jitter (lockstep scans,
#: the pre-jitter behavior — useful when a test wants deterministic
#: scan timing)
ENV_SCAN_JITTER = "EEG_TPU_FLEET_SCAN_JITTER"

#: jitter amplitude as a fraction of the scan interval: the offset is
#: a deterministic per-replica value in [-25%, +25%]
_SCAN_JITTER_AMPLITUDE = 0.25


def scan_interval() -> float:
    value = os.environ.get(ENV_SCAN_INTERVAL)
    if not value:
        return _DEFAULT_SCAN_INTERVAL_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.2fs",
            ENV_SCAN_INTERVAL, value, _DEFAULT_SCAN_INTERVAL_S,
        )
        return _DEFAULT_SCAN_INTERVAL_S


def jittered_scan_interval(replica_id: str, base: Optional[float] = None) -> float:
    """The replica's effective scan interval: the configured base ±25%,
    offset by a DETERMINISTIC function of the replica id.

    N replicas configured with one interval otherwise scan the shared
    journal in lockstep — every pass, every replica O_EXCL-races every
    claimable record and N-1 of them lose (``lease.stats()``'s
    ``claim_losses`` counts exactly these). A per-replica offset
    de-phases the scans so most passes see a record either already
    claimed (no race: the read path, not the create path) or not yet
    scanned by peers. Deterministic — blake2b of the replica id, not
    ``random`` — so a replica's cadence is stable across restarts and
    reproducible in tests. ``EEG_TPU_FLEET_SCAN_JITTER=0`` disables.
    """
    if base is None:
        base = scan_interval()
    if os.environ.get(ENV_SCAN_JITTER, "").strip() == "0":
        return base
    digest = hashlib.blake2b(
        replica_id.encode(), digest_size=8
    ).digest()
    unit = int.from_bytes(digest, "big") / float(2 ** 64)  # [0, 1)
    factor = 1.0 + _SCAN_JITTER_AMPLITUDE * (2.0 * unit - 1.0)
    return max(0.001, base * factor)


class FleetReplica:
    """One gateway replica participating in a shared-journal fleet.

    Wraps (and owns the fleet lifecycle of) a :class:`GatewayServer`
    whose executor has a ``journal_dir`` — pass an existing server, or
    let the replica build one from the keyword knobs. ``start()``
    attaches the lease directory, starts the HTTP front door WITHOUT
    the single-process ``recover()`` (the scan loop IS recovery at
    fleet scope), and spawns the scan + heartbeat threads.
    """

    def __init__(
        self,
        server: Optional[GatewayServer] = None,
        replica_id: Optional[str] = None,
        scan_interval_s: Optional[float] = None,
        heartbeat_interval_s: Optional[float] = None,
        **gateway_kwargs: Any,
    ):
        if server is None:
            gateway_kwargs.setdefault("recover", False)
            server = GatewayServer(
                replica_id=replica_id, **gateway_kwargs
            )
        self.server = server
        self.executor = server.executor
        if self.executor.journal is None:
            raise ValueError(
                "a fleet replica needs a journal_dir — the shared "
                "journal directory IS the fleet"
            )
        if replica_id:
            server.replica_id = replica_id
        self.replica_id = server.replica_id
        # fleet-scope recovery is the scan loop (one takeover path);
        # the single-process recover() would race peers for unleased
        # records without the lease claim
        server._recover = False
        self.leases = lease_mod.LeaseDir(
            self.executor.journal.directory, holder=self.replica_id
        )
        self.executor.leases = self.leases
        # the crash flight recorder (obs/report.py) reads the held
        # leases off this registration when a fleet plan dies
        lease_mod.set_active(self.leases)
        self._scan_interval_s = jittered_scan_interval(
            self.replica_id,
            base=scan_interval_s,
        )
        # the shared device pool (scheduler/placement.py): None unless
        # EEG_TPU_DEVICE_POOL opts in — placement default-off keeps
        # the PR 17 fleet behavior byte-identical (a 1-CPU-device pool
        # would serialize every plan behind one ordinal)
        self.pool = placement_mod.DevicePool.from_env(self.leases)
        self.executor.placement = self.pool
        # pod routing: a won processes=N plan runs through the
        # pod-assist coordinator (fresh subprocess per member — a
        # live gateway's jax backend cannot re-bootstrap), peers
        # enlist via the journaled assist records the scan loop reads
        self.pod_assist = PodAssist(self)
        self.executor.pod_assist = self.pod_assist
        self._heartbeat_interval_s = (
            heartbeat_interval_s if heartbeat_interval_s is not None
            else min(2.0, max(0.05, lease_mod.lease_timeout() / 4.0))
        )
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        #: plan ids this replica claimed from the scan loop (takeovers
        #: + fleet-scope recovery), for the operator surface
        self.claimed: List[str] = []
        self._claimed_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        """Start the gateway and the fleet threads; returns
        (host, port)."""
        if self._started:
            return self.server.host, self.server.port
        self._started = True
        addr = self.server.start()
        for name, target in (
            ("scan", self._scan_loop),
            ("heartbeat", self._heartbeat_loop),
        ):
            t = threading.Thread(
                target=target,
                name=f"eeg-tpu-fleet-{name}-{self.replica_id}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "fleet replica %s serving on %s:%d over journal %s",
            self.replica_id, addr[0], addr[1],
            self.executor.journal.directory,
        )
        return addr

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Hard stop (the crash-adjacent path): stop the fleet
        threads, close the gateway, release our leases. Queued
        journaled plans stay 'submitted' — peers take over."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=join_timeout_s)
        self._threads = []
        self.server.close(join_timeout_s=join_timeout_s)
        self.pod_assist.close()
        if self.pool is not None:
            self.pool.release_all()
        self.leases.release_all()

    def drain(
        self, timeout_s: float = 60.0, poll_s: float = 0.05,
    ) -> Dict[str, Any]:
        """Graceful SIGTERM drain: stop accepting (503 + not-ready),
        release every still-queued plan's lease so peers take over
        immediately, finish what is already running, then stop.
        Returns {released, finished, abandoned} plan-id lists —
        ``abandoned`` is nonempty only when ``timeout_s`` expired with
        plans still running (their journal records stay 'submitted';
        a peer breaks our stale lease once we exit)."""
        from .. import obs

        self.server.draining = True
        obs.metrics.count("fleet.drains")
        # claimable the instant the lease vanishes — no timeout wait
        released = self.executor.drain_queued()
        # snapshot what is still ours to finish NOW: a completed plan's
        # ticket is evicted once its journal record lands, so a later
        # live_ids() delta would under-report — status() falls back to
        # the journal and keeps reading the terminal state
        tracked = list(self.executor.live_ids())
        deadline = time.monotonic() + timeout_s
        finished: List[str] = []
        while True:
            states = {
                plan_id: (
                    self.executor.status(plan_id) or {}
                ).get("state")
                for plan_id in tracked
            }
            running = [
                plan_id for plan_id, state in states.items()
                if state in ("queued", "running")
            ]
            finished = sorted(
                plan_id for plan_id, state in states.items()
                if state in ("completed", "failed", "cancelled")
            )
            if not running:
                break
            if time.monotonic() >= deadline:
                logger.warning(
                    "drain timeout with %d plans still running: %s "
                    "(their journal records stay 'submitted')",
                    len(running), running,
                )
                self.close()
                return {
                    "released": released,
                    "finished": finished,
                    "abandoned": running,
                }
            time.sleep(poll_s)
        self.close()
        return {
            "released": released, "finished": finished, "abandoned": [],
        }

    def __enter__(self) -> "FleetReplica":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the scan loop (takeover + fleet-scope recovery) -----------------

    def scan_once(self) -> List[str]:
        """One pass over the shared journal: claim every unfinished
        record nobody (live) holds. Returns the plan ids claimed this
        pass. Public for tests and for the admin tooling — the loop
        just calls it on an interval."""
        claimed: List[str] = []
        for entry in self.executor.journal.unfinished():
            if self._stop.is_set() or self.server.draining:
                break
            plan_id = entry.get("plan_id")
            if not plan_id:
                continue
            try:
                handle = self.executor.claim_and_run(entry)
            except Exception as e:
                # one bad record (or a transient claim error) must not
                # wedge the scan — the whole fleet runs this loop
                logger.error(
                    "fleet claim of %s failed (%s: %s); will rescan",
                    plan_id, type(e).__name__, e,
                )
                continue
            if handle is not None:
                claimed.append(plan_id)
                logger.info(
                    "replica %s claimed %s (takeover)",
                    self.replica_id, plan_id,
                )
        if claimed:
            with self._claimed_lock:
                self.claimed.extend(claimed)
        return claimed

    def _scan_loop(self) -> None:
        while not self._stop.wait(self._scan_interval_s):
            if self.server.draining:
                continue
            try:
                self.scan_once()
            except Exception as e:  # pragma: no cover - defensive
                logger.error(
                    "fleet scan pass failed (%s: %s); continuing",
                    type(e).__name__, e,
                )
            try:
                self.pod_assist.scan_assists()
            except Exception as e:  # pragma: no cover - defensive
                logger.error(
                    "pod-assist scan pass failed (%s: %s); continuing",
                    type(e).__name__, e,
                )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._heartbeat_interval_s):
            try:
                self.leases.heartbeat_all()
            except Exception as e:  # pragma: no cover - defensive
                logger.error(
                    "fleet heartbeat pass failed (%s: %s); continuing",
                    type(e).__name__, e,
                )

    # -- the operator surface --------------------------------------------

    def fleet_view(self) -> Dict[str, Any]:
        """The replica's own fleet snapshot (plan_admin's ``fleet``
        subcommand renders the same shape straight off the shared
        directory for out-of-process observers)."""
        with self._claimed_lock:
            claimed = list(self.claimed)
        view = {
            "replica": self.replica_id,
            "draining": self.server.draining,
            "journal_dir": self.executor.journal.directory,
            "held": [
                lease.plan_id
                for lease in self.leases.held_plan_leases()
            ],
            "claimed": claimed,
            "leases": self.leases.scan(),
            "counters": lease_mod.stats(),
            "devices_held": self.leases.held_device_ordinals(),
            "scan_interval_s": round(self._scan_interval_s, 4),
        }
        if self.pool is not None:
            view["device_pool"] = self.pool.health()
        return view


class PodAssist:
    """The fleet's pod routing, both halves.

    **Coordinator half** (:meth:`run`, called from the executor's
    worker thread for a won ``processes=N`` plan): publish a
    ``podassist-<plan>.json`` record in the shared journal dir, spawn
    our OWN process-0 member as a fresh ``parallel.pod_worker``
    subprocess (a live gateway's jax backend cannot re-bootstrap;
    this is why no member runs in-process), reap it, return its
    statistics text. The record carries our pid + start token, so
    peers can tell a live request from a SIGKILLed coordinator's
    leftovers and clear the latter. Every failure path returns None —
    the executor then runs the plan inline, where the builder's
    existing preflight-timeout ladder degrades pod -> single-host:
    degrade, never wedge.

    **Peer half** (:meth:`scan_assists`, called from the fleet scan
    loop): for each live assist record from ANOTHER replica, claim
    per-rank ``assist:<plan>:<k>`` leases (the same O_EXCL protocol
    as plans — each worker rank gets exactly one parent fleet-wide)
    and spawn worker members. Children self-exit when this replica
    dies (the pod_worker parent watchdog) and are killed past
    ``EEG_TPU_ASSIST_MAX_S`` — a coordinator that vanished mid-pod
    strands no rank forever.
    """

    def __init__(self, replica: "FleetReplica"):
        self.replica = replica
        self.journal = replica.executor.journal
        self.leases = replica.leases
        self._lock = threading.Lock()
        #: lease-name -> (Popen, lease, spawn-monotonic)
        self._children: Dict[str, Any] = {}
        self.max_child_age_s = float(
            os.environ.get("EEG_TPU_ASSIST_MAX_S") or 600.0
        )
        #: worker ranks this replica will parent at once — an idle
        #: replica lends compute, a busy one stays a front door
        self.worker_cap = int(
            os.environ.get("EEG_TPU_ASSIST_WORKERS") or 2
        )

    # -- coordinator half ------------------------------------------------

    def run(self, ticket) -> Optional[str]:
        from .. import obs

        plan = ticket.plan
        processes = int(plan.pod.processes)
        coordinator = plan.pod.coordinator
        if coordinator is None:
            from ..parallel import distributed

            coordinator = f"127.0.0.1:{distributed.free_port_pair()}"
        obs.metrics.count("fleet.pod_assist_requests")
        token = lease_mod._pid_start_token(os.getpid()) or ""
        self.journal.record_assist(
            ticket.plan_id, coordinator, processes,
            holder=self.replica.replica_id,
            pid=os.getpid(), start_token=token,
            query=plan.query,
        )
        from ..parallel import pod as pod_mod

        child = None
        try:
            child = pod_mod.spawn_pod_member(
                plan.query, coordinator, processes, process_id=0,
            )
            out, err = child.communicate(
                timeout=self.max_child_age_s
            )
        except Exception as e:
            logger.warning(
                "pod-assist coordinator member for %s failed "
                "(%s: %s); degrading to the inline ladder",
                ticket.plan_id, type(e).__name__, e,
            )
            if child is not None and child.poll() is None:
                child.kill()
                child.communicate()
            obs.metrics.count("fleet.pod_assist_degraded")
            return None
        finally:
            self.journal.clear_assist(ticket.plan_id)
        if child.returncode != 0:
            logger.warning(
                "pod-assist coordinator member for %s exited rc %d; "
                "degrading to the inline ladder: %s",
                ticket.plan_id, child.returncode, err[-1500:],
            )
            obs.metrics.count("fleet.pod_assist_degraded")
            return None
        try:
            result = json.loads(out.strip().splitlines()[-1])
            statistics = result["statistics"]
        except Exception:
            obs.metrics.count("fleet.pod_assist_degraded")
            return None
        obs.metrics.count("fleet.pod_assist_completed")
        return statistics

    # -- peer half -------------------------------------------------------

    def scan_assists(self) -> List[str]:
        """One pass: reap finished worker children, clear dead
        coordinators' records, claim + spawn ranks for live ones.
        Returns the lease names newly spawned this pass."""
        from .. import obs

        self._reap()
        spawned: List[str] = []
        for rec in self.journal.assist_entries():
            plan_id = rec.get("plan_id")
            if not plan_id:
                continue
            if rec.get("holder") == self.replica.replica_id:
                continue  # our own request; rank 0 is our child
            if lease_mod._holder_dead(
                rec.get("pid"), rec.get("start_token") or ""
            ):
                # the SIGKILLed-coordinator path: the record must not
                # outlive its writer, or every scan forever would try
                # to staff a pod nobody coordinates
                self.journal.clear_assist(plan_id)
                obs.metrics.count("fleet.pod_assist_cleared")
                continue
            try:
                processes = int(rec["processes"])
                coordinator = rec["coordinator"]
                query = rec["query"]
            except (KeyError, TypeError, ValueError):
                continue
            for rank in range(1, processes):
                name = f"assist:{plan_id}:{rank}"
                with self._lock:
                    if len(self._children) >= self.worker_cap:
                        return spawned
                    if name in self._children:
                        continue
                lease = self.leases.try_claim(name)
                if lease is None or lease is lease_mod.FOREIGN_HELD:
                    continue
                try:
                    from ..parallel import pod as pod_mod

                    child = pod_mod.spawn_pod_member(
                        query, coordinator, processes,
                        process_id=rank,
                    )
                except Exception as e:
                    logger.warning(
                        "pod-assist worker spawn for %s failed "
                        "(%s: %s)", name, type(e).__name__, e,
                    )
                    self.leases.release(name)
                    continue
                with self._lock:
                    self._children[name] = (
                        child, lease, time.monotonic()
                    )
                obs.metrics.count("fleet.pod_assist_workers")
                spawned.append(name)
        return spawned

    def _reap(self) -> None:
        with self._lock:
            items = list(self._children.items())
        for name, (child, lease, since) in items:
            if child.poll() is None:
                if time.monotonic() - since > self.max_child_age_s:
                    # a rank stuck past the budget (its pod died
                    # under it mid-collective): kill, don't strand
                    child.kill()
                    child.communicate()
                else:
                    continue
            else:
                child.communicate()  # drain pipes; output discarded
            lease.release()
            with self._lock:
                self._children.pop(name, None)

    def close(self) -> None:
        with self._lock:
            items = list(self._children.items())
            self._children = {}
        for name, (child, lease, _since) in items:
            if child.poll() is None:
                child.kill()
            child.communicate()
            lease.release()
