"""``python -m eeg_dataanalysispackage_tpu.gateway`` — serve the plan
service from the command line.

Example (one replica of a three-replica fleet)::

    python -m eeg_dataanalysispackage_tpu.gateway \\
        --port 8321 --journal-dir /var/lib/eeg-tpu/journal \\
        --report-root /var/lib/eeg-tpu/reports --max-concurrent 4 \\
        --fleet --replica-id gw-a

The journal directory makes the server crash-only: kill it mid-plan,
restart with the same ``--journal-dir``, and recovery resumes every
unfinished plan under its original id (idempotency-keyed clients
rejoin them transparently). ``--fleet`` promotes that to fleet scope:
N processes over ONE ``--journal-dir`` lease-claim plans from the
shared journal (gateway/fleet.py), so any replica accepts, any replica
finishes, and a killed replica's in-flight plans complete on a peer.

Signals: **SIGTERM drains gracefully** — stop accepting (503 +
/readyz not-ready), release still-queued leases so peers take over
immediately, finish in-flight plans, exit 0. SIGKILL is the crash
path the journal + lease timeout already cover.

``EEG_TPU_GATEWAY_PORT`` sets the default port; ``--port 0`` binds an
ephemeral one (printed at startup).
"""

import argparse
import logging
import os
import signal
import sys
import threading

from .fleet import FleetReplica
from .server import ENV_PORT, GatewayServer


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="eeg_dataanalysispackage_tpu.gateway",
        description="HTTP front door over the multi-tenant PlanExecutor",
    )
    parser.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1 — loopback only)",
    )
    parser.add_argument(
        "--port", type=int,
        default=int(os.environ.get(ENV_PORT, "8321") or 8321),
        help=f"bind port (default ${ENV_PORT} or 8321; 0 = ephemeral)",
    )
    parser.add_argument(
        "--journal-dir", default=None,
        help="write-ahead journal directory (enables crash recovery "
        "and idempotent re-submits across restarts; shared by every "
        "replica of a --fleet)",
    )
    parser.add_argument(
        "--report-root", default=None,
        help="per-plan run_report.json tree (<root>/<plan_id>/)",
    )
    parser.add_argument("--max-concurrent", type=int, default=2)
    parser.add_argument("--queue-depth", type=int, default=16)
    parser.add_argument("--max-attempts", type=int, default=3)
    parser.add_argument(
        "--no-recover", action="store_true",
        help="skip journal recovery at startup (diagnostics only)",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="run as a replica of a shared-journal fleet: lease-claim "
        "plans from --journal-dir (requires it), take over dead "
        "peers' unfinished records, heartbeat held leases "
        "(gateway/fleet.py)",
    )
    parser.add_argument(
        "--replica-id", default=None,
        help="this replica's fleet identity (default gw-<pid>); "
        "written into lease files and run reports",
    )
    parser.add_argument(
        "--drain-timeout-s", type=float, default=60.0,
        help="max seconds a SIGTERM drain waits for in-flight plans "
        "before abandoning them to peer takeover (default 60)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.fleet and not args.journal_dir:
        parser.error("--fleet requires --journal-dir (the shared "
                     "journal directory IS the fleet)")
    server = GatewayServer(
        host=args.host,
        port=args.port,
        journal_dir=args.journal_dir,
        report_root=args.report_root,
        max_concurrent=args.max_concurrent,
        queue_depth=args.queue_depth,
        max_attempts=args.max_attempts,
        recover=not args.no_recover,
        replica_id=args.replica_id,
    )
    replica = FleetReplica(server=server) if args.fleet else None
    if replica is not None:
        host, port = replica.start()
    else:
        host, port = server.start()
    if server.recovery is not None:
        print(
            f"recovered journal: "
            f"{len(server.recovery['completed'])} completed kept, "
            f"{len(server.recovery['resumed'])} unfinished resumed",
            file=sys.stderr,
        )
    print(
        f"plan service listening on http://{host}:{port}"
        + (f" (fleet replica {server.replica_id})" if replica else ""),
        flush=True,
    )

    # graceful SIGTERM drain: stop accepting, hand queued leases back
    # to the fleet, finish in-flight plans, exit 0. The event dance
    # (instead of draining inside the handler) keeps the drain's
    # journal/lease I/O out of signal context.
    stop = threading.Event()

    def _on_sigterm(signum, frame):
        server.draining = True  # refuse new work instantly
        stop.set()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        stop.wait()
    except KeyboardInterrupt:
        print("shutting down", file=sys.stderr)
        if replica is not None:
            replica.close()
        else:
            server.close()
        return 0
    if replica is not None:
        outcome = replica.drain(timeout_s=args.drain_timeout_s)
    else:
        server.draining = True
        drained = (
            server.executor.drain_queued()
            if server.executor.journal is not None else []
        )
        outcome = {"released": drained, "finished": [], "abandoned": []}
        server.close()
    print(
        f"drained: {len(outcome['released'])} released to peers, "
        f"{len(outcome['finished'])} finished in-flight, "
        f"{len(outcome['abandoned'])} abandoned",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
