"""``python -m eeg_dataanalysispackage_tpu.gateway`` — serve the plan
service from the command line.

Example::

    python -m eeg_dataanalysispackage_tpu.gateway \\
        --port 8321 --journal-dir /var/lib/eeg-tpu/journal \\
        --report-root /var/lib/eeg-tpu/reports --max-concurrent 4

The journal directory makes the server crash-only: kill it mid-plan,
restart with the same ``--journal-dir``, and recovery resumes every
unfinished plan under its original id (idempotency-keyed clients
rejoin them transparently). ``EEG_TPU_GATEWAY_PORT`` sets the default
port; ``--port 0`` binds an ephemeral one (printed at startup).
"""

import argparse
import logging
import os
import sys
import time

from .server import ENV_PORT, GatewayServer


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="eeg_dataanalysispackage_tpu.gateway",
        description="HTTP front door over the multi-tenant PlanExecutor",
    )
    parser.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1 — loopback only)",
    )
    parser.add_argument(
        "--port", type=int,
        default=int(os.environ.get(ENV_PORT, "8321") or 8321),
        help=f"bind port (default ${ENV_PORT} or 8321; 0 = ephemeral)",
    )
    parser.add_argument(
        "--journal-dir", default=None,
        help="write-ahead journal directory (enables crash recovery "
        "and idempotent re-submits across restarts)",
    )
    parser.add_argument(
        "--report-root", default=None,
        help="per-plan run_report.json tree (<root>/<plan_id>/)",
    )
    parser.add_argument("--max-concurrent", type=int, default=2)
    parser.add_argument("--queue-depth", type=int, default=16)
    parser.add_argument("--max-attempts", type=int, default=3)
    parser.add_argument(
        "--no-recover", action="store_true",
        help="skip journal recovery at startup (diagnostics only)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    server = GatewayServer(
        host=args.host,
        port=args.port,
        journal_dir=args.journal_dir,
        report_root=args.report_root,
        max_concurrent=args.max_concurrent,
        queue_depth=args.queue_depth,
        max_attempts=args.max_attempts,
        recover=not args.no_recover,
    )
    host, port = server.start()
    if server.recovery is not None:
        print(
            f"recovered journal: "
            f"{len(server.recovery['completed'])} completed kept, "
            f"{len(server.recovery['resumed'])} unfinished resumed",
            file=sys.stderr,
        )
    print(f"plan service listening on http://{host}:{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down", file=sys.stderr)
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
