"""The gateway's HTTP server and its executor plumbing.

Design constraints, in order:

- **Thin.** The gateway adds a wire format, never semantics: admission
  is the executor's shed-with-evidence queue, deadlines are the
  executor's per-plan budgets, idempotency rides the write-ahead
  journal, recovery is ``recover()`` at startup. Every behavior here
  is testable without HTTP by calling the executor directly; the
  handler only maps outcomes onto status codes.
- **Stdlib only.** ``http.server.ThreadingHTTPServer`` (one thread per
  connection, daemonic) is plenty for a front door whose unit of work
  is a whole pipeline plan; request handling does no device work —
  submit returns the instant the plan is journaled+queued.
- **Crash-only.** The server holds no state the journal doesn't: kill
  it mid-plan, restart over the same ``journal_dir``, and ``recover()``
  resumes every unfinished plan under its original id while
  idempotency-keyed re-submits rejoin them (pinned in
  tests/test_gateway.py with a real SIGKILL).

Wire contract (all JSON):

====== ========================== ===========================================
method path                       outcome
====== ========================== ===========================================
POST   /plans                     201 {plan_id, state} — body is the query
                                  string, percent-escapes decoded
                                  (``pipeline.builder.decode_percent_query``);
                                  200 when ``X-Idempotency-Key`` replayed an
                                  existing plan; 400 invalid; 429 shed (with
                                  evidence + the journaled plan id); 503
                                  closed
POST   /predict                   the serving HOT PATH (requires a
                                  ``predict_service`` — a multiplexed
                                  inference service attached at
                                  construction): body is JSON {tenant,
                                  window, resolutions[, deadline_s]};
                                  200 {tenant, prediction, margin,
                                  latency_ms, batch_size}; replayed
                                  ``X-Idempotency-Key`` returns the cached
                                  answer, reused with a different body 409;
                                  400 invalid/unknown tenant; 429 shed with
                                  the per-tenant evidence body (tenant depth,
                                  quota, queue depth, oldest-age — the
                                  admission queue's structured record); 503
                                  no service/draining/wedged
GET    /plans                     200 {plans: [...]} — journal + live states
GET    /plans/<id>                200 status; 404 unknown
GET    /plans/<id>/report         200 {statistics, statistics_sha256, error,
                                  run_report}; 409 while non-terminal
DELETE /plans/<id>                200 {cancelled: true}; 409 not-queued
GET    /stats                     200 {dedup, queue_depth, scheduler
                                  counters}; with a ``predict_service``
                                  attached also ``serve`` — the full serve
                                  block including the per-tenant attribution
                                  sub-block (serve/multiplex.py); in a
                                  replica fleet also ``fleet`` — replica id
                                  + lease claim/takeover/break counters
GET    /metrics                   200 Prometheus text exposition
                                  (obs/metrics_export.py): every
                                  ``obs.metrics`` counter as a
                                  ``*_total`` series, serve latency
                                  histograms (global + per-tenant
                                  labels), lease counters, queue depth,
                                  and the ``eeg_tpu_build_info`` series
                                  naming the replica — the ONLY
                                  non-JSON endpoint; deterministic
                                  ordering, fleet_top's scrape surface
GET    /healthz                   200 {ok: true, ...} — pure LIVENESS: the
                                  process answers; never checks disk
GET    /readyz                    READINESS: 200 {ready: true} only when
                                  the journal dir is writable, the executor
                                  is accepting, and the replica is not
                                  draining; 503 {ready: false, reasons}
                                  otherwise — what a fleet's load balancer
                                  (and the fleet bench) waits on
====== ========================== ===========================================

Headers on POST /plans: ``X-Idempotency-Key`` (client retry token,
journaled with the plan record), ``X-Plan-Deadline-S`` (float; the
executor's per-plan deadline budget), ``X-Trace-Id`` (caller-supplied
distributed-trace id; minted when absent, echoed as ``trace_id`` in
the response, and journaled with the plan record so a fleet takeover
CONTINUES the same trace on the surviving replica). ``X-Trace-Id`` is
honored on POST /predict too (echoed in the prediction payload).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from .. import obs
from ..scheduler import dedup as dedup_mod
from ..scheduler.executor import (
    IdempotencyConflictError,
    PlanExecutor,
    PlanOwnedElsewhereError,
    PlanShedError,
)
from ..serve.batcher import (
    ServeError,
    ServiceClosedError,
    ServiceWedgedError,
    ShedError,
)

logger = logging.getLogger(__name__)

#: bound on the /predict idempotency replay cache (answers are small —
#: one prediction each — but the cache must not grow with traffic)
_PREDICT_CACHE_LIMIT = 4096

#: default port when none is given (0 = ephemeral, the test default)
ENV_PORT = "EEG_TPU_GATEWAY_PORT"

_PLAN_PATH = re.compile(r"^/plans/([A-Za-z0-9_.-]+)(/report)?$")

#: an acceptable inbound X-Trace-Id (filesystem-safe — trace segment
#: files embed it in attrs); anything else is ignored and a fresh id
#: is minted instead of 400ing the plan over a malformed ornament
_TRACE_ID = re.compile(r"^[A-Za-z0-9_.-]{1,128}$")


def mint_trace_id(inbound: Optional[str] = None) -> str:
    """The request's distributed-trace id: the caller's ``X-Trace-Id``
    when it is well-formed, a fresh uuid4 hex otherwise."""
    if inbound and _TRACE_ID.match(inbound):
        return inbound
    return uuid.uuid4().hex


class GatewayServer:
    """One HTTP front door over one :class:`PlanExecutor`.

    Pass an ``executor`` to front an existing one, or let the gateway
    own a fresh executor built from the keyword knobs (closed with the
    gateway). ``recover=True`` (default) replays the journal at
    :meth:`start` — the crash-only restart path.
    """

    def __init__(
        self,
        executor: Optional[PlanExecutor] = None,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        journal_dir: Optional[str] = None,
        report_root: Optional[str] = None,
        max_concurrent: int = 2,
        queue_depth: int = 16,
        max_attempts: int = 3,
        recover: bool = True,
        predict_service=None,
        replica_id: Optional[str] = None,
    ):
        if port is None:
            port = int(os.environ.get(ENV_PORT, "0") or 0)
        self.host = host
        #: this front door's identity in a replica fleet (lease files
        #: carry it; run reports echo it). Defaults to a pid-derived
        #: id so even a solo gateway is addressable.
        self.replica_id = replica_id or f"gw-{os.getpid()}"
        #: True while a graceful SIGTERM drain is in progress: new
        #: submissions answer 503, /readyz reports not-ready, and
        #: in-flight plans run to completion (gateway/fleet.py)
        self.draining = False
        self._requested_port = int(port)
        self._owns_executor = executor is None
        self.executor = executor or PlanExecutor(
            max_concurrent=max_concurrent,
            queue_depth=queue_depth,
            journal_dir=journal_dir,
            report_root=report_root,
            max_attempts=max_attempts,
            name="gateway",
        )
        self._recover = recover
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        #: plan_id -> PlanHandle, retained ONLY when the executor has
        #: no journal (the handle is then the sole route to a
        #: finished plan's statistics). With a journal, nothing is
        #: retained here: the journal is the durable record and a
        #: held handle would pin every completed plan's result (and
        #: its whole PipelineBuilder) for the server's lifetime.
        self._handles: Dict[str, Any] = {}
        self.recovery: Optional[Dict[str, Any]] = None
        #: the serving hot path's back end (serve/multiplex.py's
        #: MultiplexedService — or any service whose predict_window
        #: takes tenant=): attached by the operator, NOT owned; its
        #: start/stop lifecycle stays with whoever built it. None
        #: (the default) keeps the gateway the pure plan front door
        #: and POST /predict answers 503.
        self.predict_service = predict_service
        #: idempotency replay cache for /predict: key -> (body sha,
        #: status code, payload). Only successful answers are cached —
        #: a shed or error response must stay retryable under the
        #: same key (the /plans convention: the key is not burned).
        self._predict_cache: Dict[str, Tuple[str, int, Dict[str, Any]]] = {}
        self._predict_cache_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Recover the journal, start the executor workers, bind and
        serve. Returns (host, port) — port is the bound one when an
        ephemeral 0 was requested."""
        self.executor.start()
        if self._recover and self.executor.journal is not None:
            # resumed handles are NOT copied into _handles: the
            # journal (which recovery just replayed) serves their
            # status and outcome
            self.recovery = self.executor.recover()
        server = self

        class _Handler(_GatewayHandler):
            gateway = server

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="eeg-tpu-gateway-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("gateway serving on %s:%d", self.host, self.port)
        return self.host, self.port

    @property
    def port(self) -> int:
        if self._httpd is None:
            return self._requested_port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Stop accepting, shut the HTTP loop down, and (when the
        gateway owns its executor) close it — queued journaled plans
        stay 'submitted' for the next recover(), exactly like a
        direct executor close."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
            self._thread = None
        if self._owns_executor:
            self.executor.close(join_timeout_s=join_timeout_s)

    def __enter__(self) -> "GatewayServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the endpoint bodies (HTTP-free, directly testable) --------------

    def submit_query(
        self,
        raw_body: str,
        deadline_s: Optional[float] = None,
        idempotency_key: Optional[str] = None,
        client: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        from ..pipeline.builder import decode_percent_query

        trace_id = mint_trace_id(trace_id)
        if self.draining:
            return 503, {
                "error": f"replica {self.replica_id} is draining; "
                f"submit to a peer",
                "draining": True,
                "replica": self.replica_id,
            }
        try:
            query = decode_percent_query(raw_body.strip())
        except ValueError as e:
            return 400, {"error": str(e)}
        if not query:
            return 400, {"error": "empty request body; POST the query string"}
        # replica names the trace segment file (obs/events.py) even
        # for a solo (lease-less) gateway
        gateway_block = {"via": "http", "replica": self.replica_id}
        if idempotency_key:
            gateway_block["idempotency_key"] = idempotency_key
        if client:
            gateway_block["client"] = client
        try:
            handle = self.executor.submit(
                query,
                deadline_s=deadline_s,
                idempotency_key=idempotency_key,
                gateway=gateway_block,
                trace_id=trace_id,
            )
        except PlanShedError as e:
            # backpressure, with the evidence and the journaled id —
            # the client backs off and retries (the idempotency key
            # was deliberately not burned)
            return 429, {
                "error": str(e), "shed": True, "plan_id": e.plan_id,
            }
        except ServiceClosedError as e:
            return 503, {"error": str(e)}
        except IdempotencyConflictError as e:
            # key reused with a different body: neither replaying the
            # old outcome nor running the new body would be honest
            return 409, {"error": str(e), "idempotency_conflict": True}
        except PlanOwnedElsewhereError as e:
            # a keyed re-submit of a plan a live fleet peer is
            # executing: the original plan id IS the answer (the
            # exactly-once contract at fleet scope) — with the 307-
            # style owner hint so the client can follow the plan there
            status = self.executor.status(e.plan_id) or {}
            return 200, {
                "plan_id": e.plan_id,
                "state": status.get("state", "submitted"),
                "idempotent_replay": True,
                "owner": e.holder,
                "replica": self.replica_id,
                "trace_id": (
                    self._journaled_trace_id(e.plan_id) or trace_id
                ),
            }
        except ValueError as e:
            # PlanValidationError included: the query is the bug
            return 400, {"error": str(e)}
        replayed = bool(getattr(handle, "replayed", False))
        if not replayed and self.executor.journal is None:
            with self._lock:
                self._handles[handle.plan_id] = handle
        if replayed:
            # a keyed replay continues the ORIGINAL submission's trace
            # — the journaled id, not the one this retry minted
            trace_id = self._journaled_trace_id(handle.plan_id) or trace_id
        return (200 if replayed else 201), {
            "plan_id": handle.plan_id,
            "state": handle.state,
            "idempotent_replay": replayed,
            "trace_id": trace_id,
        }

    def _journaled_trace_id(self, plan_id: str) -> Optional[str]:
        journal = self.executor.journal
        if journal is None:
            return None
        entry = journal.entry(plan_id)
        if entry is None:
            return None
        return (entry.get("meta") or {}).get("trace_id")

    def predict_payload(
        self,
        raw_body: str,
        idempotency_key: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        """The serving hot path: one tenant-keyed prediction request
        against the attached multiplexed service.

        Body: ``{"tenant": str, "window": [[...]] (int16 raw samples,
        channels x window_len), "resolutions": [...], "deadline_s":
        float?}``. An ``X-Idempotency-Key`` replays the cached answer
        byte-identically (409 when the key is reused with a different
        body); a shed maps to 429 carrying the admission queue's
        structured per-tenant evidence."""
        import hashlib

        import numpy as np

        trace_id = mint_trace_id(trace_id)
        if self.predict_service is None:
            return 503, {
                "error": "no prediction service attached to this "
                "gateway (predict_service=)",
            }
        body_sha = hashlib.sha256(raw_body.encode()).hexdigest()
        if idempotency_key:
            with self._predict_cache_lock:
                cached = self._predict_cache.get(idempotency_key)
            if cached is not None:
                prior_sha, code, payload = cached
                if prior_sha != body_sha:
                    return 409, {
                        "error": (
                            f"idempotency key {idempotency_key!r} was "
                            f"already used with a different request "
                            f"body"
                        ),
                        "idempotency_conflict": True,
                    }
                replay = dict(payload)
                replay["idempotent_replay"] = True
                return code, replay
        try:
            request = json.loads(raw_body)
        except ValueError as e:
            return 400, {"error": f"request body is not JSON: {e}"}
        if not isinstance(request, dict):
            return 400, {"error": "request body must be a JSON object"}
        tenant = request.get("tenant")
        if not isinstance(tenant, str) or not tenant:
            return 400, {"error": "tenant must be a non-empty string"}
        deadline_s = request.get("deadline_s")
        if deadline_s is not None and not isinstance(
            deadline_s, (int, float)
        ):
            return 400, {"error": "deadline_s must be a number"}
        try:
            window = np.asarray(request["window"], dtype=np.int16)
            resolutions = np.asarray(
                request["resolutions"], dtype=np.float32
            )
        except KeyError as e:
            return 400, {"error": f"missing field {e.args[0]!r}"}
        except (TypeError, ValueError) as e:
            return 400, {"error": f"malformed window/resolutions: {e}"}
        try:
            result = self.predict_service.predict_window(
                window, resolutions, tenant=tenant,
                deadline_s=deadline_s,
            )
        except ShedError as e:
            # per-tenant backpressure, with the admission queue's
            # structured evidence (tenant depth + quota + oldest-age)
            # in the body — NOT cached: the retry must get a fresh
            # admission attempt under the same key
            return 429, {
                "error": str(e),
                "shed": True,
                "tenant": tenant,
                "evidence": e.evidence,
            }
        except (ServiceClosedError, ServiceWedgedError) as e:
            return 503, {"error": str(e), "tenant": tenant}
        except ValueError as e:
            # unknown tenant / wrong window geometry: the request is
            # the bug
            return 400, {"error": str(e), "tenant": tenant}
        except ServeError as e:
            # deadline-exceeded and exhausted-retry outcomes: the
            # request was admitted but could not be answered in budget
            return 504, {
                "error": str(e),
                "tenant": tenant,
                "failed": True,
            }
        payload = {
            "tenant": tenant,
            "prediction": float(result.prediction),
            "margin": (
                None if result.margin is None
                else float(result.margin)
            ),
            "latency_ms": round(result.latency_s * 1e3, 3),
            "batch_size": result.batch_size,
            "attempts": result.attempts,
            "idempotent_replay": False,
            # cached with the payload, so a keyed replay echoes the
            # ORIGINAL request's trace id (byte-identical answer)
            "trace_id": trace_id,
        }
        if idempotency_key:
            with self._predict_cache_lock:
                if len(self._predict_cache) >= _PREDICT_CACHE_LIMIT:
                    # bounded FIFO: drop the oldest key (dicts
                    # preserve insertion order)
                    self._predict_cache.pop(
                        next(iter(self._predict_cache))
                    )
                self._predict_cache[idempotency_key] = (
                    body_sha, 200, payload,
                )
        return 200, payload

    def _lease_owner(self, plan_id: str) -> Optional[str]:
        """The lease-holding replica's id when it is NOT this one —
        the peer-ownership hint for status/list payloads."""
        leases = self.executor.leases
        if leases is None:
            return None
        info = leases.holder_info(plan_id)
        if info is None or info["holder"] == self.replica_id:
            return None
        return info["holder"]

    def status_payload(self, plan_id: str) -> Tuple[int, Dict[str, Any]]:
        status = self.executor.status(plan_id)
        if status is None:
            return 404, {"error": f"unknown plan {plan_id}"}
        owner = self._lease_owner(plan_id)
        if owner is not None:
            # 307-style hint: any replica answers from the shared
            # journal, but THIS plan's live state machine (running /
            # attempt history) is on the lease holder
            status = dict(status)
            status["owner"] = owner
        return 200, status

    def report_payload(self, plan_id: str) -> Tuple[int, Dict[str, Any]]:
        """The finished plan's artifacts: statistics text (journal
        first — it survives restarts — the live handle as fallback),
        the terminal error if it failed, and the per-plan
        run_report.json when one was written."""
        status = self.executor.status(plan_id)
        if status is None:
            return 404, {"error": f"unknown plan {plan_id}"}
        if status["state"] not in ("completed", "failed", "cancelled"):
            return 409, {
                "error": f"plan {plan_id} is {status['state']}; "
                f"not terminal yet",
                "state": status["state"],
            }
        payload: Dict[str, Any] = {
            "plan_id": plan_id,
            "state": status["state"],
            "attempts": status.get("attempts", 0),
            "statistics": None,
            "statistics_sha256": status.get("statistics_sha256"),
            "error": status.get("error"),
            "run_report": None,
        }
        journal = self.executor.journal
        entry = journal.entry(plan_id) if journal is not None else None
        if entry is not None:
            payload["statistics"] = entry.get("statistics")
            payload["statistics_sha256"] = entry.get("statistics_sha256")
            payload["error"] = entry.get("error", payload["error"])
        if payload["statistics"] is None:
            # journal-less gateways retain their own handles; a
            # journaled gateway whose completion WRITE degraded falls
            # back to the executor's live ticket — kept precisely
            # because the journal lost the outcome
            handle = (
                self._handles.get(plan_id)
                or self.executor.handle(plan_id)
            )
            if handle is not None and handle.done:
                try:
                    import hashlib

                    text = str(handle.result(timeout=0).statistics)
                    payload["statistics"] = text
                    payload["statistics_sha256"] = hashlib.sha256(
                        text.encode()
                    ).hexdigest()
                except Exception as e:
                    payload["error"] = payload["error"] or (
                        f"{type(e).__name__}: {e}"
                    )
        report_dir = status.get("report_dir")
        if report_dir:
            try:
                with open(
                    os.path.join(report_dir, "run_report.json")
                ) as f:
                    payload["run_report"] = json.load(f)
            except (OSError, ValueError):
                pass
        return 200, payload

    def cancel_payload(self, plan_id: str) -> Tuple[int, Dict[str, Any]]:
        status = self.executor.status(plan_id)
        if status is None:
            return 404, {"error": f"unknown plan {plan_id}"}
        if self.executor.cancel(plan_id):
            return 200, {"plan_id": plan_id, "cancelled": True}
        return 409, {
            "plan_id": plan_id,
            "cancelled": False,
            "state": self.executor.status(plan_id)["state"],
            "error": "plan is not queued (already running or terminal)",
        }

    def list_payload(self) -> Tuple[int, Dict[str, Any]]:
        plans: Dict[str, Dict[str, Any]] = {}
        journal = self.executor.journal
        if journal is not None:
            for entry in journal.entries():
                meta = entry.get("meta") or {}
                plans[entry["plan_id"]] = {
                    "plan_id": entry["plan_id"],
                    "state": (
                        "cancelled" if meta.get("cancelled")
                        else entry.get("state")
                    ),
                    "attempts": int(entry.get("attempts", 0) or 0),
                    "query": entry.get("query", ""),
                }
        # live tickets override journal snapshots (a 'submitted'
        # record whose plan is mid-run shows as running)
        live = set(self.executor.live_ids())
        live.update(self._handles)
        for plan_id in live:
            status = self.executor.status(plan_id)
            if status is not None:
                plans[plan_id] = {
                    k: status.get(k)
                    for k in ("plan_id", "state", "attempts", "query")
                }
        # peer-aware: a 'submitted' record another replica lease-holds
        # is IN FLIGHT over there, not waiting — say so (and name the
        # holder) instead of letting the journal snapshot read as idle
        if self.executor.leases is not None:
            for plan_id, row in plans.items():
                owner = self._lease_owner(plan_id)
                if owner is not None:
                    row["owner"] = owner
        return 200, {"plans": [plans[k] for k in sorted(plans)]}

    def stats_payload(self) -> Tuple[int, Dict[str, Any]]:
        counters = obs.metrics.snapshot()["counters"]
        payload = {
            "dedup": dedup_mod.stats(),
            "queue_depth": len(self.executor.queue),
            "scheduler": {
                k: v for k, v in sorted(counters.items())
                if k.startswith("scheduler.")
            },
        }
        if self.predict_service is not None:
            # the serving block, per-tenant attribution included
            # (serve/multiplex.py stats_block; tools/plan_admin.py
            # --tenant filters it client-side)
            payload["serve"] = self.predict_service.stats_block()
        if self.executor.leases is not None:
            from ..scheduler import lease as lease_mod

            payload["fleet"] = {
                "replica": self.replica_id,
                "draining": self.draining,
                "held_leases": len(
                    self.executor.leases.held_plan_leases()
                ),
                "devices_held": (
                    self.executor.leases.held_device_ordinals()
                ),
                **lease_mod.stats(),
            }
            placement = getattr(self.executor, "placement", None)
            if placement is not None:
                payload["fleet"]["device_pool"] = placement.health()
        return 200, payload

    def metrics_payload(self) -> Tuple[int, str]:
        """The Prometheus text exposition for this replica
        (obs/metrics_export.py): every ``obs.metrics`` counter,
        the serve latency histograms (global + per-tenant labels),
        lease counters, queue depth, and the build-info series naming
        the replica. Deterministic ordering — the fleet aggregator
        (tools/fleet_top.py) merges N replicas' histograms exactly."""
        from ..obs import metrics_export

        snap = obs.metrics.snapshot()
        counters = dict(snap["counters"])
        gauges = dict(snap["gauges"])
        gauges["gateway.queue_depth"] = len(self.executor.queue)
        histograms = []
        if self.predict_service is not None:
            batcher = self.predict_service.batcher
            histograms.append(
                ("serve_request_latency_ms", {}, batcher.histogram_snapshot())
            )
            for tenant, hist in sorted(
                batcher.tenant_histogram_snapshot().items()
            ):
                histograms.append(
                    ("serve_request_latency_ms", {"tenant": tenant}, hist)
                )
        if self.executor.leases is not None:
            from ..scheduler import lease as lease_mod

            for key, value in lease_mod.stats().items():
                counters[f"lease.{key}"] = value
            gauges["fleet.held_leases"] = len(
                self.executor.leases.held_plan_leases()
            )
            gauges["fleet.devices_held"] = len(
                self.executor.leases.held_device_ordinals()
            )
            gauges["fleet.draining"] = int(self.draining)
            placement = getattr(self.executor, "placement", None)
            if placement is not None:
                health = placement.health()
                gauges["fleet.devices_free"] = health["free"]
                gauges["fleet.plans_waiting_placement"] = (
                    health["waiting"]
                )
        text = metrics_export.render(
            counters=counters,
            histograms=histograms,
            gauges=gauges,
            info={"replica": self.replica_id},
        )
        return 200, text

    def health_payload(self) -> Tuple[int, Dict[str, Any]]:
        """LIVENESS only — the process answers. Deliberately touches
        no disk: a replica with a read-only journal is alive (don't
        restart it into a crash loop) but not READY (don't route plans
        at it) — that split is exactly why /readyz exists."""
        return 200, {
            "ok": True,
            "replica": self.replica_id,
            "queued": len(self.executor.queue),
            "journal": self.executor.journal is not None,
        }

    def ready_payload(self) -> Tuple[int, Dict[str, Any]]:
        """READINESS: may this replica be routed new plans? Checks
        what accepting a plan actually needs — a writable journal
        directory (the write-ahead record and the lease claim both
        land there; accept-and-degrade on a read-only journal would
        silently trade away the crash-only contract) and an executor
        that is started, not closed, and not draining."""
        reasons = []
        journal = self.executor.journal
        if journal is not None:
            probe = os.path.join(
                journal.directory,
                f".readyz-{self.replica_id}-{os.getpid()}",
            )
            try:
                fd = os.open(
                    probe, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
                os.close(fd)
                os.unlink(probe)
            except OSError as e:
                reasons.append(
                    f"journal dir {journal.directory} is not "
                    f"writable ({type(e).__name__}: {e})"
                )
        if self.draining:
            reasons.append("draining (SIGTERM received)")
        if self.executor._stop.is_set():
            reasons.append("executor is closed")
        elif not self.executor._started:
            reasons.append("executor workers not started")
        placement = getattr(self.executor, "placement", None)
        if placement is not None:
            # device-pool health: plans are waiting on devices, the
            # fleet has zero claimable ordinals, and THIS replica
            # holds none of the held ones — new plans routed here
            # would only deepen the wait; a load balancer should
            # prefer the replicas actually holding devices
            try:
                health = placement.health()
            except Exception:  # pragma: no cover - observer only
                health = None
            if (
                health is not None
                and health["waiting"] > 0
                and health["free"] == 0
                and not health["held"]
            ):
                reasons.append(
                    f"device pool exhausted: 0 of {health['size']} "
                    f"ordinals claimable, none held here, "
                    f"{health['waiting']} plan(s) waiting (oldest: "
                    f"{health['oldest_waiting']})"
                )
        payload = {
            "ready": not reasons,
            "replica": self.replica_id,
            "queued": len(self.executor.queue),
            "capacity": self.executor.max_concurrent,
        }
        if reasons:
            payload["reasons"] = reasons
            return 503, payload
        return 200, payload


class _GatewayHandler(BaseHTTPRequestHandler):
    """Routes HTTP onto the gateway's endpoint bodies; every response
    is one JSON object."""

    #: bound by GatewayServer.start()'s subclass
    gateway: GatewayServer = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"

    # -- plumbing --------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet by default
        logger.debug("gateway http: " + fmt, *args)

    def _send(self, code: int, payload: Dict[str, Any]) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(
        self, code: int, text: str, content_type: str,
    ) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> str:
        length = int(self.headers.get("Content-Length", "0") or 0)
        return self.rfile.read(length).decode("utf-8", "replace")

    # -- methods ---------------------------------------------------------

    def do_POST(self) -> None:
        if self.path.rstrip("/") == "/predict":
            code, payload = self.gateway.predict_payload(
                self._body(),
                idempotency_key=self.headers.get("X-Idempotency-Key"),
                trace_id=self.headers.get("X-Trace-Id"),
            )
            self._send(code, payload)
            return
        if self.path.rstrip("/") != "/plans":
            self._send(404, {"error": f"no such endpoint {self.path}"})
            return
        deadline_s: Optional[float] = None
        raw_deadline = self.headers.get("X-Plan-Deadline-S")
        if raw_deadline:
            try:
                deadline_s = float(raw_deadline)
            except ValueError:
                self._send(400, {
                    "error": f"X-Plan-Deadline-S must be a float, got "
                    f"{raw_deadline!r}"
                })
                return
        code, payload = self.gateway.submit_query(
            self._body(),
            deadline_s=deadline_s,
            idempotency_key=self.headers.get("X-Idempotency-Key"),
            client=self.client_address[0],
            trace_id=self.headers.get("X-Trace-Id"),
        )
        self._send(code, payload)

    def do_GET(self) -> None:
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._send(*self.gateway.health_payload())
            return
        if path == "/readyz":
            self._send(*self.gateway.ready_payload())
            return
        if path == "/stats":
            self._send(*self.gateway.stats_payload())
            return
        if path == "/metrics":
            from ..obs import metrics_export

            code, text = self.gateway.metrics_payload()
            self._send_text(code, text, metrics_export.CONTENT_TYPE)
            return
        if path.rstrip("/") == "/plans":
            self._send(*self.gateway.list_payload())
            return
        match = _PLAN_PATH.match(path)
        if match is None:
            self._send(404, {"error": f"no such endpoint {path}"})
            return
        plan_id, want_report = match.group(1), match.group(2)
        if want_report:
            self._send(*self.gateway.report_payload(plan_id))
        else:
            self._send(*self.gateway.status_payload(plan_id))

    def do_DELETE(self) -> None:
        match = _PLAN_PATH.match(self.path)
        if match is None or match.group(2):
            self._send(404, {"error": f"no such endpoint {self.path}"})
            return
        self._send(*self.gateway.cancel_payload(match.group(1)))
