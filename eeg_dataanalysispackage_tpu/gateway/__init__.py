"""The networked plan service: an HTTP front door over the
multi-tenant :class:`~eeg_dataanalysispackage_tpu.scheduler.PlanExecutor`.

ROADMAP item 1 — the "millions of users" front door. One thin,
dependency-free HTTP server (stdlib ``ThreadingHTTPServer``) exposes
the executor's whole contract over loopback/LAN:

- ``POST /plans``            — submit a query string, get a plan id
  (idempotent under the ``X-Idempotency-Key`` header; shed-with-
  evidence becomes HTTP 429);
- ``GET /plans/<id>``        — queued/running/terminal status with the
  attempt history;
- ``GET /plans/<id>/report`` — the finished statistics text + the
  plan's ``run_report.json``;
- ``DELETE /plans/<id>``     — cancel-if-queued;
- ``GET /plans`` / ``GET /stats`` / ``GET /healthz`` — the operator
  surface (tools/plan_admin.py).

The write-ahead journal already makes a killed server resumable:
:class:`GatewayServer` runs ``recover()`` at startup, and submissions
carry client idempotency keys journaled with the plan record — a
retried submit after a crash or timeout returns the original plan id
instead of double-running. Cross-tenant plan-prefix dedup
(scheduler/dedup.py) runs underneath, so tenants whose plans share an
ingest+featurize prefix compute it once.

``gateway/fleet.py`` replicates the front door (ROADMAP item 4): N
:class:`FleetReplica` processes over ONE shared journal directory,
lease-claiming plans (scheduler/lease.py) so any replica accepts, any
replica finishes, and a SIGKILLed replica's in-flight plans complete
on a surviving peer under their original ids with byte-identical
statistics. ``/readyz`` is the fleet's routability check (writable
journal + accepting executor, vs ``/healthz``'s pure liveness), and
SIGTERM drains gracefully — queued leases released for immediate peer
takeover, in-flight plans finished.

``python -m eeg_dataanalysispackage_tpu.gateway`` serves from the
command line (``--port`` / ``EEG_TPU_GATEWAY_PORT``; ``--fleet
--replica-id`` for a fleet member); see README "Plan service" for
curl examples.
"""

from .fleet import FleetReplica  # noqa: F401
from .server import GatewayServer  # noqa: F401
