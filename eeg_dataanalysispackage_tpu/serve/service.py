"""The resident P300 inference service.

Loads a saved classifier ONCE, compiles the fused serving program
(serve/engine.py) once, and serves prediction requests through the
micro-batching front end (serve/batcher.py) until drained. The
reference has no serving story at all — every query is a cold Spark
job; this is the ROADMAP "millions of users" subsystem, built
robustness-first: a request admitted here resolves (answer, shed,
deadline-exceeded, or fail-fast on a wedge) — it never hangs its
caller and the queue never grows without bound.

Typical use::

    with InferenceService.from_saved("logreg", "/models/p300") as svc:
        result = svc.predict_window(window_i16, resolutions)
        # or async:
        fut = svc.submit(window_i16, resolutions, deadline_s=0.5)
        ...
        result = fut.result()

Closing the context drains gracefully: in-flight requests complete,
new ones are rejected with :class:`serve.batcher.ServiceClosedError`.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import List, Optional, Sequence

import numpy as np

from . import batcher as batcher_mod
from . import engine as engine_mod
from . import lifecycle as lifecycle_mod
from ..io import deadline as deadline_mod
from ..models import registry as clf_registry
from ..obs import events
from ..obs import metrics_export
from ..utils import constants

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs; all bounded, all with shed-don't-stall defaults.

    ``max_batch`` is also the compiled program's static capacity —
    every batch size from 1 to it reuses one executable.
    """

    max_batch: int = 64
    queue_depth: int = 256
    coalesce_s: float = 0.002
    #: bounded coalescing window (microseconds): with a request
    #: already waiting, the batcher holds dispatch up to this long for
    #: queued compatible requests to FILL the bucket
    #: (serve/batcher.py). 0 (the default) is byte-identically
    #: yesterday's behavior; under closed-loop load at concurrency 16
    #: the default dispatch races the submitters and mean batch size
    #: settles near 2-3 — a few hundred microseconds here trades that
    #: latency for full buckets (serve_flush_us= /
    #: EEG_TPU_SERVE_FLUSH_US; measured per level in serve_bench's
    #: mean_batch_size).
    flush_us: int = 0
    #: per-tenant admission budget for multiplexed services
    #: (serve/multiplex.py): at most this many of one tenant's
    #: requests queued at once, so one noisy tenant sheds against its
    #: OWN budget instead of starving the shared queue. None (the
    #: default, and the only meaningful value for single-model
    #: services) disables the per-tenant check.
    tenant_quota: Optional[int] = None
    default_deadline_s: float = 2.0
    max_attempts: int = 3
    retry_backoff_s: float = 0.05
    watchdog_s: float = 5.0
    drain_timeout_s: float = 10.0
    #: the latency objective (milliseconds) the SLO block scores
    #: attainment against — the fraction of completed requests whose
    #: latency landed at or under this bound (serve_slo_ms= /
    #: computed from the fixed-bucket histogram, so two replicas'
    #: attainment merges exactly)
    slo_latency_ms: float = 50.0
    #: the availability objective: completed / admitted (sheds,
    #: failures, and deadline misses all count against it). The error
    #: budget is 1 - this target; burn rate 1.0 means spending the
    #: budget exactly as fast as the objective allows.
    slo_availability_target: float = 0.999


def _percentile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_values:
        return 0.0
    idx = min(
        len(sorted_values) - 1,
        max(0, int(round(q / 100.0 * (len(sorted_values) - 1)))),
    )
    return sorted_values[idx]


class InferenceService:
    """One loaded model + one micro-batching loop + one watchdog."""

    def __init__(
        self,
        classifier,
        wavelet_index: int = 8,
        n_channels: int = constants.USED_CHANNELS,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
        config: Optional[ServeConfig] = None,
        host_extractor=None,
        precision: str = "f32",
        engine_rung: str = "auto",
        lifecycle: Optional[lifecycle_mod.LifecycleConfig] = None,
    ):
        self.config = config or ServeConfig()
        self.engine = engine_mod.ServingEngine(
            classifier,
            wavelet_index=wavelet_index,
            n_channels=n_channels,
            pre=pre,
            post=post,
            capacity=self.config.max_batch,
            host_extractor=host_extractor,
            precision=precision,
            engine_rung=engine_rung,
        )
        #: the model lifecycle manager (serve/lifecycle.py): streaming
        #: partial-fit over labeled feedback, shadow-scored hot swap,
        #: drift detection — None unless the service was built with a
        #: LifecycleConfig (``adapt=true`` in pipeline terms)
        self.lifecycle = (
            None if lifecycle is None
            else lifecycle_mod.LifecycleManager(self.engine, lifecycle)
        )
        self.batcher = batcher_mod.MicroBatcher(
            self.engine.execute,
            max_batch=self.config.max_batch,
            queue_depth=self.config.queue_depth,
            coalesce_s=self.config.coalesce_s,
            flush_us=self.config.flush_us,
            max_attempts=self.config.max_attempts,
            retry_backoff_s=self.config.retry_backoff_s,
            watchdog_s=self.config.watchdog_s,
        )
        self._accepting = False
        self._started = False
        self._drained_cleanly: Optional[bool] = None
        self._lock = threading.Lock()

    @classmethod
    def from_saved(
        cls,
        classifier_name: str,
        model_path: str,
        warmup: bool = True,
        **kwargs,
    ) -> "InferenceService":
        """Load ``classifier_name`` from ``model_path`` (local path or
        remote URI — io/modelfiles routing, with its retry + circuit
        machinery) exactly once, build the service around it, and
        (by default) compile the serving program before any traffic.
        """
        classifier = clf_registry.create(classifier_name)
        classifier.load(model_path)
        service = cls(classifier, **kwargs)
        if warmup:
            service.engine.warmup()
        return service

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "InferenceService":
        # compile before traffic (idempotent — from_saved already did
        # it): a cold XLA compile must happen HERE, not inside the
        # batcher where the watchdog would read a long one as a wedge
        self.engine.warmup()
        with self._lock:
            if self._started:
                return self
            self.batcher.start()
            if self.lifecycle is not None:
                self.lifecycle.start()
            self._accepting = True
            self._started = True
        events.event("serve.started")
        logger.info(
            "inference service started (%s, max_batch=%d, "
            "queue_depth=%d)", self.engine.mode,
            self.config.max_batch, self.config.queue_depth,
        )
        return self

    def stop(self, drain: bool = True) -> bool:
        """Shut down. With ``drain`` (default) the service stops
        admitting, lets everything already admitted complete (bounded
        by ``drain_timeout_s``), then stops the threads. Returns True
        iff the drain completed cleanly."""
        with self._lock:
            if not self._started:
                return True
            self._accepting = False
        drained = True
        if drain:
            drained = self.batcher.wait_idle(self.config.drain_timeout_s)
            if not drained:
                logger.warning(
                    "serve drain incomplete after %.1fs (%d queued, "
                    "wedged=%s)", self.config.drain_timeout_s,
                    len(self.batcher.queue),
                    self.batcher.wedged.is_set(),
                )
        self.batcher.stop()
        # anything still pending after a failed (or skipped) drain
        # resolves NOW — the no-hanging-caller contract survives
        # shutdown too. In-flight requests may race their own batch's
        # completion; resolve-once semantics make that benign.
        with self.batcher._in_flight_lock:
            in_flight = list(self.batcher._in_flight)
        for req in in_flight + self.batcher.queue.drain_pending():
            req.future.fail(batcher_mod.ServiceClosedError(
                "service stopped before the request could complete"
            ))
        if self.lifecycle is not None:
            # a clean drain also flushes queued feedback (the last
            # trials of a session still adapt); stop(drain=False) and
            # a failed drain skip straight to shutdown — an abort must
            # not train (or promote) its way through the backlog, and
            # the adapter must not outlive the service it feeds
            self.lifecycle.close(
                flush=drain and drained,
                timeout_s=self.config.drain_timeout_s,
            )
        with self._lock:
            self._started = False
        self._drained_cleanly = drained
        events.event("serve.stopped", drained=drained)
        return drained

    def __enter__(self) -> "InferenceService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)

    # -- request path ---------------------------------------------------

    def submit(
        self,
        window: np.ndarray,
        resolutions: np.ndarray,
        deadline_s: Optional[float] = None,
        block_s: float = 0.0,
        label: Optional[float] = None,
    ) -> batcher_mod.ServeFuture:
        """Admit one request; returns its future.

        ``label`` (requires a lifecycle-enabled service) is the
        request's known true target — the speller's post-trial ground
        truth — forwarded as feedback to the lifecycle manager
        (serve/lifecycle.py) for streaming partial-fit and shadow
        scoring; when the label only becomes known later, call
        :meth:`feedback` instead.

        Raises :class:`ShedError` when the bounded queue is full (pass
        ``block_s`` to cooperate with backpressure instead),
        :class:`ServiceClosedError` when draining/stopped, and
        :class:`ServiceWedgedError` when the watchdog has declared the
        batcher wedged — all synchronously, with evidence: admission
        failures are loud and immediate, never a queued request that
        nobody will ever serve.
        """
        if label is not None and self.lifecycle is None:
            raise ValueError(
                "submit(label=) needs a lifecycle-enabled service "
                "(adapt=true); this one has no adapter to feed"
            )
        self.batcher._count("submitted")
        if not self._accepting:
            self.batcher._count("rejected_closed")
            raise batcher_mod.ServiceClosedError(
                "service is not accepting requests "
                "(draining or stopped)"
            )
        if self.batcher.wedged.is_set():
            self.batcher._count("rejected_wedged")
            raise batcher_mod.ServiceWedgedError(
                "service wedged (watchdog tripped); restart the "
                "service"
            )
        req = batcher_mod.Request(
            window=np.asarray(window),
            resolutions=np.asarray(resolutions, np.float32),
            deadline=deadline_mod.Deadline(
                deadline_s if deadline_s is not None
                else self.config.default_deadline_s
            ),
        )
        if not self.batcher.queue.offer(req, block_s=block_s):
            self.batcher._count("shed")
            events.event(
                "serve.shed", queue_depth=self.batcher.queue.depth
            )
            raise batcher_mod.ShedError(
                f"request shed by admission control: "
                f"{self.batcher.queue._last_shed_evidence}"
            )
        if not self._accepting:
            # stop() may have swept the queue between the accepting
            # check above and this offer landing — fail the future NOW
            # (resolve-once: a no-op if the drain actually served it)
            # so shutdown can never strand an admitted request
            if req.future.fail(batcher_mod.ServiceClosedError(
                "service stopped while the request was being admitted"
            )):
                self.batcher._count("rejected_closed")
        if label is not None:
            try:
                self.lifecycle.feedback(window, resolutions, label)
            except batcher_mod.ServiceClosedError:
                # stop() raced the admission between the accepting
                # check and this forward: the request itself was
                # admitted, so its future is still owed to the caller
                # — the label is dropped (the adapter is closing), not
                # the answer
                pass
        return req.future

    def feedback(
        self,
        window: np.ndarray,
        resolutions: np.ndarray,
        label: float,
    ) -> bool:
        """One labeled served outcome for the lifecycle manager — the
        speller's post-trial ground truth, the seizure line's
        confirmed annotation. Returns True when queued for the
        adapter (False = dropped with a counted reason); raises
        :class:`ServiceClosedError` once the service is draining or
        stopped, mirroring :meth:`submit`."""
        if self.lifecycle is None:
            raise ValueError(
                "feedback() needs a lifecycle-enabled service "
                "(adapt=true); this one has no adapter to feed"
            )
        if not self._accepting:
            raise batcher_mod.ServiceClosedError(
                "service is not accepting feedback "
                "(draining or stopped)"
            )
        return self.lifecycle.feedback(window, resolutions, label)

    def _result_timeout(self, budget: float) -> float:
        """Caller-side wait bound: the watchdog guarantees resolution;
        the slack only bounds the pathological late-detected wedge."""
        return budget + self.config.watchdog_s + 5.0

    def predict_window(
        self,
        window: np.ndarray,
        resolutions: np.ndarray,
        deadline_s: Optional[float] = None,
    ) -> batcher_mod.Result:
        """Blocking convenience: submit + wait within the deadline."""
        budget = (
            deadline_s if deadline_s is not None
            else self.config.default_deadline_s
        )
        fut = self.submit(window, resolutions, deadline_s=budget)
        return fut.result(timeout=self._result_timeout(budget))

    def predict_all(
        self,
        windows: Sequence[np.ndarray],
        resolutions,
        deadline_s: Optional[float] = None,
    ) -> List[batcher_mod.Result]:
        """Drive a whole epoch set through the service with submitter-
        side backpressure (blocking admission), collecting results in
        input order — the ``serve=`` pipeline mode's driver.

        ``resolutions`` is either one ``(n_channels,)`` vector shared
        by every window, or a per-window sequence of them (a mixed-
        resolution session; the batcher's coalescing key keeps each
        micro-batch homogeneous).
        """
        budget = (
            deadline_s if deadline_s is not None
            else self.config.default_deadline_s
        )
        res_arr = np.asarray(resolutions, dtype=np.float32)
        per_window = res_arr.ndim == 2
        if per_window and len(res_arr) != len(windows):
            raise ValueError(
                f"{len(res_arr)} resolution vectors for "
                f"{len(windows)} windows"
            )
        futures = []
        for i, w in enumerate(windows):
            futures.append(
                self.submit(
                    w, res_arr[i] if per_window else res_arr,
                    deadline_s=budget, block_s=budget,
                )
            )
        timeout = self._result_timeout(budget)
        return [f.result(timeout=timeout) for f in futures]

    # -- observability --------------------------------------------------

    def stats_block(self) -> dict:
        """The ``serve`` block for run reports and bench lines; safe
        to call on a live service (snapshot under the batcher lock)."""
        counters, lat = self.batcher.snapshot()
        lat.sort()
        return {
            "mode": self.engine.mode,
            "rung": self.engine.rung,
            # non-f32 serving attribution: the warmup gate's decision
            # (requested/used/max_abs_dev); None for f32 engines
            "precision": self.engine.precision_record,
            # mega-rung attribution: resolution + warmup parity gate
            # (ops/serve_mega.py); None when the rung was never a
            # candidate (schema-stable)
            "mega": self.engine.mega_record,
            "max_batch": self.config.max_batch,
            "queue_depth": self.config.queue_depth,
            "flush_us": self.config.flush_us,
            "requests": {
                "submitted": counters.get("submitted", 0),
                "completed": counters.get("completed", 0),
                "shed": counters.get("shed", 0),
                "deadline_exceeded": counters.get("deadline_exceeded", 0),
                "failed": counters.get("failed", 0),
                "retries": counters.get("retries", 0),
                "rejected_closed": counters.get("rejected_closed", 0),
                "rejected_wedged": counters.get("rejected_wedged", 0),
            },
            "batches": counters.get("batches", 0),
            "batch_failures": counters.get("batch_failures", 0),
            "mean_batch_size": round(
                counters.get("completed", 0)
                / max(1, counters.get("batches", 0)), 3
            ),
            "latency_ms": {
                "p50": round(_percentile(lat, 50.0) * 1e3, 3),
                "p99": round(_percentile(lat, 99.0) * 1e3, 3),
                "max": round((lat[-1] if lat else 0.0) * 1e3, 3),
                "n": len(lat),
            },
            "watchdog_trips": counters.get("watchdog_trips", 0),
            "wedged": self.batcher.wedged.is_set(),
            "drained_cleanly": self._drained_cleanly,
            # the service-wide SLO block (obs/metrics_export.py):
            # availability vs admitted traffic, latency-objective
            # attainment off the fixed-bucket histogram, and the
            # error-budget burn rate — per-tenant variants live in the
            # multiplexed service's tenants sub-block
            "slo": metrics_export.slo_block(
                self.batcher.histogram_snapshot(),
                {
                    "completed": counters.get("completed", 0),
                    "shed": counters.get("shed", 0),
                    "failed": counters.get("failed", 0),
                    "deadline_exceeded": counters.get(
                        "deadline_exceeded", 0
                    ),
                },
                objective_ms=self.config.slo_latency_ms,
                availability_target=self.config.slo_availability_target,
            ),
            # model lifecycle attribution (serve/lifecycle.py):
            # feedback/partial-fit counters, the candidate's shadow
            # window, gate decisions, swaps/rollbacks/drift — None for
            # services without a lifecycle manager (schema-stable)
            "lifecycle": (
                None if self.lifecycle is None
                else self.lifecycle.block()
            ),
        }
