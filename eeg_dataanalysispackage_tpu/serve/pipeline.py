"""``serve=true`` pipeline mode: drive a session through the service.

The batch pipeline's ``load_clf=`` mode answers "how does this saved
model score this session" with one big fused featurization; this mode
answers the same question through the ONLINE path — every kept epoch
becomes an individual request (raw int16 window bytes) submitted to a
resident :class:`serve.service.InferenceService`, micro-batched,
deadline-bounded, and admission-controlled. The statistics out the
other end are pinned bit-identical to the batch ``load_clf=`` run on
the same inputs (tests/test_serve.py) — the parity contract every
prior subsystem honored, now holding across the batch/online seam.

Query surface (README "Query-string reference")::

    serve=true&load_clf=logreg&load_name=/models/p300
        &fe=dwt-8-fused&info_file=...
        [&serve_deadline_ms=2000] [&serve_batch=64] [&serve_queue=256]

``faults=`` specs may target ``serve.request`` / ``serve.batch``; the
run then proves the no-wedge contract live (requests retry or fail
with evidence, the drain completes) and the run report's ``serve``
block records the outcome counters.
"""

from __future__ import annotations

import logging
import os
import re

import numpy as np

from . import engine as engine_mod
from . import service as service_mod
from ..epochs.extractor import BalanceState
from ..models import registry as clf_registry
from ..models import stats
from ..utils import java_compat

logger = logging.getLogger(__name__)

def _conflicting_keys(query_map) -> list:
    """Keys that actually ENABLE a conflicting mode — judged by the
    same conditions the batch path uses, so an explicit no-op like
    ``elastic=false`` or ``cv=1`` does not spuriously reject the run."""
    from ..models import population

    conflicts = [k for k in ("train_clf", "classifiers") if k in query_map]
    for flag in ("save_clf", "elastic"):
        if query_map.get(flag) == "true":
            conflicts.append(flag)
    if population.PopulationSpec.from_query_map(query_map).active:
        conflicts.append("cv=/seeds=/sweep=")
    return conflicts


def _int_knob(query_map, name: str, default: int) -> int:
    value = query_map.get(name, "")
    if not value:
        return default
    try:
        return int(value)
    except ValueError:
        raise ValueError(
            f"query parameter {name}= must be an integer, got {value!r}"
        )


def _float_knob(query_map, name: str, default: float) -> float:
    value = query_map.get(name, "")
    if not value:
        return default
    try:
        return float(value)
    except ValueError:
        raise ValueError(
            f"query parameter {name}= must be a number, got {value!r}"
        )


#: process default for the bounded batch-fill window (microseconds);
#: a per-run ``serve_flush_us=`` query value wins.
ENV_SERVE_FLUSH_US = "EEG_TPU_SERVE_FLUSH_US"


def default_flush_us() -> int:
    raw = os.environ.get(ENV_SERVE_FLUSH_US, "")
    if not raw:
        return 0
    try:
        return int(raw)
    except ValueError:
        logger.warning(
            "%s=%r is not an integer; using 0 (no flush window)",
            ENV_SERVE_FLUSH_US, raw,
        )
        return 0


def serve_config_from_query(query_map) -> service_mod.ServeConfig:
    tenant_quota = _int_knob(query_map, "serve_tenant_quota", 0)
    return service_mod.ServeConfig(
        max_batch=_int_knob(query_map, "serve_batch", 64),
        queue_depth=_int_knob(query_map, "serve_queue", 256),
        flush_us=_int_knob(
            query_map, "serve_flush_us", default_flush_us()
        ),
        # 0 / absent = no per-tenant budget (single-model services
        # never have one; serve/multiplex.py documents the knob)
        tenant_quota=tenant_quota if tenant_quota > 0 else None,
        default_deadline_s=_int_knob(
            query_map, "serve_deadline_ms", 2000
        ) / 1000.0,
        # the per-tenant SLO objectives the stats/metrics SLO block
        # scores against (obs/metrics_export.py)
        slo_latency_ms=_float_knob(query_map, "serve_slo_ms", 50.0),
        slo_availability_target=_float_knob(
            query_map, "serve_slo_availability", 0.999
        ),
    )


def parse_tenant_spec(spec: str) -> dict:
    """Parse a multi-tenant model spec into ``{tenant: (classifier,
    path)}``.

    The spec is the operator's one-line tenant registry —
    ``name=classifier@path`` entries joined by commas::

        alice=logreg@/models/alice,bob=logreg@/models/bob

    Order is preserved (the first tenant anchors the engine's
    geometry). Raises ``ValueError`` with the offending entry on any
    malformed piece — a fleet bootstrap must fail loudly, not serve a
    partial registry."""
    tenants = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        classifier_name, sep2, path = rest.partition("@")
        if not (name.strip() and sep and classifier_name.strip()
                and sep2 and path.strip()):
            raise ValueError(
                f"malformed tenant entry {entry!r}; expected "
                f"name=classifier@path"
            )
        name = name.strip()
        if name in tenants:
            raise ValueError(f"duplicate tenant {name!r} in spec")
        tenants[name] = (classifier_name.strip(), path.strip())
    if not tenants:
        raise ValueError(
            "tenant spec is empty; expected name=classifier@path[,...]"
        )
    return tenants


def load_tenants(spec: str) -> dict:
    """Load every tenant named by :func:`parse_tenant_spec` into
    ``{tenant: classifier}`` — the runtime registry a
    :class:`serve.multiplex.MultiplexedService` (or a running one's
    ``add_tenant``) is built from. Each model loads exactly once
    through the io/modelfiles routing."""
    loaded = {}
    for name, (classifier_name, path) in parse_tenant_spec(spec).items():
        classifier = clf_registry.create(classifier_name)
        classifier.load(path)
        loaded[name] = classifier
    return loaded


def lifecycle_config_from_query(
    query_map, cost_fp: float = 1.0, cost_fn: float = 1.0
):
    """The ``adapt=`` family -> a LifecycleConfig, or None when the
    run did not opt in (the lifecycle is strictly additive: without
    ``adapt=true`` the service is byte-identically the pre-lifecycle
    one)."""
    from . import lifecycle as lifecycle_mod

    if query_map.get("adapt") != "true":
        return None
    return lifecycle_mod.LifecycleConfig.from_query_map(
        query_map, cost_fp=cost_fp, cost_fn=cost_fn
    )


def _adapt_feedback(service, stage, requests, targets_arr) -> None:
    """Close the train/serve loop for one pipeline session: every
    served window's true target (the speller KNOWS it after the
    trial) feeds the lifecycle manager in submission order, then the
    adapter flushes — partial-fit chunks, shadow scoring, gate
    decisions, and (behind the gate) a promotion all happen here,
    AFTER the session's own predictions were served, so the run's
    statistics are untouched by its own adaptation (the promoted
    model serves the NEXT session; byte-identity pinned in
    tests/test_lifecycle.py)."""
    if service.lifecycle is None or not requests:
        return
    with stage("adapt", requests=len(requests)):
        for (window, resolutions), label in zip(requests, targets_arr):
            service.feedback(window, resolutions, float(label))
        service.lifecycle.flush(
            timeout_s=service.config.drain_timeout_s
        )


def run_serve(query_map, provider_factory, stage):
    """Execute one ``serve=true`` run.

    ``provider_factory`` builds the run's ``OfflineDataProvider``
    (the builder owns filesystem routing / worker knobs); ``stage`` is
    the builder's stage context factory (span + StageTimer). Returns
    ``(ClassificationStatistics, serve_block)``.
    """
    conflicts = _conflicting_keys(query_map)
    if conflicts:
        raise ValueError(
            f"serve=true is an inference mode; it cannot combine "
            f"with {', '.join(conflicts)}"
        )
    if "load_clf" not in query_map:
        raise ValueError(
            "serve=true requires load_clf= (the model to serve)"
        )
    if "load_name" not in query_map:
        raise ValueError("Classifier location not provided")
    fused_match = re.fullmatch(
        r"dwt-(\d+)-fused(-pallas|-block|-xla|-decode)?",
        query_map.get("fe", ""),
    )
    if fused_match is None:
        raise ValueError(
            "serve=true runs the fused bytes->features->predict "
            "program; fe= must be a dwt-<i>-fused form"
        )
    from ..ops import decode_ingest

    wavelet_index = int(fused_match.group(1))
    # precision=bf16/int8/int4 serve through the reduced-precision
    # feature path behind the engine's warmup accuracy gate
    # (serve/engine.py); the decision is recorded in the serve block's
    # ``precision`` entry
    precision = (
        query_map.get("precision")
        or os.environ.get("EEG_TPU_PRECISION")
        or "f32"
    )
    if precision not in decode_ingest.PRECISIONS:
        raise ValueError(
            f"precision= must be f32, bf16, int8, or int4, got "
            f"{precision!r}"
        )

    classifier = clf_registry.create(query_map["load_clf"])
    classifier.load(query_map["load_name"])
    # the recall-tuning margin knob (docs/serving.md); absent = the
    # model's own threshold, which is what the batch-parity pin runs
    threshold = resolve_serve_threshold(query_map, classifier)

    odp = provider_factory()
    config = serve_config_from_query(query_map)
    # the engine's geometry comes from the provider, not re-derived
    # from constants: a provider constructed with non-default pre/
    # post/channels must produce windows the engine accepts
    service = service_mod.InferenceService(
        classifier,
        wavelet_index=wavelet_index,
        n_channels=odp.n_channels,
        pre=odp.pre,
        post=odp.post,
        config=config,
        precision=precision,
        lifecycle=lifecycle_config_from_query(query_map),
    )

    # 1. ingest: parse the session into per-epoch raw windows (the
    # online analogue of the fused path's plan+stage step; the shared
    # BalanceState keeps cross-file retention identical to batch)
    balance = BalanceState()
    requests = []  # (window, resolutions)
    targets = []
    with stage("ingest", mode="serve"):
        for _rel, guessed, rec in odp.iter_recordings():
            windows, rec_targets, resolutions = (
                engine_mod.windows_from_recording(
                    rec, odp.channel_indices_for(rec), guessed,
                    pre=odp.pre, post=odp.post, balance=balance,
                )
            )
            requests.extend((w, resolutions) for w in windows)
            targets.append(rec_targets)
    targets_arr = (
        np.concatenate(targets) if targets else np.zeros(0, np.float64)
    )
    n = len(requests)

    # 2. serve: the resident service answers every epoch as an online
    # request — micro-batched, deadline-bounded, shed-don't-stall
    service.start()  # warms the compiled program before traffic
    try:
        with stage("serve", requests=n):
            # per-recording resolutions may differ; predict_all takes
            # the per-window vectors and the batcher's coalescing key
            # keeps each micro-batch homogeneous
            results = []
            if n:
                results = service.predict_all(
                    [r[0] for r in requests],
                    [r[1] for r in requests],
                )
        # 2b. adapt=true: the session's labeled outcomes feed the
        # lifecycle manager (streaming partial-fit + shadow-scored
        # swap + drift) after its predictions were served
        _adapt_feedback(service, stage, requests, targets_arr)
    finally:
        drained = service.stop(drain=True)

    predictions = np.array(
        [r.prediction for r in results], dtype=np.float64
    )

    # 3. statistics, the load_clf= way: evaluated over the seed-1
    # shuffled order (permutation-invariant sums, but byte-identical
    # construction keeps the parity contract auditable)
    with stage("test", classifier=query_map["load_clf"]):
        perm = java_compat.java_shuffle_indices(n, seed=1)
        statistics = stats.ClassificationStatistics.from_arrays(
            predictions[perm], targets_arr[perm],
            confusion_only=classifier.confusion_only_stats,
        )

    block = service.stats_block()
    block["requests"]["total_epochs"] = n
    block["drained_cleanly"] = drained
    if threshold is not None:
        block["serve_threshold"] = threshold
    logger.info(
        "served %d epochs: %d completed, %d shed, %d deadline-"
        "exceeded, %d failed (drained=%s)",
        n, block["requests"]["completed"], block["requests"]["shed"],
        block["requests"]["deadline_exceeded"],
        block["requests"]["failed"], drained,
    )
    return statistics, block


def resolve_serve_threshold(query_map, classifier):
    """``serve_threshold=<margin>``: the recall-tuning decision knob
    for seizure serving (docs/serving.md). Applied to the loaded
    linear model's margin threshold — a lower threshold trades false
    positives for recall without retraining. Linear family only: the
    other classifiers emit hard labels with no margin to re-threshold.
    Returns the float applied, or None when the knob is absent."""
    from ..models import linear as linear_mod

    value = query_map.get("serve_threshold", "")
    if not value:
        return None
    try:
        threshold = float(value)
    except ValueError:
        raise ValueError(
            f"serve_threshold= must be a float margin, got {value!r}"
        )
    if not isinstance(classifier, linear_mod._LinearClassifier):
        raise ValueError(
            "serve_threshold= re-thresholds a linear margin; "
            f"{type(classifier).__name__} has none"
        )
    classifier.margin_threshold = threshold
    return threshold


def run_serve_seizure(query_map, provider_factory, stage):
    """``task=seizure&serve=true``: stream continuous sliding windows
    through the resident service.

    The engine runs in host-extractor mode (serve/engine.py): the
    seizure subband features have no fused device twin, so every
    request takes the exact featurize+predict path the batch
    ``task=seizure&load_clf=`` run takes — which is what pins served
    statistics identical to the batch run (tests/test_seizure_
    pipeline.py). Windows are the SAME float64 scaled slices the
    batch epocher cuts (provider.sliding_batch_for), shipped with
    unit resolutions so the engine's scaling is exact. The
    ``serve_threshold=`` knob re-thresholds the linear margin for
    recall-tuned serving (with it set, statistics intentionally
    diverge from the default-threshold batch run).

    Returns ``(ClassificationStatistics, serve block, workload
    block)``.
    """
    from ..epochs.sliding import SlidingConfig
    from ..pipeline.builder import PipelineBuilder

    conflicts = _conflicting_keys(query_map)
    if conflicts:
        raise ValueError(
            f"serve=true is an inference mode; it cannot combine "
            f"with {', '.join(conflicts)}"
        )
    if "load_clf" not in query_map:
        raise ValueError(
            "serve=true requires load_clf= (the model to serve)"
        )
    if "load_name" not in query_map:
        raise ValueError("Classifier location not provided")
    fe_value = query_map.get("fe", "")
    if not fe_value:
        raise ValueError("Missing the feature extraction argument")
    if "-fused" in fe_value:
        raise ValueError(
            "task=seizure serves host-extracted features; fe= must be "
            "a registry form, not a -fused mode"
        )
    from ..features import registry as fe_registry

    window = int(query_map.get("window") or 512)
    stride = int(query_map.get("stride") or max(1, window // 2))
    overlap = float(query_map.get("label_overlap") or 0.5)
    slide_cfg = SlidingConfig(
        window=window, stride=stride, label_overlap=overlap
    )
    fe = fe_registry.create(fe_value)

    classifier = clf_registry.create(query_map["load_clf"])
    classifier.load(query_map["load_name"])
    threshold = resolve_serve_threshold(query_map, classifier)

    odp = provider_factory()
    config = serve_config_from_query(query_map)
    # the workload config parameterizes the engine's window length:
    # continuous windows have no prestimulus segment (pre=0)
    service = service_mod.InferenceService(
        classifier,
        n_channels=odp.n_channels,
        pre=0,
        post=window,
        config=config,
        host_extractor=fe,
        # lifecycle windows judge on the run's misclassification
        # costs (the explicit knobs; class_weight=balanced resolves
        # training weights, not scoring costs)
        lifecycle=lifecycle_config_from_query(
            query_map,
            cost_fp=float(query_map.get("cost_fp") or 1.0),
            cost_fn=float(query_map.get("cost_fn") or 1.0),
        ),
    )

    # 1. ingest: the SAME sliding batches the batch run cuts — float64
    # scaled windows, unit resolutions (scale-by-1.0 is exact, so the
    # served feature rows are byte-identical to the batch run's)
    requests = []
    targets = []
    unit_res = np.ones(odp.n_channels, dtype=np.float32)
    with stage("ingest", mode="serve", task="seizure"):
        for _rel, _guessed, rec in odp.iter_recordings():
            batch = odp.sliding_batch_for(rec, slide_cfg)
            requests.extend(
                (np.asarray(w), unit_res) for w in batch.epochs
            )
            targets.append(batch.targets)
    targets_arr = (
        np.concatenate(targets) if targets else np.zeros(0, np.float64)
    )
    n = len(requests)

    # 2. serve: micro-batched, deadline-bounded, shed-don't-stall —
    # the same front end the P300 service runs
    service.start()
    try:
        with stage("serve", requests=n, task="seizure"):
            results = []
            if n:
                results = service.predict_all(
                    [r[0] for r in requests],
                    [r[1] for r in requests],
                )
        _adapt_feedback(service, stage, requests, targets_arr)
    finally:
        drained = service.stop(drain=True)

    predictions = np.array(
        [r.prediction for r in results], dtype=np.float64
    )

    # 3. statistics, the batch load_clf= way (seed-1 shuffled order;
    # confusion_only=False — the seizure workload reports the TRUE
    # confusion matrix, the builder's _seizure_classifier contract)
    with stage("test", classifier=query_map["load_clf"], task="seizure"):
        perm = java_compat.java_shuffle_indices(n, seed=1)
        statistics = stats.ClassificationStatistics.from_arrays(
            predictions[perm], targets_arr[perm],
            confusion_only=False,
        )
    wp, wn, cost_fp, cost_fn = PipelineBuilder.seizure_weights(
        query_map, targets_arr
    )
    stats.mark_extended(statistics, cost_fp=cost_fp, cost_fn=cost_fn)

    block = service.stats_block()
    block["requests"]["total_epochs"] = n
    block["drained_cleanly"] = drained
    if threshold is not None:
        block["serve_threshold"] = threshold
    n_pos = int(np.sum(targets_arr == 1.0))
    workload = {
        "task": "seizure",
        "window": window,
        "stride": stride,
        "label_overlap": overlap,
        "windows": n,
        "positives": n_pos,
        "class_ratio": round(n_pos / n, 6) if n else 0.0,
        "weight_pos": round(wp, 6),
        "weight_neg": round(wn, 6),
        "cost_fp": cost_fp,
        "cost_fn": cost_fn,
        "fe": fe_value,
        "serve_threshold": threshold,
    }
    logger.info(
        "served %d seizure windows: %d completed, %d shed, %d "
        "deadline-exceeded, %d failed (drained=%s)",
        n, block["requests"]["completed"], block["requests"]["shed"],
        block["requests"]["deadline_exceeded"],
        block["requests"]["failed"], drained,
    )
    return statistics, block, workload
