"""Multiplexed multi-tenant serving: N models, ONE resident engine.

The single-model service (serve/service.py) gives every tenant their
own compiled program, warmup, batcher, and queue — N tenants cost N
resident engines even when most are idle, the opposite fleet shape
from the ROADMAP's "millions of users" north star. But the engine's
zero-recompile swap contract already proves the weights are DATA, not
program: they ride as a traced argument. This module stacks N tenants'
weight vectors into the columns of one ``(d, 128)`` matrix — the same
128-lane padding the mega kernel's weight matrix has always carried
(ops/serve_mega.py puts the solo model in column 0 and wastes the
other 127) — and serves every tenant through ONE compiled program:

- each admitted request carries a tenant id; the batcher coalesces
  mixed-tenant requests into one bucket (tenant is deliberately NOT in
  the batch key), so ``serve_flush_us`` fills buckets ACROSS tenants;
- the fused/mega multi programs gather each row's tenant weight column
  by index (``engine._multi_serving_program`` /
  ``serve_mega.make_serve_mega_multi_program``) — margins are
  byte-identical to a solo engine serving that tenant alone, pinned in
  tests/test_multitenant.py;
- adding or swapping a tenant rewrites ONE column of the host mirror
  and re-stages the (tiny — 48x128 f32 = 24 KB) stack with
  ``jax.device_put``: no jitted scatter, no trace, 0 XLA compiles
  (pinned via the report's CompilationMonitor).

**Isolation contract.** The single-model engine's per-batch classifier
snapshot generalizes: :meth:`MultiplexedEngine.execute` reads the
immutable :class:`TenantStack` ONCE per batch, so tenant A's
``swap_model``/``remove_tenant`` (or a fault plan scoped to A — the
``serve.batch.tenant.<name>`` chaos point) can never tear tenant B's
in-flight batch — B's rows are served wholly by the stack that was
live when the batch started, and B's statistics are pinned identical
to a B-only run under A-scoped chaos. A per-tenant admission quota
(``ServeConfig.tenant_quota``) sheds one noisy tenant's burst against
its OWN budget — with per-tenant depth + oldest-age evidence in the
``ShedError`` (and the gateway's 429 body) — while the rest of the
queue keeps admitting everyone else.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from . import batcher as batcher_mod
from . import engine as engine_mod
from . import service as service_mod
from ..io import deadline as deadline_mod
from ..models import linear
from ..obs import events
from ..obs import metrics_export
from ..utils import constants

logger = logging.getLogger(__name__)

#: lane width of the tenant stack — one column per tenant in the
#: (d, 128) weight matrix, the mega kernel's native layout
MAX_TENANTS = engine_mod.MAX_TENANTS

#: the pre-registered accelerator consolidation margin (the PR 9/12
#: decision-path pattern): a staged ``serve_multitenant`` chip
#: artifact must show the multiplexed engine's 16-tenant
#: concurrency-16 predictions/sec at >= this ratio of the solo fleet's
#: before operators consolidate per-tenant engines onto one
#: multiplexed engine on that platform. 1.0: the multiplexed engine
#: must at least MATCH the fleet it replaces — its win is resident
#: footprint (1 program vs N) and cross-tenant batch fill, not a raw
#: throughput regression traded away silently.
MULTIPLEX_FLIP_RATIO = 1.0

#: sweep-artifact filename stems carrying a serve_multitenant chip run
_MULTITENANT_ARTIFACTS = ("serve_multitenant*.json",)


def accelerator_decision(root: str | None = None) -> dict:
    """The multiplexed engine's accelerator decision, as DATA: harvest
    the best on-chip ``serve_multitenant`` sweep (staged by
    tools/collect_chip_runs.sh) and judge its 16-tenant
    multiplexed-vs-solo-fleet throughput ratio against the
    pre-registered :data:`MULTIPLEX_FLIP_RATIO`. Returns
    ``{"consolidate", "multiplexed_preds_per_s", "fleet_preds_per_s",
    "ratio", "source", "threshold_ratio", "reason"}`` — artifact
    lands, the consolidation call flips, zero code change."""
    import glob
    import json
    import os

    from ..ops import serve_mega

    base = root or serve_mega._sweep_results_root()
    best = None
    best_src = None
    for pattern in _MULTITENANT_ARTIFACTS:
        for path in glob.glob(os.path.join(base, "*", pattern)):
            try:
                if os.path.getsize(path) == 0:
                    continue
                with open(path) as f:
                    rec = json.loads(f.read().strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                continue
            if rec.get("platform") not in ("tpu", "axon"):
                continue
            levels = (
                (rec.get("serve") or {}).get("multitenant") or {}
            ).get("levels") or []
            for level in levels:
                if level.get("tenants") != 16:
                    continue
                mult = (
                    level.get("multiplexed") or {}
                ).get("preds_per_s")
                fleet = (
                    level.get("solo_fleet") or {}
                ).get("preds_per_s")
                if not (
                    isinstance(mult, (int, float))
                    and isinstance(fleet, (int, float))
                    and mult > 0 and fleet > 0
                ):
                    continue
                if best is None or mult / fleet > best[0]:
                    best, best_src = (mult / fleet, mult, fleet), path
    decision = {
        "threshold_ratio": MULTIPLEX_FLIP_RATIO,
        "source": (
            os.path.relpath(best_src, os.path.dirname(base))
            if best_src
            else None
        ),
    }
    if best is None:
        decision.update(
            consolidate=False,
            multiplexed_preds_per_s=None,
            fleet_preds_per_s=None,
            ratio=None,
            reason=(
                "no on-chip serve_multitenant sweep in the staged "
                "artifacts; per-tenant engines stand"
            ),
        )
        return decision
    ratio, mult, fleet = best
    decision.update(
        multiplexed_preds_per_s=mult,
        fleet_preds_per_s=fleet,
        ratio=round(ratio, 4),
    )
    if ratio >= MULTIPLEX_FLIP_RATIO:
        decision.update(
            consolidate=True,
            reason=(
                f"serve_multitenant measured {mult:.0f} preds/s on "
                f"chip at 16 tenants >= {MULTIPLEX_FLIP_RATIO:g}x the "
                f"solo fleet ({fleet:.0f}); consolidate onto the "
                f"multiplexed engine"
            ),
        )
    else:
        decision.update(
            consolidate=False,
            reason=(
                f"serve_multitenant measured {mult:.0f} preds/s on "
                f"chip at 16 tenants < {MULTIPLEX_FLIP_RATIO:g}x the "
                f"solo fleet ({fleet:.0f}); per-tenant engines stand"
            ),
        )
    return decision


class TenantStack(NamedTuple):
    """One immutable snapshot of the stacked tenant state — the unit
    the engine reads ONCE per batch (the tear-free isolation seam).

    ``weights`` is the device-resident ``(d, 128)`` f32 matrix (tenant
    t's weight vector in column ``lane[t]``, unregistered lanes zero);
    ``intercepts``/``thresholds`` are per-lane PYTHON floats — applied
    per tenant group host-side with exactly the scalar numpy semantics
    the solo engine uses, which is what keeps the post-intercept
    margins byte-identical; ``classifiers`` carries each lane's live
    classifier object for the host rung's per-tenant ``predict``;
    ``generations`` counts swaps per lane (attribution)."""

    weights: object            # jax.Array (d, 128) float32, resident
    intercepts: tuple          # 128 python floats
    thresholds: tuple          # 128 python floats
    classifiers: tuple         # 128 entries: classifier or None
    generations: tuple         # 128 ints
    #: the quantized-stack residency fields (ISSUE 18): when
    #: ``weights_precision`` is int8/int4 the published snapshot
    #: carries the PACKED device payload + per-lane scales and
    #: ``weights`` is None — what is resident is the quantized stack;
    #: the f32 host mirror stays master on the host, so the
    #: zero-recompile admin path is untouched.
    packed: object = None      # jax.Array int8 (d,128) / uint8 (d/2,128)
    scales: object = None      # jax.Array (128,) float32
    weights_precision: str = "f32"


class MultiplexedEngine(engine_mod.ServingEngine):
    """One resident compiled program serving N tenants' models.

    ``tenants`` maps tenant name -> trained/loaded classifier; every
    tenant must be the fused-linear family (float32 linear weights of
    one shared shape — the stacked-matrix contract). The engine keeps
    the solo engine's whole ladder — mega -> fused -> host with the
    same warmup margin-parity gate and degradation bookkeeping — but
    every execute path is tenant-stacked: the batch carries one lane
    index per row and the program gathers that row's weight column.
    """

    def __init__(
        self,
        tenants,
        wavelet_index: int = 8,
        n_channels: int = constants.USED_CHANNELS,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        capacity: int = 64,
        engine_rung: str = "auto",
        weights_precision: str = "f32",
    ):
        from ..ops import quant

        if weights_precision not in quant.WEIGHTS_PRECISIONS:
            raise ValueError(
                f"weights_precision= must be one of "
                f"{quant.WEIGHTS_PRECISIONS}, got {weights_precision!r}"
            )
        items = list(
            tenants.items() if isinstance(tenants, dict) else tenants
        )
        if not items:
            raise ValueError(
                "a multiplexed engine needs at least one tenant"
            )
        if len(items) > MAX_TENANTS:
            raise ValueError(
                f"{len(items)} tenants exceed the {MAX_TENANTS}-lane "
                f"stack (the weight matrix's 128-lane width)"
            )
        first_name, first_clf = items[0]
        self._require_fused_linear(first_name, first_clf)
        super().__init__(
            first_clf,
            wavelet_index=wavelet_index,
            n_channels=n_channels,
            pre=pre,
            post=post,
            epoch_size=epoch_size,
            skip_samples=skip_samples,
            feature_size=feature_size,
            capacity=capacity,
            precision="f32",
            engine_rung=engine_rung,
        )
        assert self._fused_linear  # _require_fused_linear guaranteed it
        self._weight_shape = first_clf.weights.shape
        self._multi_program = engine_mod._multi_serving_program(
            *self._geometry, precision="f32",
        )
        #: quantized-stack state: ``_weights_precision_requested`` is
        #: the knob; the ACTIVE precision starts (and on a failed gate
        #: or runtime degradation, stays/returns to) f32 — promotion
        #: happens only in :meth:`_weights_quant_warmup`, behind the
        #: margin-parity gate against the f32 stack.
        self._weights_precision_requested = weights_precision
        self._weights_precision = "f32"
        self._multi_program_quant = None
        self._consecutive_quant_failures = 0
        self._quant_degrade_after = 2
        self.weights_record = None
        self._resident_bytes = 0
        # tenant registry: name -> lane (a column of the stack). All
        # mutation happens under the lock and ends in _publish(); the
        # hot path never takes it — execute() reads the published
        # stack snapshot once per batch.
        self._tenant_lock = threading.RLock()
        self._lanes: Dict[str, int] = {}
        self._w_host = np.zeros(
            (int(np.prod(self._weight_shape)), MAX_TENANTS), np.float32
        )
        self._intercepts = [0.0] * MAX_TENANTS
        self._thresholds = [0.0] * MAX_TENANTS
        self._classifiers: List[object] = [None] * MAX_TENANTS
        self._generations = [0] * MAX_TENANTS
        self._stack: Optional[TenantStack] = None
        #: per-batch stash (set by execute, read by the _execute_*
        #: overrides the inherited ladder dispatches to) — the engine
        #: is driven by ONE batcher thread, like the solo engine
        self._batch_lanes: Optional[np.ndarray] = None
        self._batch_stack: Optional[TenantStack] = None
        for name, clf in items:
            self._admit(name, clf)
        self._publish()

    # -- tenant registry ------------------------------------------------

    @staticmethod
    def _require_fused_linear(name: str, classifier) -> None:
        w = getattr(classifier, "weights", None)
        if (
            not isinstance(classifier, linear._LinearClassifier)
            or w is None
            or w.dtype != np.float32
        ):
            raise ValueError(
                f"tenant {name!r} is not multiplexable: the stacked "
                f"engine needs the fused-linear family (trained "
                f"float32 linear weights); got "
                f"{type(classifier).__name__} with weights="
                f"{None if w is None else (w.dtype, w.shape)}"
            )

    def _admit(self, name: str, classifier) -> int:
        """Register one tenant into a free lane (caller publishes)."""
        self._require_fused_linear(name, classifier)
        if classifier.weights.shape != self._weight_shape:
            raise ValueError(
                f"tenant {name!r} has weights of shape "
                f"{classifier.weights.shape}; the stack serves "
                f"{self._weight_shape} (one compiled geometry)"
            )
        if name in self._lanes:
            raise ValueError(f"tenant {name!r} is already registered")
        lane = next(
            (
                i for i in range(MAX_TENANTS)
                if self._classifiers[i] is None
            ),
            None,
        )
        if lane is None:
            raise ValueError(
                f"tenant stack is full ({MAX_TENANTS} lanes)"
            )
        self._lanes[name] = lane
        self._w_host[:, lane] = np.asarray(
            classifier.weights, np.float32
        ).reshape(-1)
        self._intercepts[lane] = float(classifier.intercept)
        self._thresholds[lane] = float(classifier.margin_threshold)
        self._classifiers[lane] = classifier
        return lane

    def _publish(self) -> None:
        """Stage the host mirror and publish a fresh immutable stack.

        ``device_put`` (NOT a jitted scatter) keeps the add/swap path
        off the compiler entirely — the 0-recompile pin is structural.
        Publication is one attribute assignment: an in-flight batch
        holds the previous snapshot and is served wholly by it.

        With a promoted quantized stack the f32 mirror is still what
        the admin path mutates (master copy), but what ships to the
        device is its packed int8/int4 payload + per-lane scales
        (numpy quantize — ops/quant.py — then device_put: still zero
        compiles); ``weights`` is None on those snapshots, so the
        resident footprint really IS the quantized one."""
        common = dict(
            intercepts=tuple(self._intercepts),
            thresholds=tuple(self._thresholds),
            classifiers=tuple(self._classifiers),
            generations=tuple(self._generations),
        )
        if self._weights_precision != "f32":
            from ..ops import quant

            packed_np, scales_np = quant.quantize_weight_stack(
                self._w_host, self._weights_precision
            )
            self._resident_bytes = quant.resident_weight_bytes(
                packed_np, scales_np
            )
            self._stack = TenantStack(
                weights=None,
                packed=jax.device_put(packed_np),
                scales=jax.device_put(scales_np),
                weights_precision=self._weights_precision,
                **common,
            )
        else:
            self._resident_bytes = int(self._w_host.nbytes)
            self._stack = TenantStack(
                weights=jax.device_put(self._w_host), **common,
            )

    @property
    def tenants(self) -> Tuple[str, ...]:
        """Registered tenant names, lane order."""
        with self._tenant_lock:
            return tuple(
                sorted(self._lanes, key=self._lanes.__getitem__)
            )

    def tenant_info(self, name: str) -> dict:
        """One tenant's registry record: lane + swap generation."""
        with self._tenant_lock:
            if name not in self._lanes:
                raise ValueError(f"unknown tenant {name!r}")
            lane = self._lanes[name]
            return {
                "lane": lane,
                "generation": self._generations[lane],
            }

    @property
    def resident_weight_bytes(self) -> int:
        """Bytes of the device-resident stacked weight payload — the
        whole per-tenant model footprint of the multiplexed engine
        (one matrix serves all 128 lanes). With a promoted quantized
        stack this is the packed matrix + per-lane scales (the 4x/8x
        reduction the bench line accounts), not the f32 mirror."""
        return int(self._resident_bytes)

    @property
    def weights_precision(self) -> str:
        """The ACTIVE weight-stack precision (what is resident now —
        f32 until the warmup gate promotes the requested rung, and
        again after a runtime degradation)."""
        return self._weights_precision

    def add_tenant(self, name: str, classifier) -> int:
        """Register a new tenant at runtime; returns its lane. One
        column write + device_put — 0 recompiles, and every other
        tenant's in-flight traffic is untouched (snapshot seam)."""
        with self._tenant_lock:
            lane = self._admit(name, classifier)
            self._publish()
        events.event("serve.tenant_added", tenant=name, lane=lane)
        return lane

    def remove_tenant(self, name: str):
        """Unregister a tenant; returns its displaced classifier. The
        lane's column is zeroed and freed for reuse. Requests already
        in flight for this tenant ride the pre-removal snapshot (the
        isolation contract); NEW submissions for it are refused by the
        service's registry check."""
        with self._tenant_lock:
            if name not in self._lanes:
                raise ValueError(f"unknown tenant {name!r}")
            if len(self._lanes) == 1:
                raise ValueError(
                    f"cannot remove {name!r}: a multiplexed engine "
                    f"serves at least one tenant"
                )
            lane = self._lanes.pop(name)
            displaced = self._classifiers[lane]
            self._classifiers[lane] = None
            self._w_host[:, lane] = 0.0
            self._intercepts[lane] = 0.0
            self._thresholds[lane] = 0.0
            self._generations[lane] += 1
            self._publish()
        events.event("serve.tenant_removed", tenant=name, lane=lane)
        return displaced

    def swap_model(self, classifier, tenant: Optional[str] = None):
        """Hot-swap ONE tenant's model; returns the displaced one.

        The solo engine's zero-recompile contract, per lane: the
        replacement must be float32 linear weights of the stack's
        shape (refused loudly otherwise — and a refused swap leaves
        the published stack untouched, so no other tenant can be torn
        by a failed swap). ``tenant`` may be omitted only while
        exactly one tenant is registered."""
        with self._tenant_lock:
            if tenant is None:
                if len(self._lanes) != 1:
                    raise ValueError(
                        f"{len(self._lanes)} tenants are registered; "
                        f"swap_model needs tenant= to pick one"
                    )
                tenant = next(iter(self._lanes))
            if tenant not in self._lanes:
                raise ValueError(f"unknown tenant {tenant!r}")
            self._require_fused_linear(tenant, classifier)
            if classifier.weights.shape != self._weight_shape:
                raise ValueError(
                    f"hot swap for tenant {tenant!r} requires float32 "
                    f"linear weights of the stack shape "
                    f"{self._weight_shape} (the zero-recompile "
                    f"contract); got {classifier.weights.shape}"
                )
            lane = self._lanes[tenant]
            displaced = self._classifiers[lane]
            self._w_host[:, lane] = np.asarray(
                classifier.weights, np.float32
            ).reshape(-1)
            self._intercepts[lane] = float(classifier.intercept)
            self._thresholds[lane] = float(classifier.margin_threshold)
            self._classifiers[lane] = classifier
            self._generations[lane] += 1
            self._publish()
        events.event("serve.tenant_swapped", tenant=tenant, lane=lane)
        return displaced

    # -- execution ------------------------------------------------------

    def execute(
        self,
        windows: Sequence[np.ndarray],
        resolutions: np.ndarray,
        tenants: Optional[Sequence[Optional[str]]] = None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Run one mixed-tenant micro-batch: ``tenants[i]`` names the
        model serving window ``i`` (None rows — and a None sequence —
        fall to the oldest registered tenant, the warmup convention).
        The stack snapshot and the name->lane mapping are both read
        ONCE here; the inherited ladder (mega -> fused -> host, with
        the solo engine's degradation bookkeeping) then dispatches to
        the tenant-stacked overrides below."""
        n = len(windows)
        stack, lanes = self._resolve(tenants, n)
        self._batch_stack = stack
        self._batch_lanes = lanes
        try:
            return super().execute(windows, resolutions)
        finally:
            self._batch_stack = None
            self._batch_lanes = None

    def _resolve(self, tenants, n: int):
        with self._tenant_lock:
            stack = self._stack
            if tenants is None:
                default_lane = min(self._lanes.values())
                return stack, np.full(n, default_lane, np.int32)
            if len(tenants) != n:
                raise ValueError(
                    f"{len(tenants)} tenant ids for {n} windows"
                )
            default_lane = min(self._lanes.values())
            lanes = np.empty(n, np.int32)
            for i, name in enumerate(tenants):
                if name is None:
                    lanes[i] = default_lane
                elif name in self._lanes:
                    lanes[i] = self._lanes[name]
                else:
                    raise ValueError(f"unknown tenant {name!r}")
            return stack, lanes

    def _postprocess(self, margins: np.ndarray, lanes, stack):
        """Intercept + threshold per TENANT GROUP with python-float
        scalars — the exact numpy scalar semantics the solo engine's
        ``margins + clf.intercept`` uses, so a tenant's post-intercept
        margins (and therefore predictions) stay byte-identical to its
        solo service."""
        n = len(margins)
        out_margins = np.empty(n, margins.dtype)
        predictions = np.empty(n, np.float64)
        for lane in np.unique(lanes):
            rows = lanes == lane
            m = margins[rows] + stack.intercepts[lane]
            out_margins[rows] = m
            predictions[rows] = (
                m > stack.thresholds[lane]
            ).astype(np.float64)
        return predictions, out_margins

    def _execute_fused(self, windows, resolutions):
        n = len(windows)
        stack = self._batch_stack
        lanes = self._batch_lanes
        stream, mask = self._stage_fused_stream(windows)
        staged = jax.device_put(stream)
        res = np.asarray(resolutions, dtype=np.float32)
        tids = np.zeros(self.capacity, np.int32)
        tids[:n] = lanes
        if stack.weights_precision != "f32":
            try:
                _feats, margins = self._multi_program_quant(
                    staged, res, self._positions, mask,
                    stack.packed, stack.scales, tids,
                )
                self._consecutive_quant_failures = 0
            except Exception as e:
                # the quantized stack's runtime degradation seam: the
                # batch is served by the f32 MASTER mirror (device_put
                # on the fly — exact weights, zero compiles), and two
                # consecutive failures retire the quantized stack for
                # the engine's lifetime (crash-only: stepping down is
                # survival, never silence)
                self._consecutive_quant_failures += 1
                err = f"{type(e).__name__}: {e}"
                events.event("serve.weights_quant_error", error=err)
                if (
                    self._consecutive_quant_failures
                    >= self._quant_degrade_after
                ):
                    self._disable_weights_quant(err)
                staged = jax.device_put(stream)
                _feats, margins = self._multi_program(
                    staged, res, self._positions, mask,
                    jax.device_put(self._w_host), tids,
                )
        else:
            _feats, margins = self._multi_program(
                staged, res, self._positions, mask, stack.weights,
                tids,
            )
        return self._postprocess(
            np.asarray(margins[:n]), np.asarray(lanes), stack
        )

    def _execute_mega(self, windows, resolutions):
        from ..ops import serve_mega

        n = len(windows)
        stack = self._batch_stack
        lanes = self._batch_lanes
        stream = serve_mega.stage_mega_stream(
            windows, self.n_channels, self.window_len,
            self._mega_stride, self.capacity,
        )
        staged = jax.device_put(stream)
        res = np.asarray(resolutions, dtype=np.float32)
        tids = np.zeros(self.capacity, np.int32)
        tids[:n] = lanes
        if stack.weights_precision != "f32":
            # the packed-stack mega lowering (a mega-rung failure here
            # rides the inherited mega->fused degradation, where the
            # fused path above owns the quant bookkeeping)
            margins = np.asarray(
                self._mega_program(
                    staged, res, stack.packed, stack.scales, tids
                )
            )[:n]
        else:
            margins = np.asarray(
                self._mega_program(staged, res, stack.weights, tids)
            )[:n]
        return self._postprocess(margins, np.asarray(lanes), stack)

    def _disable_weights_quant(self, error: str) -> None:
        """Retire the quantized stack: republish the f32 mirror (all
        later snapshots are f32) and, if the promoted mega program was
        built for the packed signature, step the ladder down to the
        always-alive f32 fused multi program."""
        from .. import obs

        with self._tenant_lock:
            if self._weights_precision == "f32":
                return
            self._weights_precision = "f32"
            if self.weights_record is not None:
                self.weights_record["used"] = "f32"
                self.weights_record["degraded"] = True
                self.weights_record["error"] = error
            if self._rung == "mega":
                self._rung = "fused"
            self._publish()
        obs.metrics.count("serve.weights_quant_degraded")
        events.event("serve.weights_quant_degraded", error=error)
        logger.warning(
            "serve.weights_quant degraded to the f32 stack after %d "
            "consecutive failures (%s)",
            self._consecutive_quant_failures, error,
        )

    def _execute_host(self, windows, resolutions):
        """The host floor, per tenant group: one shared featurization
        (row-independent, like the fused stream) and each group's rows
        through its OWN classifier's ``predict`` — the same call a
        solo host-rung service makes for that tenant."""
        stack = self._batch_stack
        lanes = (
            np.asarray(self._batch_lanes)
            if self._batch_lanes is not None
            else np.zeros(len(windows), np.int32)
        )
        feats = self._host_features(windows, resolutions)
        predictions = np.empty(len(windows), np.float64)
        for lane in np.unique(lanes):
            rows = lanes == lane
            clf = stack.classifiers[lane]
            predictions[rows] = np.asarray(
                clf.predict(feats[rows]), dtype=np.float64
            )
        return predictions, None

    # -- warmup ---------------------------------------------------------

    def warmup(self) -> None:
        """Compile the multi-tenant program(s) before traffic, resolve
        the mega rung behind the SAME margin-parity gate the solo
        engine uses (multi-mega vs multi-fused on the shared gate
        windows, tenant lanes cycling over the registered tenants so
        the gather path itself is what's judged), then trace both
        request dtypes. Idempotent.

        Order matters: the quantized-stack gate runs FIRST (it judges
        the quant fused program against the f32 fused program and, on
        a pass, republishes the packed stack), so the mega gate then
        pins mega-vs-fused at whatever weight residency actually
        serves."""
        if self._warmed:
            return
        self._weights_quant_warmup()
        self._mega_multi_warmup()
        names = self.tenants
        for dtype in (np.int16, np.float32):
            self.execute(
                [np.zeros((self.n_channels, self.window_len), dtype)],
                np.ones(self.n_channels, np.float32),
                [names[0]],
            )
        self._warmed = True

    def _multi_gate_margins(self, windows, res, tids):
        """The fused multi program on the gate windows (pre-intercept
        margins for the live rows) — the parity gate's reference,
        served by whatever stack is currently published (f32, or the
        packed payload once the quant gate promoted it)."""
        n = len(windows)
        stream, mask = self._stage_fused_stream(windows)
        padded_tids = np.zeros(self.capacity, np.int32)
        padded_tids[:n] = tids
        stack = self._stack
        if stack.weights_precision != "f32":
            _feats, margins = self._multi_program_quant(
                jax.device_put(stream), res, self._positions, mask,
                stack.packed, stack.scales, padded_tids,
            )
        else:
            _feats, margins = self._multi_program(
                jax.device_put(stream), res, self._positions, mask,
                stack.weights, padded_tids,
            )
        return np.asarray(margins)[:n]

    def _gate_tids(self, n: int) -> np.ndarray:
        """Gate-window tenant lanes cycling over the REGISTERED
        tenants: the gather (and with a quantized stack, every lane's
        own scale) — not just lane 0 — is what the pins judge."""
        with self._tenant_lock:
            lanes = sorted(self._lanes.values())
        return np.asarray(
            [lanes[i % len(lanes)] for i in range(n)], np.int32
        )

    def _weights_quant_warmup(self) -> None:
        """Resolve and (when earned) promote the quantized weight
        stack: build the packed-stack fused program, quantize the
        CURRENT host mirror, and pin its per-tenant margins against
        the f32 stack's on the shared gate windows at the derived
        envelope tolerance (ops/quant.weights_gate_tolerance;
        EEG_TPU_WEIGHTS_GATE_TOL=0 is the forced-off drill). Above
        tolerance — or on any build/compile failure — the f32 stack
        stands, recorded, never silent."""
        from .. import obs
        from ..ops import quant

        wp = self._weights_precision_requested
        if wp == "f32":
            return
        record = {"requested": wp, "used": "f32", "gate": None}
        self.weights_record = record
        try:
            program = engine_mod._multi_serving_program(
                *self._geometry, precision="f32",
                weights_precision=wp,
            )
            windows, res = self._gate_windows()
            n = len(windows)
            tids = self._gate_tids(n)
            f32_margins = self._multi_gate_margins(windows, res, tids)
            packed_np, scales_np = quant.quantize_weight_stack(
                self._w_host, wp
            )
            stream, mask = self._stage_fused_stream(windows)
            padded_tids = np.zeros(self.capacity, np.int32)
            padded_tids[:n] = tids
            _feats, q_margins = program(
                jax.device_put(stream), res, self._positions, mask,
                jax.device_put(packed_np), jax.device_put(scales_np),
                padded_tids,
            )
            q_margins = np.asarray(q_margins)[:n]
            tol = quant.weights_gate_tolerance(wp, self._w_host)
            dev = float(
                np.max(np.abs(q_margins - f32_margins)) if n else 0.0
            )
            gate = {
                "max_abs_dev": dev,
                "tolerance": tol,
                "ok": bool(dev <= tol),
                "rows_checked": n,
            }
        except Exception as e:
            record["error"] = f"{type(e).__name__}: {e}"
            obs.metrics.count("serve.weights_quant_unavailable")
            events.event(
                "serve.weights_quant_unavailable",
                error=record["error"],
            )
            logger.warning(
                "serve.weights_quant (%s) unavailable (%s); serving "
                "the f32 stack", wp, record["error"],
            )
            return
        record["gate"] = gate
        if not gate["ok"]:
            obs.metrics.count("serve.weights_quant_gate_disabled")
            events.event("serve.weights_quant_gate", **gate)
            logger.warning(
                "serve.weights_quant_gate refused the %s stack: max "
                "abs margin dev %.3e > gate %.3e; serving the f32 "
                "stack", wp, gate["max_abs_dev"], gate["tolerance"],
            )
            return
        self._multi_program_quant = program
        with self._tenant_lock:
            self._weights_precision = wp
            self._publish()
        record["used"] = wp
        events.event(
            "serve.weights_quant_promoted", weights_precision=wp,
            resident_bytes=self._resident_bytes,
        )

    def _mega_multi_warmup(self) -> None:
        from ..ops import serve_mega
        from .. import obs

        if self.pre < 1:
            return
        requested = self._engine_rung_requested
        if requested == "fused":
            return
        resolved = (
            serve_mega.default_engine_rung()
            if requested == "auto"
            else requested
        )
        record = {
            "requested": requested,
            "resolved": resolved,
            "used": "fused",
            "lowering": None,
            "gate": None,
        }
        self.mega_record = record
        if resolved != "mega":
            return
        # the mega program is built for whatever stack the quant gate
        # left published — packed signature when promoted, f32 weights
        # otherwise — so the rung it earns is the rung it serves
        wp = self._weights_precision
        record["weights_precision"] = wp
        try:
            lowering = serve_mega.default_lowering()
            record["lowering"] = lowering
            program = serve_mega.make_serve_mega_multi_program(
                wavelet_index=self.wavelet_index,
                epoch_size=self.epoch_size,
                skip_samples=self.skip_samples,
                feature_size=self.feature_size,
                n_channels=self.n_channels,
                pre=self.pre,
                post=self.post,
                capacity=self.capacity,
                lowering=lowering,
                weights_precision=wp,
            )
            stride = serve_mega.padded_stride(self.pre, self.post)
            windows, res = self._gate_windows()
            # gate lanes cycle over the REGISTERED tenants: the gather
            # itself — not just lane 0 — is what the pin judges
            tids = self._gate_tids(len(windows))
            padded_tids = np.zeros(self.capacity, np.int32)
            padded_tids[: len(windows)] = tids
            mega_stream = serve_mega.stage_mega_stream(
                windows, self.n_channels, self.window_len, stride,
                self.capacity,
            )
            staged = jax.device_put(mega_stream)
            if wp != "f32":
                mega_margins = np.asarray(program(
                    staged, res, self._stack.packed,
                    self._stack.scales, padded_tids,
                ))[: len(windows)]
            else:
                mega_margins = np.asarray(program(
                    staged, res, self._stack.weights, padded_tids,
                ))[: len(windows)]
            fused_margins = self._multi_gate_margins(windows, res, tids)
            tol = serve_mega.mega_gate_tolerance()
            dev = float(
                np.max(np.abs(mega_margins - fused_margins))
                if len(windows)
                else 0.0
            )
            gate = {
                "max_abs_dev": dev,
                "tolerance": tol,
                "ok": bool(dev <= tol),
                "rows_checked": len(windows),
            }
        except Exception as e:
            record["error"] = f"{type(e).__name__}: {e}"
            obs.metrics.count("serve.mega_unavailable")
            events.event(
                "serve.mega_unavailable", error=record["error"]
            )
            logger.warning(
                "serve.mega (multi-tenant) unavailable (%s); serving "
                "the fused multi program", record["error"],
            )
            return
        record["gate"] = gate
        if not gate["ok"]:
            obs.metrics.count("serve.mega_gate_disabled")
            events.event("serve.mega_gate", **gate)
            logger.warning(
                "serve.mega_gate refused the multi-tenant rung: max "
                "abs margin dev %.3e > gate %.3e; serving the fused "
                "multi program",
                gate["max_abs_dev"], gate["tolerance"],
            )
            return
        self._mega_program = program
        self._mega_stride = stride
        self._rung = "mega"
        record["used"] = "mega"
        events.event(
            "serve.mega_promoted", lowering=record["lowering"],
            tenants=len(self.tenants),
        )


class MultiplexedService(service_mod.InferenceService):
    """N tenants' models behind one engine, one batcher, one queue.

    The single-model service's lifecycle (start/drain/stop, watchdog,
    stats) unchanged; what multiplexing adds is the tenant key on
    every request, runtime tenant administration
    (:meth:`add_tenant` / :meth:`remove_tenant` / :meth:`swap_tenant`
    — all 0-recompile), per-tenant attribution in the stats block, and
    the per-tenant admission quota (``ServeConfig.tenant_quota``)."""

    def __init__(
        self,
        tenants,
        wavelet_index: int = 8,
        n_channels: int = constants.USED_CHANNELS,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
        config: Optional[service_mod.ServeConfig] = None,
        engine_rung: str = "auto",
        weights_precision: str = "f32",
    ):
        self.config = config or service_mod.ServeConfig()
        self.engine = MultiplexedEngine(
            tenants,
            wavelet_index=wavelet_index,
            n_channels=n_channels,
            pre=pre,
            post=post,
            capacity=self.config.max_batch,
            engine_rung=engine_rung,
            weights_precision=weights_precision,
        )
        #: multiplexed services have no (single) lifecycle manager;
        #: per-tenant model state is the stack's swap generations
        self.lifecycle = None
        self.batcher = batcher_mod.MicroBatcher(
            self.engine.execute,
            max_batch=self.config.max_batch,
            queue_depth=self.config.queue_depth,
            coalesce_s=self.config.coalesce_s,
            flush_us=self.config.flush_us,
            max_attempts=self.config.max_attempts,
            retry_backoff_s=self.config.retry_backoff_s,
            watchdog_s=self.config.watchdog_s,
            tenant_aware=True,
            tenant_quota=self.config.tenant_quota,
        )
        self._accepting = False
        self._started = False
        self._drained_cleanly: Optional[bool] = None
        self._lock = threading.Lock()

    @classmethod
    def from_saved(
        cls,
        tenants: Dict[str, Tuple[str, str]],
        warmup: bool = True,
        **kwargs,
    ) -> "MultiplexedService":
        """Load each tenant's saved model exactly once and build the
        multiplexed service around the stack: ``tenants`` maps tenant
        name -> ``(classifier_name, model_path)`` (io/modelfiles
        routing, like the solo ``from_saved``)."""
        from ..models import registry as clf_registry

        loaded = {}
        for name, (classifier_name, model_path) in tenants.items():
            classifier = clf_registry.create(classifier_name)
            classifier.load(model_path)
            loaded[name] = classifier
        service = cls(loaded, **kwargs)
        if warmup:
            service.engine.warmup()
        return service

    # -- tenant administration ------------------------------------------

    @property
    def tenants(self) -> Tuple[str, ...]:
        return self.engine.tenants

    def add_tenant(self, name: str, classifier) -> int:
        """Register a tenant at runtime (0 recompiles); returns its
        lane."""
        lane = self.engine.add_tenant(name, classifier)
        self.batcher._count("tenant_adds")
        return lane

    def add_tenant_from_saved(
        self, name: str, classifier_name: str, model_path: str
    ) -> int:
        """Load a saved model and register it as ``name`` — the
        runtime tenant-onboarding path (gateway/operator surface)."""
        from ..models import registry as clf_registry

        classifier = clf_registry.create(classifier_name)
        classifier.load(model_path)
        return self.add_tenant(name, classifier)

    def remove_tenant(self, name: str):
        """Unregister a tenant; in-flight requests ride the
        pre-removal snapshot, new submissions for it are refused.
        The tenant's batcher-side accounting (latency reservoir,
        histogram, per-tenant counters) is evicted with it — a
        long-lived service with add/remove churn must not accumulate
        departed tenants' state."""
        displaced = self.engine.remove_tenant(name)
        self.batcher.evict_tenant(name)
        self.batcher._count("tenant_removes")
        return displaced

    def swap_tenant(self, name: str, classifier):
        """Hot-swap one tenant's model (0 recompiles, tear-free for
        every other tenant); returns the displaced classifier."""
        displaced = self.engine.swap_model(classifier, tenant=name)
        self.batcher._count("tenant_swaps")
        self.batcher._count_tenant(name, "swaps")
        return displaced

    # -- request path ---------------------------------------------------

    def submit(
        self,
        window: np.ndarray,
        resolutions: np.ndarray,
        tenant: Optional[str] = None,
        deadline_s: Optional[float] = None,
        block_s: float = 0.0,
        label: Optional[float] = None,
    ) -> batcher_mod.ServeFuture:
        """Admit one tenant-keyed request; returns its future. An
        unknown tenant is a ``ValueError`` at the door (never a queued
        request the engine will refuse later); a quota/queue shed
        raises :class:`ShedError` with the structured per-tenant
        evidence on ``.evidence`` (depth, quota, oldest-age — the
        gateway's 429 body)."""
        if label is not None:
            raise ValueError(
                "multiplexed services have no lifecycle manager; "
                "submit(label=) is the solo service's surface"
            )
        if tenant is None:
            raise ValueError(
                "a multiplexed service needs tenant= on every "
                "request (the tenant keys the weight column)"
            )
        if tenant not in self.engine.tenants:
            raise ValueError(
                f"unknown tenant {tenant!r}; registered: "
                f"{list(self.engine.tenants)}"
            )
        self.batcher._count("submitted")
        self.batcher._count_tenant(tenant, "submitted")
        if not self._accepting:
            self.batcher._count("rejected_closed")
            raise batcher_mod.ServiceClosedError(
                "service is not accepting requests "
                "(draining or stopped)"
            )
        if self.batcher.wedged.is_set():
            self.batcher._count("rejected_wedged")
            raise batcher_mod.ServiceWedgedError(
                "service wedged (watchdog tripped); restart the "
                "service"
            )
        req = batcher_mod.Request(
            window=np.asarray(window),
            resolutions=np.asarray(resolutions, np.float32),
            deadline=deadline_mod.Deadline(
                deadline_s if deadline_s is not None
                else self.config.default_deadline_s
            ),
            tenant=tenant,
        )
        if not self.batcher.queue.offer(req, block_s=block_s):
            self.batcher._count("shed")
            self.batcher._count_tenant(tenant, "shed")
            details = self.batcher.queue.last_shed_details
            details.setdefault("tenant", tenant)
            events.event(
                "serve.shed", queue_depth=self.batcher.queue.depth,
                tenant=tenant,
            )
            raise batcher_mod.ShedError(
                f"request shed by admission control: "
                f"{self.batcher.queue._last_shed_evidence}",
                evidence=details,
            )
        if not self._accepting:
            if req.future.fail(batcher_mod.ServiceClosedError(
                "service stopped while the request was being admitted"
            )):
                self.batcher._count("rejected_closed")
        return req.future

    def predict_window(
        self,
        window: np.ndarray,
        resolutions: np.ndarray,
        tenant: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> batcher_mod.Result:
        """Blocking convenience: tenant-keyed submit + wait."""
        budget = (
            deadline_s if deadline_s is not None
            else self.config.default_deadline_s
        )
        fut = self.submit(
            window, resolutions, tenant=tenant, deadline_s=budget
        )
        return fut.result(timeout=self._result_timeout(budget))

    def predict_all(
        self,
        windows: Sequence[np.ndarray],
        resolutions,
        tenants,
        deadline_s: Optional[float] = None,
    ) -> List[batcher_mod.Result]:
        """Drive a window set through the service with backpressure,
        results in input order. ``tenants`` is one tenant name (all
        windows) or a per-window sequence — a mixed sequence is the
        multiplexed fill path: consecutive compatible windows coalesce
        into shared buckets regardless of tenant."""
        budget = (
            deadline_s if deadline_s is not None
            else self.config.default_deadline_s
        )
        if isinstance(tenants, str):
            tenants = [tenants] * len(windows)
        if len(tenants) != len(windows):
            raise ValueError(
                f"{len(tenants)} tenant ids for {len(windows)} windows"
            )
        res_arr = np.asarray(resolutions, dtype=np.float32)
        per_window = res_arr.ndim == 2
        if per_window and len(res_arr) != len(windows):
            raise ValueError(
                f"{len(res_arr)} resolution vectors for "
                f"{len(windows)} windows"
            )
        futures = []
        for i, w in enumerate(windows):
            futures.append(
                self.submit(
                    w, res_arr[i] if per_window else res_arr,
                    tenant=tenants[i], deadline_s=budget,
                    block_s=budget,
                )
            )
        timeout = self._result_timeout(budget)
        return [f.result(timeout=timeout) for f in futures]

    # -- observability --------------------------------------------------

    def stats_block(self) -> dict:
        """The solo service's ``serve`` block plus the per-tenant
        attribution sub-block: per tenant, outcome counters, latency
        percentiles, the lane, and the swap generation (the tenant's
        model-state record; multiplexed services carry no lifecycle
        manager). Safe on a live service — every read is a snapshot
        under the batcher's lock."""
        block = super().stats_block()
        counters, _ = self.batcher.snapshot()
        tenant_lat = self.batcher.tenant_latency_snapshot()
        tenant_hists = self.batcher.tenant_histogram_snapshot()
        tenants_block = {}
        for name in self.engine.tenants:
            lat = sorted(tenant_lat.get(name, []))
            info = self.engine.tenant_info(name)
            tenants_block[name] = {
                "lane": info["lane"],
                "generation": info["generation"],
                "swaps": counters.get(f"tenant.{name}.swaps", 0),
                "requests": {
                    key: counters.get(f"tenant.{name}.{key}", 0)
                    for key in (
                        "submitted", "completed", "shed",
                        "deadline_exceeded", "failed", "retries",
                    )
                },
                "latency_ms": {
                    "p50": round(
                        service_mod._percentile(lat, 50.0) * 1e3, 3
                    ),
                    "p99": round(
                        service_mod._percentile(lat, 99.0) * 1e3, 3
                    ),
                    "n": len(lat),
                },
                # the tenant's SLO scorecard (obs/metrics_export.py):
                # availability, latency-objective attainment off the
                # tenant's fixed-bucket histogram, error-budget burn
                "slo": metrics_export.slo_block(
                    tenant_hists.get(
                        name, metrics_export.LatencyHistogram()
                    ),
                    {
                        key: counters.get(f"tenant.{name}.{key}", 0)
                        for key in (
                            "completed", "shed", "failed",
                            "deadline_exceeded",
                        )
                    },
                    objective_ms=self.config.slo_latency_ms,
                    availability_target=(
                        self.config.slo_availability_target
                    ),
                ),
                # per-tenant model-lifecycle attribution: None —
                # schema-stable with the solo block; the stack's swap
                # generation above is the multiplexed model state
                "lifecycle": None,
            }
        block["tenants"] = tenants_block
        block["tenant_quota"] = self.config.tenant_quota
        block["resident_weight_bytes"] = (
            self.engine.resident_weight_bytes
        )
        block["weights_precision"] = self.engine.weights_precision
        if self.engine.weights_record is not None:
            block["weights"] = dict(self.engine.weights_record)
        return block
