"""Model lifecycle manager for the resident serving service.

Both served workloads are non-stationary — electrodes drift and
subjects fatigue in the P300 speller, and seizure prediction is a
concept-drift problem by definition — yet the service used to load a
classifier once and serve it forever. This module closes the
train/serve loop (ROADMAP item 4) with three cooperating pieces, all
running OFF the request path on one adapter thread:

- **Streaming partial-fit.** Labeled feedback from served requests
  (the speller *knows* the true target after each trial;
  ``InferenceService.submit(..., label=)`` / ``feedback()``)
  accumulates into bounded batches. Each full batch is featurized
  through the engine's own program and trains a **candidate** via the
  resumable elastic chunked-SGD seam (``models/sgd.partial_fit_linear``
  over ``_run_sgd_chunk`` with absolute iteration indices), warm-
  started from the live weights. The feedback matrix lives in a
  fixed-capacity ring with a sample mask (the population engine's
  inert-row seam), so a growing buffer retriggers **zero recompiles**.
  Every chunk's carry — weights AND the buffers it trained on —
  checkpoints through ``checkpoint/manager``, so a SIGKILL'd adapter
  restores the latest carry and replays the remaining feedback to
  **byte-identical** candidate weights.

- **Shadow-scored hot swap with rollback.** The candidate is staged
  next to the live model and shadow-scored on the same labeled
  traffic (both models' decisions over each feedback batch feed
  per-model :class:`models.stats.WindowedStatistics`). Promotion is
  gated: only when the candidate's windowed expected cost beats the
  live model's under the ``swap_gate=`` policy does
  :meth:`ServingEngine.swap_model` install it — weights ride as a
  traced argument (serve/engine.py), so a linear-family swap
  retriggers **0 compiles** and an in-flight micro-batch is served
  wholly by the old or wholly by the new model, never dropped or
  double-served. The displaced model is retained; if the promoted
  model's windowed cost regresses past the pre-swap record, it is
  **rolled back** with the evidence counted and event-logged. A
  candidate that never passes the gate leaves live serving
  byte-identical to a service that never staged one — the rollback
  pin (tests/test_lifecycle.py).

- **Drift detection.** The live model's windowed expected cost is
  judged against the baseline earned by its first full window; a
  window that degrades past ``drift_factor`` emits a ``serve.drift``
  event + metric (rate-limited to once per window span) — the signal
  an operator (or a future auto-recalibration) keys on. Everything
  lands in the ``lifecycle`` block of ``run_report.json`` and the
  serve bench lines.

Chaos points ``serve.adapt`` (one partial-fit chunk) and
``serve.swap`` (one promotion attempt) land in the adapter's retry
machinery: a failed chunk retries (then drops, counted) and a failed
swap leaves the live model untouched with the candidate retained —
under ``faults=serve.swap:p=0.2;serve.adapt:p=0.2`` every request
still resolves (docs/resilience.md).

State machine (docs/serving.md): ``live`` —feedback→ ``adapting``
(candidate staged + shadow-scored) —gate pass→ promoted (previous
model retained) —regression→ rolled back; a wedged adapter step
(watchdog) discards the candidate and live serving continues.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from .batcher import ServiceClosedError
from ..models import stats as stats_mod

logger = logging.getLogger(__name__)


def parse_swap_gate(value: str):
    """``swap_gate=`` grammar -> ``(mode, ratio)``.

    ``off`` disables promotion (shadow-score only — the no-swap
    byte-identity mode); ``cost`` promotes when the candidate's
    windowed expected cost is <= the live model's; ``cost:<ratio>``
    scales the bar (ratio > 1 is permissive, < 1 strict). Raises
    ``ValueError`` on anything else — a typo'd gate must never
    silently promote."""
    if value == "off":
        return ("off", None)
    head, sep, tail = value.partition(":")
    if head != "cost":
        raise ValueError(
            f"swap_gate= must be 'off' or 'cost[:<ratio>]', "
            f"got {value!r}"
        )
    if not sep:
        return ("cost", 1.0)
    try:
        ratio = float(tail)
    except ValueError:
        raise ValueError(
            f"swap_gate= ratio must be a float, got {tail!r}"
        )
    if not ratio > 0.0:
        raise ValueError(
            f"swap_gate= ratio must be > 0, got {ratio}"
        )
    return ("cost", ratio)


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """Lifecycle knobs; all bounded, all recorded in the block."""

    #: feedback items per partial-fit batch (one chunk per batch)
    adapt_batch: int = 16
    #: SGD iterations per chunk (absolute indices continue across
    #: chunks — the resumable-trajectory seam)
    adapt_iters: int = 20
    #: static row capacity of the feedback ring (oldest rows are
    #: overwritten; one compiled chunk program for the residency)
    capacity: int = 1024
    #: outcomes per windowed-statistics window (gate + drift currency)
    drift_window: int = 64
    #: parsed ``swap_gate=`` policy
    gate_mode: str = "cost"
    gate_ratio: Optional[float] = 1.0
    #: windowed-cost degradation factor that fires ``serve.drift``
    drift_factor: float = 1.5
    #: candidate checkpoint/promotion artifact directory (None =
    #: in-memory only, no resume)
    checkpoint_dir: Optional[str] = None
    #: misclassification costs for the windowed statistics
    cost_fp: float = 1.0
    cost_fn: float = 1.0
    #: adapter-step wedge detector (an adapter that stops beating for
    #: this long while busy is declared wedged; the candidate is
    #: discarded and live serving continues untouched). The default
    #: clears the first chunk's cold XLA compile on real chips (the
    #: repo's documented ~20-40 s window) with headroom — a cold
    #: compile must read as slow, never as a wedge
    watchdog_s: float = 120.0
    #: retry budget for a chaos/transiently-failed partial-fit chunk
    max_attempts: int = 3
    #: bounded feedback queue (oldest dropped + counted past it — the
    #: adapter must never become an unbounded memory leak)
    queue_depth: int = 4096
    #: roll a promoted model back when its windowed cost regresses
    #: past the pre-swap record
    rollback: bool = True

    @classmethod
    def from_query_map(cls, query_map, cost_fp: float = 1.0,
                       cost_fn: float = 1.0) -> "LifecycleConfig":
        """The ``adapt=``/``swap_gate=``/``drift_window=`` family (plus
        ``checkpoint_path=`` for the adapter's resume directory and
        the tuning knobs ``adapt_batch=``/``adapt_iters=``), validated
        with the IR's messages (pipeline/plan.py re-runs the grammar
        at parse time — defense in depth, same errors)."""

        def _int(name, default, floor=1):
            value = query_map.get(name, "")
            if not value:
                return default
            try:
                n = int(value)
            except ValueError:
                raise ValueError(
                    f"query parameter {name}= must be an integer, "
                    f"got {value!r}"
                )
            if n < floor:
                raise ValueError(f"{name}= must be >= {floor}, got {n}")
            return n

        gate_mode, gate_ratio = parse_swap_gate(
            query_map.get("swap_gate") or "cost"
        )
        return cls(
            adapt_batch=_int("adapt_batch", 16),
            adapt_iters=_int("adapt_iters", 20),
            drift_window=_int("drift_window", 64),
            gate_mode=gate_mode,
            gate_ratio=gate_ratio,
            checkpoint_dir=query_map.get("checkpoint_path") or None,
            cost_fp=float(cost_fp),
            cost_fn=float(cost_fn),
        )


class _Candidate:
    """One staged candidate generation: the chunk carry, the bounded
    feedback ring it trains on, and its shadow window."""

    def __init__(self, d: int, config: LifecycleConfig, live_weights,
                 generation: int):
        from ..models import sgd

        self.d = int(d)
        self.generation = int(generation)
        w, converged, n_updates = sgd.partial_fit_carry(
            d, weights=live_weights
        )
        self.w = np.asarray(w, np.float32)
        self.converged = bool(converged)
        self.n_updates = int(n_updates)
        #: absolute iteration index (the trajectory position)
        self.t = 0
        self.features = np.zeros((config.capacity, d), np.float32)
        self.labels = np.zeros((config.capacity,), np.float32)
        self.mask = np.zeros((config.capacity,), np.float32)
        self.rows_seen = 0
        self.batches = 0
        self.window = stats_mod.WindowedStatistics(
            config.drift_window, cost_fp=config.cost_fp,
            cost_fn=config.cost_fn,
        )

    # -- checkpoint pytree ------------------------------------------------

    def state(self) -> dict:
        return {
            "w": self.w,
            "converged": np.asarray(self.converged),
            "n_updates": np.asarray(self.n_updates, np.int32),
            "t": np.asarray(self.t, np.int64),
            "features": self.features,
            "labels": self.labels,
            "mask": self.mask,
            "rows_seen": np.asarray(self.rows_seen, np.int64),
        }

    def adopt(self, state: dict, batches: int, generation: int) -> None:
        self.w = np.asarray(state["w"], np.float32)
        self.converged = bool(state["converged"])
        self.n_updates = int(state["n_updates"])
        self.t = int(state["t"])
        self.features = np.asarray(state["features"], np.float32)
        self.labels = np.asarray(state["labels"], np.float32)
        self.mask = np.asarray(state["mask"], np.float32)
        self.rows_seen = int(state["rows_seen"])
        self.batches = int(batches)
        self.generation = int(generation)

    def block(self) -> dict:
        return {
            "generation": self.generation,
            "batches": self.batches,
            "t": self.t,
            "rows": min(self.rows_seen, len(self.mask)),
            "window": self.window.summary(),
        }


class LifecycleManager:
    """Streaming partial-fit + shadow-scored hot swap + drift
    detection for one :class:`~serve.engine.ServingEngine`.

    ``featurize`` defaults to the engine's own
    :meth:`~serve.engine.ServingEngine.featurize` (the same program
    that serves traffic — feedback rows cannot drift from served
    rows); tests and the SIGKILL worker inject a pure function.
    """

    def __init__(
        self,
        engine,
        config: Optional[LifecycleConfig] = None,
        featurize: Optional[Callable] = None,
    ):
        from ..models import linear

        self.engine = engine
        self.config = config or LifecycleConfig()
        self._featurize = featurize or (
            engine.featurize if engine is not None else None
        )
        if self._featurize is None:
            raise ValueError(
                "lifecycle needs an engine or an explicit featurize "
                "callable"
            )
        live = engine.classifier if engine is not None else None
        if live is not None and not isinstance(
            live, linear._LinearClassifier
        ):
            raise ValueError(
                "lifecycle adaptation trains the linear family "
                "(logreg/svm); "
                f"{type(live).__name__} has no partial-fit surface"
            )
        self._sgd_config = self._resolve_sgd_config(live)
        self._queue: "collections.deque" = collections.deque()
        self._cond = threading.Condition()
        self._pending = None  # (items, attempts) — a retrying batch
        self._processing = False
        self._stop = threading.Event()
        self._flush_requested = threading.Event()
        self.wedged = threading.Event()
        self._closed = False
        self._heartbeat = time.monotonic()
        self._thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

        self.counters = collections.Counter()
        self.generation = 0
        self.candidate: Optional[_Candidate] = None
        self.live_window = stats_mod.WindowedStatistics(
            self.config.drift_window, cost_fp=self.config.cost_fp,
            cost_fn=self.config.cost_fn,
        )
        #: the original model's first-full-window cost — the drift
        #: reference for the residency
        self.baseline_cost: Optional[float] = None
        self._last_drift_at = 0
        self.last_gate: Optional[dict] = None
        #: (classifier, pre-swap windowed cost) retained for rollback
        self._previous = None
        self.promoted_path: Optional[str] = None
        self._manager = None
        if self.config.checkpoint_dir:
            from ..checkpoint.manager import CheckpointManager

            self._manager = CheckpointManager(
                os.path.join(self.config.checkpoint_dir, "candidate"),
                max_to_keep=2,
            )
            self._try_resume()

    @staticmethod
    def _resolve_sgd_config(live):
        """The candidate's chunk config: the live model's own
        hyperparameters with the convergence early-stop DISABLED — a
        carried ``converged`` flag would freeze the candidate on its
        first quiet window and it could never adapt again."""
        import dataclasses as dc

        from ..models import sgd

        base = (
            live._sgd_config() if live is not None else sgd.SGDConfig()
        )
        return dc.replace(base, convergence_tol=0.0)

    # -- resume -----------------------------------------------------------

    def _try_resume(self) -> None:
        """Adopt the latest checkpointed candidate trajectory (a
        SIGKILL'd adapter resumes mid-trajectory; tests pin the
        resumed weights byte-identical to an uninterrupted run)."""
        step = self._manager.latest_step()
        if step is None:
            return
        meta = self._manager.read_metadata(step)
        extra = meta.get("extra", {})
        d = int(extra["d"])
        cand = _Candidate(
            d, self.config, None, int(extra.get("generation", 0))
        )
        state, _ = self._manager.restore(cand.state(), step=step)
        cand.adopt(
            state, batches=int(extra.get("batches", step)),
            generation=int(extra.get("generation", 0)),
        )
        self.candidate = cand
        self.generation = cand.generation
        logger.info(
            "lifecycle resumed candidate g%d at t=%d (%d batches) "
            "from %s", cand.generation, cand.t, cand.batches,
            self._manager.directory,
        )

    @property
    def batches_trained(self) -> int:
        return self.candidate.batches if self.candidate else 0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "LifecycleManager":
        from ..obs import domain as run_domain

        if self._thread is not None:
            return self
        domain = run_domain.capture()

        def adopted(body):
            def run():
                with run_domain.adopt(domain):
                    body()
            return run

        self._thread = threading.Thread(
            target=adopted(self._run), name="eeg-tpu-serve-adapter",
            daemon=True,
        )
        self._thread.start()
        self._watchdog_thread = threading.Thread(
            target=adopted(self._watchdog_run),
            name="eeg-tpu-serve-adapter-watchdog", daemon=True,
        )
        self._watchdog_thread.start()
        return self

    def close(self, flush: bool = True, timeout_s: float = 10.0) -> None:
        """Stop adapting. With ``flush`` the remaining feedback queue
        (including a final partial batch) is processed first, bounded
        by ``timeout_s``. Idempotent; feedback after close raises
        :class:`~serve.batcher.ServiceClosedError`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if flush and not self.wedged.is_set():
            self.flush(timeout_s=timeout_s)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in (self._thread, self._watchdog_thread):
            if t is not None:
                t.join(timeout=5.0)

    # -- feedback ---------------------------------------------------------

    def feedback(self, window, resolutions, label) -> bool:
        """One labeled served outcome. Returns True when queued;
        False when dropped (wedged adapter or a full queue — counted,
        never silent). Raises after :meth:`close`."""
        if self._closed:
            raise ServiceClosedError(
                "lifecycle is closed; feedback is not accepted "
                "(draining or stopped)"
            )
        self._count("feedback")
        if self.wedged.is_set():
            self._count("feedback_dropped")
            return False
        item = (
            np.array(window, copy=True),
            np.asarray(resolutions, np.float32).copy(),
            float(label),
        )
        with self._cond:
            if len(self._queue) >= self.config.queue_depth:
                self._queue.popleft()
                self._count("feedback_dropped")
            self._queue.append(item)
            self._cond.notify()
        return True

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued feedback item (including a final
        partial batch) has been processed. True = idle; False = the
        timeout (or a wedge) cut the wait."""
        self._flush_requested.set()
        with self._cond:
            self._cond.notify_all()
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                if self.wedged.is_set():
                    return False
                with self._cond:
                    idle = (
                        not self._queue
                        and self._pending is None
                        and not self._processing
                    )
                if idle:
                    return True
                time.sleep(0.005)
            return False
        finally:
            self._flush_requested.clear()

    # -- the adapter loop -------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        from .. import obs

        with self._lock:
            self.counters[key] += n
        obs.metrics.count(f"serve.{key}", n)

    def _next_batch(self, wait_s: float):
        """Pop the next batch: a retrying pending batch first, else a
        full ``adapt_batch`` run, else (under flush) the remainder."""
        with self._cond:
            if self._pending is not None:
                items, attempts = self._pending
                self._pending = None
                self._processing = True
                return items, attempts
            want = self.config.adapt_batch
            if len(self._queue) < want and not (
                self._flush_requested.is_set() and self._queue
            ):
                self._cond.wait(wait_s)
            if not self._queue:
                return None, 0
            if len(self._queue) < want and not self._flush_requested.is_set():
                return None, 0
            take = min(want, len(self._queue))
            items = [self._queue.popleft() for _ in range(take)]
            self._processing = True
            return items, 0

    def _run(self) -> None:
        while not self._stop.is_set():
            self._heartbeat = time.monotonic()
            items, attempts = self._next_batch(wait_s=0.05)
            if items is None:
                with self._cond:
                    self._processing = False
                continue
            try:
                self._process_batch(items, attempts)
            finally:
                with self._cond:
                    self._processing = False
                if self.wedged.is_set():
                    return

    def _process_batch(self, items, attempts: int) -> None:
        """One partial-fit chunk over one feedback batch: chaos gate,
        featurize, train (all-or-nothing commit), checkpoint, then
        score/gate/drift. A failure before commit retries the SAME
        batch (bounded), so the candidate trajectory is identical when
        the retry lands — chaos costs time, never a fork."""
        from ..obs import chaos, events

        self._heartbeat = time.monotonic()
        try:
            # one partial-fit chunk == one chaos opportunity
            chaos.maybe_fire("serve.adapt")
            feats = self._featurize_batch(items)
            labels = np.asarray([y for _w, _res, y in items], np.float32)
            live = self.engine.classifier if self.engine else None
            cand = self.candidate
            if cand is None:
                cand = _Candidate(
                    feats.shape[1], self.config,
                    live.weights if live is not None else None,
                    self.generation,
                )
            if feats.shape[1] != cand.d:
                raise ValueError(
                    f"feedback features are {feats.shape[1]}-d but the "
                    f"candidate trains {cand.d}-d rows"
                )
            # shadow decisions BEFORE this batch trains (honest
            # scoring: the candidate is judged on data it has not
            # seen) — captured as locals, committed only on success
            cand_w_before = cand.w
            new_state = self._train_chunk(cand, feats, labels)
        except Exception as e:
            self._count("adapt_failures")
            events.event(
                "serve.adapt_failed", attempt=attempts + 1,
                error=f"{type(e).__name__}: {e}",
            )
            if attempts + 1 >= self.config.max_attempts:
                self._count("adapt_dropped")
                logger.error(
                    "lifecycle dropped a feedback batch after %d "
                    "attempts (%s: %s)", attempts + 1,
                    type(e).__name__, e,
                )
                return
            with self._cond:
                self._pending = (items, attempts + 1)
            return
        if self.wedged.is_set():
            # the watchdog declared this adapter dead while the chunk
            # stalled: a late wake-up must not re-commit (or
            # checkpoint, or roll back with) a candidate the watchdog
            # already discarded
            return
        # commit: the candidate (possibly fresh) adopts the trained
        # state; everything after this point is side-effect machinery
        # that never needs a retry
        self.candidate = cand
        cand.adopt(
            new_state, batches=cand.batches + 1,
            generation=cand.generation,
        )
        self._count("adapt_batches")
        events.event(
            "serve.adapt_chunk", t=cand.t, batch=cand.batches,
            generation=cand.generation, rows=len(items),
        )
        self._checkpoint(cand)
        self._score(feats, labels, cand_w_before)
        # rollback is judged BEFORE promotion: a promoted model that
        # regressed must be restored before any new candidate is
        # allowed on top of it
        self._maybe_rollback()
        self._maybe_promote()
        self._maybe_drift()

    def _featurize_batch(self, items) -> np.ndarray:
        """Featurize one feedback batch, split into runs of equal
        per-channel resolutions (a batch may straddle a recording
        boundary; the featurizer scales one resolution vector per
        call, the batcher's coalescing-key rule)."""
        rows = []
        start = 0
        while start < len(items):
            res = items[start][1]
            end = start
            while end < len(items) and np.array_equal(
                items[end][1], res
            ):
                end += 1
            rows.append(np.asarray(
                self._featurize(
                    [w for w, _res, _y in items[start:end]], res
                ),
                np.float32,
            ))
            start = end
        return np.concatenate(rows, axis=0)

    def _train_chunk(self, cand: _Candidate, feats, labels) -> dict:
        """Ingest the batch into a COPY of the candidate's ring and
        run one chunk; returns the would-be state (the caller commits
        it). Absolute iteration indices + static buffer shapes: the
        one compiled program replays the one true trajectory."""
        from ..models import sgd

        features = cand.features.copy()
        lab = cand.labels.copy()
        mask = cand.mask.copy()
        rows_seen = cand.rows_seen
        cap = features.shape[0]
        for i in range(feats.shape[0]):
            slot = rows_seen % cap
            features[slot] = feats[i]
            lab[slot] = labels[i]
            mask[slot] = 1.0
            rows_seen += 1
        carry = (
            cand.w,
            np.asarray(cand.converged),
            np.asarray(cand.n_updates, np.int32),
        )
        w, converged, n_updates = sgd.partial_fit_linear(
            carry, cand.t, features, lab, self._sgd_config,
            self.config.adapt_iters, sample_mask=mask,
        )
        self._count("adapt_chunks")
        return {
            "w": np.asarray(w, np.float32),
            "converged": np.asarray(bool(converged)),
            "n_updates": np.asarray(int(n_updates), np.int32),
            "t": np.asarray(cand.t + self.config.adapt_iters, np.int64),
            "features": features,
            "labels": lab,
            "mask": mask,
            "rows_seen": np.asarray(rows_seen, np.int64),
        }

    def _checkpoint(self, cand: _Candidate) -> None:
        if self._manager is None:
            return
        try:
            self._manager.save(
                cand.batches, cand.state(),
                extra={
                    "d": cand.d,
                    "batches": cand.batches,
                    "generation": cand.generation,
                },
            )
        except OSError as e:
            # a full disk must degrade resume, never adaptation
            self._count("checkpoint_failures")
            logger.warning("lifecycle checkpoint failed: %s", e)

    # -- scoring / gate / drift -------------------------------------------

    def _decide(self, feats, weights, intercept, threshold):
        margins = feats @ np.asarray(weights, np.float32) + intercept
        return (margins > threshold).astype(np.float64)

    def _score(self, feats, labels, cand_w_before) -> None:
        live = self.engine.classifier if self.engine else None
        if live is not None and live.weights is not None:
            live_preds = self._decide(
                feats, live.weights, live.intercept,
                live.margin_threshold,
            )
            for p, y in zip(live_preds, labels):
                self.live_window.add(p, y)
        cand = self.candidate
        if cand is not None:
            threshold = live.margin_threshold if live is not None else 0.0
            cand_preds = self._decide(
                feats, cand_w_before, 0.0, threshold
            )
            for p, y in zip(cand_preds, labels):
                cand.window.add(p, y)

    def _maybe_promote(self) -> None:
        cand = self.candidate
        if (
            cand is None
            or self.config.gate_mode == "off"
            or self.engine is None
            or self.wedged.is_set()
        ):
            return
        if not (cand.window.full and self.live_window.full):
            return
        live_cost = self.live_window.expected_cost()
        cand_cost = cand.window.expected_cost()
        import math

        ok = (
            not math.isnan(live_cost)
            and not math.isnan(cand_cost)
            and cand_cost <= live_cost * self.config.gate_ratio
        )
        self.last_gate = {
            "candidate_cost": round(cand_cost, 6),
            "live_cost": round(live_cost, 6),
            "ratio": self.config.gate_ratio,
            "promote": bool(ok),
            "generation": cand.generation,
        }
        if not ok:
            return
        self._attempt_swap(cand)

    def _attempt_swap(self, cand: _Candidate) -> None:
        """One promotion attempt (the ``serve.swap`` chaos point). A
        failure leaves the LIVE MODEL UNTOUCHED and the candidate
        retained — the gate simply retries after the next batch."""
        from ..obs import chaos, events

        live = self.engine.classifier
        try:
            chaos.maybe_fire("serve.swap")
            clone = self._clone_with_weights(
                live, cand.w, live.margin_threshold
            )
            promoted_path = None
            if self.config.checkpoint_dir:
                promoted_path = os.path.join(
                    self.config.checkpoint_dir, "promoted"
                )
                # the batch-parity artifact: load_clf= of this file
                # predicts byte-identically to the swapped service
                clone.save(promoted_path)
            previous = self.engine.swap_model(clone)
        except Exception as e:
            self._count("swap_failures")
            events.event(
                "serve.swap_failed", generation=cand.generation,
                error=f"{type(e).__name__}: {e}",
            )
            logger.warning(
                "lifecycle promotion attempt failed (%s: %s); live "
                "model untouched, candidate retained",
                type(e).__name__, e,
            )
            return
        pre_swap_cost = self.live_window.expected_cost()
        self._previous = (previous, pre_swap_cost)
        self.promoted_path = (
            promoted_path + ".npz" if promoted_path else None
        )
        self._count("swaps")
        events.event(
            "serve.promoted", generation=cand.generation,
            candidate_cost=self.last_gate["candidate_cost"],
            live_cost=self.last_gate["live_cost"],
        )
        logger.info(
            "lifecycle promoted candidate g%d (windowed cost %.4f vs "
            "live %.4f)", cand.generation,
            self.last_gate["candidate_cost"],
            self.last_gate["live_cost"],
        )
        # bounded retention: the promoted trajectory's checkpoints are
        # superseded — the disk footprint is the live+candidate pair,
        # never the swap history (the PR 2 elastic clear() contract)
        if self._manager is not None:
            self._manager.clear()
        self.generation += 1
        self.candidate = None
        # the promoted model must earn its own windowed record
        self.live_window.reset()

    @staticmethod
    def _clone_with_weights(live, weights, margin_threshold):
        """A fresh classifier of the live model's class carrying the
        candidate weights: natively-trained linear semantics
        (interceptless) with the operator's serving threshold carried
        over, so a recall-tuned service stays tuned across a swap."""
        clone = type(live)()
        clone.set_config(dict(live.config))
        clone.weights = np.asarray(weights, np.float32)
        clone.intercept = 0.0
        clone.margin_threshold = float(margin_threshold)
        return clone

    def _maybe_rollback(self) -> None:
        if (
            self._previous is None
            or not self.config.rollback
            or self.engine is None
            or self.wedged.is_set()
        ):
            return
        if not self.live_window.full:
            return
        import math

        previous, pre_swap_cost = self._previous
        cost = self.live_window.expected_cost()
        if math.isnan(cost) or math.isnan(pre_swap_cost):
            return
        if cost <= pre_swap_cost * (self.config.gate_ratio or 1.0):
            # the promoted model held its gate promise over a full
            # post-swap window: the rollback arm disarms
            self._previous = None
            return
        from ..obs import events

        self.engine.swap_model(previous)
        self._previous = None
        self._count("rollbacks")
        events.event(
            "serve.rollback",
            post_swap_cost=round(cost, 6),
            pre_swap_cost=round(pre_swap_cost, 6),
        )
        logger.warning(
            "lifecycle ROLLED BACK the promoted model: windowed cost "
            "%.4f regressed past the pre-swap record %.4f",
            cost, pre_swap_cost,
        )
        self.live_window.reset()

    def _maybe_drift(self) -> None:
        if not self.live_window.full:
            return
        import math

        cost = self.live_window.expected_cost()
        if math.isnan(cost):
            return
        if self.baseline_cost is None:
            self.baseline_cost = cost
            return
        if (
            self.live_window.seen - self._last_drift_at
            < self.config.drift_window
        ):
            return  # at most one firing per window span
        bar = max(
            self.baseline_cost * self.config.drift_factor,
            self.baseline_cost + 0.01,
        )
        if cost <= bar:
            return
        from ..obs import events

        self._last_drift_at = self.live_window.seen
        self._count("drift")
        events.event(
            "serve.drift", cost=round(cost, 6),
            baseline=round(self.baseline_cost, 6),
            window=self.config.drift_window,
        )
        logger.warning(
            "serve.drift: windowed expected cost %.4f exceeds the "
            "baseline %.4f (factor %.2f over window %d) — "
            "recalibration advised", cost, self.baseline_cost,
            self.config.drift_factor, self.config.drift_window,
        )

    # -- the adapter watchdog ---------------------------------------------

    def _watchdog_run(self) -> None:
        poll = max(0.01, self.config.watchdog_s / 4.0)
        while not self._stop.is_set():
            # stop-interruptible sleep: close() must not pay a poll
            # interval (or its join timeout) waiting this thread out
            if self._stop.wait(poll):
                return
            with self._cond:
                busy = self._processing
            age = time.monotonic() - self._heartbeat
            if busy and age > self.config.watchdog_s:
                self.wedged.set()
                self._count("lifecycle_wedged")
                from ..obs import events

                events.event(
                    "serve.lifecycle_wedged",
                    heartbeat_age_s=round(age, 2),
                )
                logger.error(
                    "lifecycle adapter wedged (heartbeat %.1fs old); "
                    "candidate discarded, live serving continues",
                    age,
                )
                # the wedged thread may never return: the candidate is
                # discarded HERE so a later wake-up cannot promote a
                # model trained by a half-dead adapter
                self.candidate = None
                with self._cond:
                    self._queue.clear()
                return

    # -- observability ----------------------------------------------------

    @property
    def state(self) -> str:
        if self.wedged.is_set():
            return "wedged"
        if self._closed:
            return "closed"
        if self.candidate is not None:
            return "adapting"
        return "live"

    def block(self) -> dict:
        """The ``lifecycle`` block for run reports and bench lines."""
        with self._lock:
            counters = dict(self.counters)
        # one snapshot: the adapter thread clears self.candidate on
        # promotion — a monitor reading mid-swap must not None-deref
        cand = self.candidate
        return {
            "enabled": True,
            "state": self.state,
            "generation": self.generation,
            "config": {
                "adapt_batch": self.config.adapt_batch,
                "adapt_iters": self.config.adapt_iters,
                "capacity": self.config.capacity,
                "drift_window": self.config.drift_window,
                "swap_gate": (
                    "off" if self.config.gate_mode == "off"
                    else f"cost:{self.config.gate_ratio}"
                ),
                "drift_factor": self.config.drift_factor,
            },
            "feedback": {
                "received": counters.get("feedback", 0),
                "dropped": counters.get("feedback_dropped", 0),
                "batches": counters.get("adapt_batches", 0),
                "chunks": counters.get("adapt_chunks", 0),
                "failures": counters.get("adapt_failures", 0),
                "dropped_batches": counters.get("adapt_dropped", 0),
            },
            "candidate": None if cand is None else cand.block(),
            "live_window": self.live_window.summary(),
            "baseline_cost": (
                None if self.baseline_cost is None
                else round(self.baseline_cost, 6)
            ),
            "gate": self.last_gate,
            "swaps": counters.get("swaps", 0),
            "swap_failures": counters.get("swap_failures", 0),
            "rollbacks": counters.get("rollbacks", 0),
            "rollback_armed": self._previous is not None,
            "drift_events": counters.get("drift", 0),
            "promoted_path": self.promoted_path,
            "checkpoint": (
                None if self._manager is None else {
                    "dir": self._manager.directory,
                    "steps": len(self._manager.all_steps()),
                }
            ),
            "wedged": self.wedged.is_set(),
        }
