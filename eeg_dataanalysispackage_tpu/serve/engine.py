"""The resident serving program: raw epoch windows -> predictions.

One micro-batch of requests — each carrying the raw (unscaled int16)
samples of one stimulus-locked window — is coalesced into a synthetic
recording stream and run through **the same fused featurizer the
batch pipeline compiles** (``ops.device_ingest.
make_device_ingest_featurizer``: resolution scaling, window gather,
baseline correction, DWT cascade matmul, L2 normalization in one XLA
program), with the linear-family margin fused onto the end. Reusing
the batch path's program (not a re-implementation of it) is what makes
the parity contract structural — with one shape caveat, measured not
assumed: XLA specializes numerics per compiled shape, and the epoch
**capacity** (the row count entering the DWT matmul) is part of the
shape. The batch planner buckets capacity to multiples of 64
(``plan_ingest(capacity_multiple=64)``), so this engine buckets its
own capacity to the same multiple: a served window then runs through
the *same-shaped* program that featurized it in the batch pipeline
and its features are **bit-identical** for sessions inside one bucket
(pinned in tests/test_serve.py and tools/serve_bench.py's parity
block). Across bucket boundaries (a recording with >capacity kept
epochs) features are tolerance-level identical — the exact contract
the degradation ladder's rungs already share (~1e-7, decision-
irrelevant in practice), with predictions still pinned equal.

Shapes are static: the stream is sized for the bucketed ``capacity``,
positions/mask padded to it, so every micro-batch size from 1 to
capacity reuses ONE compiled program — no retrace under bursty load. The staged stream buffer is donated to
the program on accelerator backends (its int16 bytes are dead after
the scale), mirroring the batch path's donation discipline; on CPU
donation is skipped (XLA:CPU cannot alias them and would warn per
call).

Above the fused program sits the **mega rung** (ops/serve_mega.py):
the whole request path — int16 decode, window cut, baseline, DWT
cascade, feature normalize, linear margin — as ONE kernel over the
regular serving layout, whose only HBM output is the margin vector.
The engine ladder is mega → fused → host: the mega rung is promoted
at warmup only after a margin-parity pin against the fused program
(the ladder-rung tolerance class), a persistently failing mega
program steps down to fused without dropping the in-flight batch,
and the PR 6 fused→host latch below it is unchanged. Within one
capacity bucket a window's mega margin is bit-identical whatever
batch it rides in (row-independent compute, one compiled program),
which is what keeps served statistics byte-identical to the batch
path across the rung change.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..epochs.extractor import BalanceState
from ..models import linear
from ..ops import device_ingest
from ..utils import constants


def _donate_argnums() -> tuple:
    """Donate the staged stream only where the backend can alias it."""
    return () if jax.default_backend() == "cpu" else (0,)


@functools.lru_cache(maxsize=None)
def _serving_program(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    n_channels: int,
    pre: int,
    post: int,
    with_margin: bool,
    precision: str = "f32",
):
    """The jitted micro-batch program, cached per geometry (shared by
    every service instance with the same acquisition config).

    ``with_margin=True`` fuses the linear-family margin matvec onto
    the featurizer — features never round-trip to the host before the
    decision. Weights ride as a traced argument, so swapping a model
    recompiles nothing. ``precision="bf16"`` runs the featurizer's
    cascade contraction on bfloat16 epochs; ``precision="int8"`` /
    ``"int4"`` compute f32 features and quantize the finished rows per
    subband (ops/decode_ingest.quantize_dequantize_int8 /
    ops/quant.quantize_dequantize_int4) before the margin — every
    non-f32 rung gates at warmup and falls back to the f32 program
    above its documented tolerance.
    """
    from ..ops import decode_ingest, quant

    featurizer = device_ingest.make_device_ingest_featurizer(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        channels=tuple(range(1, n_channels + 1)),
        pre=pre,
        post=post,
        precision="bf16" if precision == "bf16" else "f32",
    )

    def features_of(raw, resolutions, positions, mask):
        feats = featurizer(raw, resolutions, positions, mask)
        if precision == "int8":
            feats, _ = decode_ingest.quantize_dequantize_int8(
                feats, feature_size
            )
        elif precision == "int4":
            feats, _ = quant.quantize_dequantize_int4(
                feats, feature_size
            )
        return feats

    if with_margin:

        def run(raw, resolutions, positions, mask, weights):
            feats = features_of(raw, resolutions, positions, mask)
            return feats, feats @ weights

    else:

        def run(raw, resolutions, positions, mask):
            return features_of(raw, resolutions, positions, mask), None

    return jax.jit(run, donate_argnums=_donate_argnums())


#: lane width of the multi-tenant weight stack (serve/multiplex.py):
#: per-tenant weight vectors live in the columns of one ``(d, 128)``
#: matrix — the same 128-lane padding the mega kernel's weight matrix
#: already carries, so one compiled program serves any tenant mix and
#: a tenant add/swap rewrites one column (0 recompiles).
MAX_TENANTS = 128


@functools.lru_cache(maxsize=None)
def _multi_serving_program(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    n_channels: int,
    pre: int,
    post: int,
    precision: str = "f32",
    weights_precision: str = "f32",
):
    """The tenant-stacked twin of :func:`_serving_program`: one jitted
    program ``(raw, resolutions, positions, mask, weight_matrix
    (d, 128), tenant_lanes (capacity,) int32) -> (feats, margins)``
    serving every tenant mix.

    Bit-identity is the load-bearing constraint: row ``i``'s margin
    must be byte-identical to what the SOLO program computes for
    tenant ``tenant_lanes[i]`` (the multiplex parity contract,
    tests/test_multitenant.py). A single ``feats @ weight_matrix``
    followed by a column gather is NOT that — XLA's matmul tiles the
    reduction differently from its matvec (measured: ~3e-5 margin
    drift on CPU) — so the program unrolls the stack into 128 matvecs,
    each the byte-identical primitive the solo program runs, and
    gathers the requested column per row. Same flops as the matmul
    (the gather is free), one compile, still zero-recompile on swap:
    the weight matrix rides as a traced argument exactly like the solo
    weights vector.

    ``weights_precision="int8"|"int4"`` (ops/quant.py) changes WHAT is
    resident, not the math's shape: the program takes the packed
    int8/int4 matrix plus per-lane scales, dequantizes INSIDE
    (elementwise — the packed payload is what lives on device), and
    runs the same 128 unrolled matvecs on the reconstruction. Swap
    stays zero-recompile (packed + scales are traced arguments), and
    per-tenant margin parity vs the f32 stack is gated at warmup by
    the multiplexed engine (quant.weights_gate_tolerance), never
    assumed.
    """
    import jax.numpy as jnp

    from ..ops import quant

    featurizer = device_ingest.make_device_ingest_featurizer(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        channels=tuple(range(1, n_channels + 1)),
        pre=pre,
        post=post,
        precision="bf16" if precision == "bf16" else "f32",
    )
    d = n_channels * feature_size

    def margins_of(feats, weight_matrix, tenant_lanes):
        # 128 unrolled matvecs — each bitwise the solo program's
        # ``feats @ weights`` — then a per-row column pick
        columns = jnp.stack(
            [feats @ weight_matrix[:, t] for t in range(MAX_TENANTS)],
            axis=1,
        )
        return jnp.take_along_axis(
            columns, tenant_lanes[:, None], axis=1
        )[:, 0]

    if weights_precision == "f32":

        def run(raw, resolutions, positions, mask, weight_matrix,
                tenant_lanes):
            feats = featurizer(raw, resolutions, positions, mask)
            return feats, margins_of(feats, weight_matrix, tenant_lanes)

    else:

        def run(raw, resolutions, positions, mask, packed, scales,
                tenant_lanes):
            feats = featurizer(raw, resolutions, positions, mask)
            weight_matrix = quant.dequantize_weight_stack(
                packed, scales, weights_precision, d
            )
            return feats, margins_of(feats, weight_matrix, tenant_lanes)

    return jax.jit(run, donate_argnums=_donate_argnums())


class ServingEngine:
    """Executes micro-batches for one loaded classifier.

    ``classifier`` is any registry classifier that has been trained or
    loaded. The linear family (logreg/svm with native float32 weights)
    runs fully fused — window bytes to margin in one program; every
    other classifier gets the fused featurizer plus its own host-side
    ``predict`` on the resulting rows (the exact call the batch
    pipeline's ``test_features`` makes, so parity holds there too).
    """

    def __init__(
        self,
        classifier,
        wavelet_index: int = 8,
        n_channels: int = constants.USED_CHANNELS,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        capacity: int = 64,
        host_extractor=None,
        precision: str = "f32",
        engine_rung: str = "auto",
    ):
        """``pre``/``post`` parameterize the window length from the
        workload's config — the engine no longer assumes the P300
        path's fixed geometry (the seizure service runs ``pre=0,
        post=<window>`` windows). ``host_extractor`` pins the engine
        to the host rung with the given registry feature extractor
        instead of compiling the fused P300 program — the seizure
        workload's serving mode, whose subband features have no fused
        twin; requests then take the exact host featurize+predict
        path the batch run takes, which is what makes served
        statistics identical to it.

        ``engine_rung`` picks the top of the serving ladder:
        ``"auto"`` resolves per platform through the mega decision
        path (ops/serve_mega.default_engine_rung — mega on CPU, the
        recorded chip decision on accelerators), ``"mega"`` forces
        the megakernel attempt, ``"fused"`` pins the engine to the
        PR 6 fused program (the bench's same-process twin). Whatever
        is requested, the mega rung only ever serves after its warmup
        parity gate passes against the fused program."""
        from ..ops import decode_ingest

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if precision not in decode_ingest.PRECISIONS:
            raise ValueError(
                f"unknown precision {precision!r}; use one of "
                f"{decode_ingest.PRECISIONS}"
            )
        if engine_rung not in ("auto", "mega", "fused"):
            raise ValueError(
                f"unknown engine_rung {engine_rung!r}; use 'auto', "
                f"'mega', or 'fused'"
            )
        #: non-f32 precision request + its warmup gate decision; None
        #: for plain f32 engines (schema-stable in the serve stats
        #: block)
        self.precision_record = None
        #: mega-rung resolution + its warmup parity gate; None when
        #: the rung was never a candidate (host-extractor mode,
        #: non-linear classifiers, non-f32 precision, pre=0 geometry)
        self.mega_record = None
        self._engine_rung_requested = engine_rung
        self._mega_program = None
        self._mega_stride = None
        self._consecutive_mega_failures = 0
        self._precision = precision
        self.classifier = classifier
        self.n_channels = int(n_channels)
        self.pre = int(pre)
        self.post = int(post)
        self.window_len = self.pre + self.post
        # bucket to the batch planner's capacity multiple: the program
        # shape (and therefore its f32 numerics) then MATCHES the
        # batch path's, which is what makes served features
        # bit-identical to load_features_device's (module docstring)
        self.capacity = device_ingest._round_capacity(int(capacity), 64)
        self.wavelet_index = int(wavelet_index)
        self._geometry = (
            int(wavelet_index), int(epoch_size), int(skip_samples),
            int(feature_size), self.n_channels, self.pre, self.post,
        )
        self.epoch_size = int(epoch_size)
        self.skip_samples = int(skip_samples)
        self.feature_size = int(feature_size)
        if host_extractor is not None:
            # host-extractor mode: no fused program exists for this
            # feature family — the host floor IS the serving path,
            # not a degradation (rung reads "host" from the start)
            self._fused_linear = False
            self._program = None
            self._rung = "host"
            self._host_fe = host_extractor
            self._consecutive_fused_failures = 0
            self._degrade_after = 2
            self._warmed = False
            self._positions = np.zeros((0,), np.int32)
            return
        # the fused-margin fast path: native float32 linear weights
        # (an imported f64 MLlib model keeps its bit-exact host-f64
        # predict; fusing would downcast it)
        self._fused_linear = (
            isinstance(classifier, linear._LinearClassifier)
            and classifier.weights is not None
            and classifier.weights.dtype == np.float32
        )
        self._program = _serving_program(
            *self._geometry,
            with_margin=self._fused_linear,
            precision=precision,
        )
        # the serving arm of the degradation ladder (io/provider's
        # pallas->block->xla->host contract, collapsed to its two
        # serving-relevant rungs): the fused device program, with a
        # host featurize+predict floor. Transient failures are the
        # batcher's retry job; PERSISTENT fused failures (a backend
        # that broke mid-residency) step the engine down permanently —
        # slower, but the service survives, exactly like the batch
        # ladder. An operator re-promotes by restarting the service.
        self._rung = "fused"
        self._consecutive_fused_failures = 0
        self._degrade_after = 2
        self._host_fe = None
        self._warmed = False
        # static plan for the synthetic stream: window i lives at
        # [i * window_len, (i + 1) * window_len), so its marker
        # position is i * window_len + pre — one plan for every batch
        self._positions = (
            np.arange(self.capacity, dtype=np.int32) * self.window_len
            + self.pre
        )

    # -- execution ------------------------------------------------------

    def execute(
        self,
        windows: Sequence[np.ndarray],
        resolutions: np.ndarray,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Run one micro-batch: ``windows`` is a sequence of
        ``(n_channels, window_len)`` raw sample arrays (int16 for
        INT_16 recordings; float32 with unit resolutions otherwise —
        the ``stage_raw`` convention), all sharing ``resolutions``.

        Returns ``(predictions (B,) float64, margins (B,) or None)``.
        """
        n = len(windows)
        if n == 0:
            return np.zeros((0,), np.float64), None
        if n > self.capacity:
            raise ValueError(
                f"micro-batch of {n} exceeds engine capacity "
                f"{self.capacity}"
            )
        if self._rung == "host":
            return self._execute_host(windows, resolutions)
        if self._rung == "mega":
            try:
                result = self._execute_mega(windows, resolutions)
            except ValueError:
                # shape/validation errors are the caller's bug, not a
                # backend failure — never a reason to degrade
                raise
            except Exception as e:
                self._consecutive_mega_failures += 1
                if self._consecutive_mega_failures < self._degrade_after:
                    raise
                # the mega rung broke mid-residency: step down to the
                # fused program and serve THIS batch through it — the
                # ladder degrades, the request is never dropped
                from .. import obs
                from ..obs import events
                import logging

                self._rung = "fused"
                if self.mega_record is not None:
                    self.mega_record["used"] = "fused"
                    self.mega_record["error"] = (
                        f"{type(e).__name__}: {e}"
                    )
                obs.metrics.count("serve.mega_degraded_to_fused")
                events.event(
                    "serve.mega_degraded", to="fused",
                    error=f"{type(e).__name__}: {e}",
                    consecutive_failures=(
                        self._consecutive_mega_failures
                    ),
                )
                logging.getLogger(__name__).error(
                    "serve.degrade landed=fused after %d consecutive "
                    "mega failures (%s: %s); serving continues on the "
                    "fused program",
                    self._consecutive_mega_failures,
                    type(e).__name__, e,
                )
            else:
                self._consecutive_mega_failures = 0
                return result
        try:
            result = self._execute_fused(windows, resolutions)
        except ValueError:
            # shape/validation errors are the caller's bug, not a
            # backend failure — never a reason to degrade
            raise
        except Exception as e:
            self._consecutive_fused_failures += 1
            if self._consecutive_fused_failures >= self._degrade_after:
                from .. import obs
                from ..obs import events
                import logging

                self._rung = "host"
                obs.metrics.count("serve.degraded_to_host")
                events.event(
                    "serve.degraded", to="host",
                    error=f"{type(e).__name__}: {e}",
                    consecutive_failures=(
                        self._consecutive_fused_failures
                    ),
                )
                logging.getLogger(__name__).error(
                    "serve.degrade landed=host after %d consecutive "
                    "fused failures (%s: %s); serving continues on "
                    "the host floor",
                    self._consecutive_fused_failures,
                    type(e).__name__, e,
                )
                return self._execute_host(windows, resolutions)
            raise
        self._consecutive_fused_failures = 0
        return result

    def _execute_fused(self, windows, resolutions):
        n = len(windows)
        # one snapshot per batch: a lifecycle hot swap
        # (serve/lifecycle.py) replaces the classifier object between
        # batches — reading it once makes the batch wholly-old or
        # wholly-new, never weights from one model with the intercept
        # of another
        clf = self.classifier
        stream, mask = self._stage_fused_stream(windows)
        # explicit staging so the program can donate the buffer (the
        # int16 stream is dead after the on-device scale)
        staged = jax.device_put(stream)
        res = np.asarray(resolutions, dtype=np.float32)
        if self._fused_linear:
            feats, margins = self._program(
                staged, res, self._positions, mask,
                clf.weights,
            )
            margins = np.asarray(margins[:n]) + clf.intercept
            predictions = (
                margins > clf.margin_threshold
            ).astype(np.float64)
            return predictions, margins
        feats, _ = self._program(staged, res, self._positions, mask)
        predictions = np.asarray(
            clf.predict(np.asarray(feats)[:n]),
            dtype=np.float64,
        )
        return predictions, None

    def _stage_fused_stream(self, windows):
        """Lay a micro-batch out as the fused program's synthetic
        stream: ``(stream, mask)`` with window i at
        ``[i*window_len, (i+1)*window_len)`` and the mask marking the
        live rows — the one staging layout shared by execution,
        :meth:`featurize`, and the warmup gates."""
        n = len(windows)
        stream = np.zeros(
            (self.n_channels, self.capacity * self.window_len),
            dtype=np.asarray(windows[0]).dtype,
        )
        for i, w in enumerate(windows):
            w = np.asarray(w)
            if w.shape != (self.n_channels, self.window_len):
                raise ValueError(
                    f"window {i} has shape {w.shape}, expected "
                    f"({self.n_channels}, {self.window_len})"
                )
            stream[:, i * self.window_len:(i + 1) * self.window_len] = w
        mask = np.zeros(self.capacity, dtype=bool)
        mask[:n] = True
        return stream, mask

    def _execute_mega(self, windows, resolutions):
        """The megakernel rung: the micro-batch laid out at the
        128-padded window stride and run through ONE program — decode,
        window cut, baseline, cascade, normalize, margin — whose only
        output is the margin vector (ops/serve_mega.py). Features
        never materialize; each window's compute is row-independent,
        so its margin is bit-identical whatever batch it rides in
        (one compiled program per bucket, like the fused rung)."""
        from ..ops import serve_mega

        n = len(windows)
        # one classifier snapshot per batch (the hot-swap tear guard
        # _execute_fused documents)
        clf = self.classifier
        stream = serve_mega.stage_mega_stream(
            windows, self.n_channels, self.window_len,
            self._mega_stride, self.capacity,
        )
        staged = jax.device_put(stream)
        res = np.asarray(resolutions, dtype=np.float32)
        margins = np.asarray(
            self._mega_program(staged, res, clf.weights)
        )[:n] + clf.intercept
        predictions = (
            margins > clf.margin_threshold
        ).astype(np.float64)
        return predictions, margins

    def _execute_host(self, windows, resolutions):
        """The host floor: scale + baseline-correct on the host and
        run the registry DWT extractor plus the classifier's own
        predict — the reference-shaped path, device-free. Features are
        tolerance-level vs the fused rung (the ladder's contract);
        the service survives a broken device backend."""
        clf = self.classifier  # the hot-swap tear guard
        feats = self._host_features(windows, resolutions)
        predictions = np.asarray(
            clf.predict(feats), dtype=np.float64
        )
        return predictions, None

    def _host_features(self, windows, resolutions) -> np.ndarray:
        """Host-floor featurization: scale + baseline-correct and run
        the registry extractor (the reference-shaped path, shared by
        :meth:`_execute_host` and :meth:`featurize` in host mode)."""
        from ..features import registry as fe_registry

        if self._host_fe is None:
            self._host_fe = fe_registry.create(
                f"dwt-{self.wavelet_index}"
            )
        res = np.asarray(resolutions, dtype=np.float64)
        epochs = []
        for w in windows:
            w = np.asarray(w)
            if w.shape != (self.n_channels, self.window_len):
                raise ValueError(
                    f"window has shape {w.shape}, expected "
                    f"({self.n_channels}, {self.window_len})"
                )
            scaled = w.astype(np.float64) * res[:, None]
            if self.pre:
                base = scaled[:, : self.pre].mean(axis=1)
                epochs.append((scaled - base[:, None])[:, self.pre:])
            else:
                # continuous windows (pre=0, the seizure geometry)
                # have no prestimulus segment to correct against
                epochs.append(scaled)
        return np.asarray(
            self._host_fe.extract_batch(np.stack(epochs))
        )

    def featurize(
        self,
        windows: Sequence[np.ndarray],
        resolutions: np.ndarray,
    ) -> np.ndarray:
        """Feature rows for ``windows`` through the engine's OWN path
        — the fused program where one exists (margins discarded), the
        host extractor otherwise. The lifecycle's partial-fit seam
        (serve/lifecycle.py): feedback rows come from the same
        computation that features served traffic, so a candidate
        trains on exactly what its shadow scoring judges. Batches
        larger than the capacity bucket are featurized in capacity-
        sized slices."""
        n = len(windows)
        if n == 0:
            d = self.n_channels * self.feature_size
            return np.zeros((0, d), np.float32)
        if n > self.capacity:
            parts = [
                self.featurize(windows[i:i + self.capacity], resolutions)
                for i in range(0, n, self.capacity)
            ]
            return np.concatenate(parts, axis=0)
        if self._program is None or self._rung == "host":
            return np.asarray(
                self._host_features(windows, resolutions), np.float32
            )
        stream, mask = self._stage_fused_stream(windows)
        res = np.asarray(resolutions, dtype=np.float32)
        args = [jax.device_put(stream), res, self._positions, mask]
        if self._fused_linear:
            args.append(self.classifier.weights)
        feats, _ = self._program(*args)
        return np.asarray(feats)[:n].astype(np.float32, copy=False)

    def swap_model(self, classifier, tenant=None):
        """Hot-swap the served model; returns the displaced one.

        ``tenant`` is the multiplexed engine's keyed-swap surface
        (serve/multiplex.py rewrites one column of the tenant stack);
        this single-model engine refuses it loudly rather than
        silently swapping the wrong tenant's traffic.

        The zero-recompile contract: on the fused-linear path the
        weights ride as a TRACED argument of the compiled program
        (module docstring), so a replacement with float32 weights of
        the same shape re-executes the existing executable — the swap
        is one attribute assignment, an in-flight batch reads the
        classifier once (:meth:`_execute_fused`) and is served wholly
        by the old or wholly by the new model, and nothing is dropped.
        A shape/dtype mismatch is refused loudly: it would retrace
        inside the batcher, where the watchdog reads a long compile as
        a wedge."""
        if tenant is not None:
            raise ValueError(
                f"this engine serves one model; a tenant-keyed swap "
                f"(tenant={tenant!r}) needs the MultiplexedEngine "
                f"(serve/multiplex.py)"
            )
        old = self.classifier
        if self._fused_linear:
            w = getattr(classifier, "weights", None)
            if (
                w is None
                or w.dtype != np.float32
                or w.shape != old.weights.shape
            ):
                raise ValueError(
                    "hot swap requires float32 linear weights of the "
                    f"live shape {old.weights.shape} (the "
                    "zero-recompile contract); got "
                    f"{None if w is None else (w.dtype, w.shape)}"
                )
        elif getattr(classifier, "predict", None) is None:
            raise ValueError(
                "hot swap requires a classifier with a predict surface"
            )
        self.classifier = classifier
        return old

    def warmup(self) -> None:
        """Compile the program before traffic arrives (one dummy
        batch), so the first real request doesn't pay XLA latency —
        and, as importantly, so a long cold compile can never happen
        inside the batcher where the watchdog would read it as a
        wedge. A non-f32 engine additionally runs its accuracy gate
        here (:meth:`_precision_warmup_gate`) — above the documented
        tolerance the engine swaps to the f32 program before a single
        request is served — and an f32 fused-linear engine resolves
        its mega rung (:meth:`_mega_warmup`: the megakernel is built,
        parity-pinned against the fused program, and only promoted
        when the pin holds). Every decision lands in the serve stats
        block. Idempotent."""
        if self._warmed:
            return
        if self._program is None:
            # host-extractor mode: pure numpy featurization — there
            # is no XLA program to compile ahead of traffic. A non-f32
            # request still gets a RECORDED decision (the gate
            # policy's "recorded, never silent"): the host extractor
            # computes f64, exactly like the batch pipeline's host
            # floor records used=host-f64.
            if self._precision != "f32":
                self.precision_record = {
                    "requested": self._precision,
                    "used": "host-f64",
                    "gate": None,
                }
            self._warmed = True
            return
        if self._precision != "f32":
            self._precision_warmup_gate()
        self._mega_warmup()
        # both request dtypes the stage_raw convention produces:
        # int16 (INT_16 recordings) and the float32 fallback — a
        # non-INT_16 session must not pay its cold trace inside the
        # batcher either (and with the mega rung landed, this is also
        # its compile-before-traffic warmup)
        for dtype in (np.int16, np.float32):
            self.execute(
                [np.zeros((self.n_channels, self.window_len), dtype)],
                np.ones(self.n_channels, np.float32),
            )
        self._warmed = True

    def _gate_windows(self):
        """Deterministic synthetic int16 gate windows — full-amplitude
        signal over a large DC offset, the cancellation-stressing
        shape the f32-safety analysis worries about — shared by the
        precision gate and the mega parity pin (same bytes, so the two
        gates judge the same stimulus). Returns ``(windows,
        resolutions)``."""
        rng = np.random.RandomState(0)
        n = min(16, self.capacity)
        body = (
            rng.randint(-3000, 3000,
                        size=(self.n_channels, n * self.window_len))
            + np.asarray([15000, -12000, 9000] * 40)[
                : self.n_channels, None
            ]
        ).astype(np.int16)
        windows = [
            body[:, i * self.window_len:(i + 1) * self.window_len]
            for i in range(n)
        ]
        return windows, np.full(self.n_channels, 0.1, np.float32)

    def _fused_gate_margins(self, program, windows, res):
        """Run the fused-shape program on the gate windows; returns
        ``(features, margins-or-None)`` numpy rows for the live
        windows."""
        n = len(windows)
        stream, mask = self._stage_fused_stream(windows)
        # device_put per call: the program may donate its stream
        feats, margins = program(
            jax.device_put(stream), res, self._positions, mask,
            *([self.classifier.weights] if self._fused_linear else []),
        )
        return (
            np.asarray(feats)[:n],
            None if margins is None else np.asarray(margins)[:n],
        )

    def _precision_warmup_gate(self) -> None:
        """The serving arm of the precision accuracy gate (bf16 and
        int8 share it): the gate windows featurized through both the
        requested-precision and the f32 programs, judged against
        ops/decode_ingest's documented per-rung tolerance. Above it,
        the engine serves f32 (recorded, never silent)."""
        from ..ops import decode_ingest

        windows, res = self._gate_windows()
        f32_program = _serving_program(
            *self._geometry,
            with_margin=self._fused_linear,
            precision="f32",
        )
        rung_feats, _ = self._fused_gate_margins(
            self._program, windows, res
        )
        f32_feats, _ = self._fused_gate_margins(
            f32_program, windows, res
        )
        gate = decode_ingest.feature_precision_gate(
            rung_feats, f32_feats, precision=self._precision
        )
        self.precision_record = {
            "requested": self._precision,
            "used": self._precision if gate["ok"] else "f32",
            "gate": gate,
        }
        if not gate["ok"]:
            from .. import obs
            from ..obs import events
            import logging

            self._program = f32_program
            obs.metrics.count(
                f"serve.{self._precision}_gate_disabled"
            )
            events.event(f"serve.{self._precision}_gate", **gate)
            logging.getLogger(__name__).warning(
                "serve.%s_gate auto-disable: max abs dev %.3e > "
                "gate %.3e; serving f32",
                self._precision, gate["max_abs_dev"], gate["tolerance"],
            )

    def _mega_warmup(self) -> None:
        """Resolve and (when earned) promote the mega rung: build the
        megakernel program for this geometry/bucket, pin its margins
        against the fused program on the shared gate windows at the
        documented tolerance, and only then make it the serving rung.
        A build/compile failure or a gate miss leaves the engine on
        the fused program with the evidence recorded — the ladder's
        contract: stepping down is survival, never silence.

        Quantized-feature engines (int8/int4) attempt the rung too
        (ISSUE 18 closed the PR 12 leftover that hard-pinned them to
        fused): the mega program is built at the engine's EFFECTIVE
        precision — what the precision gate left it serving, so a
        gated-off engine pins mega against f32 like any f32 engine —
        and judged at that rung's own documented tolerance (a single
        quantization-boundary flip between the fused and mega
        formulations moves a margin by up to one quantization step,
        orders beyond the f32 rungs' 5e-5 parity, and is exactly the
        deviation class the rung's tolerance already licenses). bf16
        stays pinned to fused: its cascade runs bfloat16 OPERANDS —
        there is no bf16 mega twin to gate."""
        from ..ops import decode_ingest, serve_mega

        effective = (
            (self.precision_record or {}).get("used", self._precision)
            if self._precision != "f32"
            else "f32"
        )
        if (
            self._host_fe is not None
            or not self._fused_linear
            or effective == "bf16"
            or self.pre < 1
        ):
            return
        requested = self._engine_rung_requested
        if requested == "fused":
            return
        resolved = (
            serve_mega.default_engine_rung()
            if requested == "auto"
            else requested
        )
        record = {
            "requested": requested,
            "resolved": resolved,
            "used": "fused",
            "lowering": None,
            "gate": None,
            "precision": effective,
        }
        self.mega_record = record
        if resolved != "mega":
            # the accelerator decision path said fused stands (no chip
            # artifact yet, or one that shows mega losing) — recorded,
            # zero code change when the artifact lands and flips it
            return
        from .. import obs
        from ..obs import events
        import logging

        try:
            lowering = serve_mega.default_lowering()
            record["lowering"] = lowering
            program = serve_mega.make_serve_mega_program(
                wavelet_index=self.wavelet_index,
                epoch_size=self.epoch_size,
                skip_samples=self.skip_samples,
                feature_size=self.feature_size,
                n_channels=self.n_channels,
                pre=self.pre,
                post=self.post,
                capacity=self.capacity,
                lowering=lowering,
                precision=effective,
            )
            stride = serve_mega.padded_stride(self.pre, self.post)
            windows, res = self._gate_windows()
            mega_stream = serve_mega.stage_mega_stream(
                windows, self.n_channels, self.window_len, stride,
                self.capacity,
            )
            mega_margins = np.asarray(program(
                jax.device_put(mega_stream), res,
                self.classifier.weights,
            ))[: len(windows)]
            _, fused_margins = self._fused_gate_margins(
                self._program, windows, res
            )
            # f32 engines pin at the mega parity bound; quantized-
            # feature engines at their rung's own tolerance (see the
            # docstring — boundary flips dwarf 5e-5 by construction)
            tol = (
                serve_mega.mega_gate_tolerance()
                if effective == "f32"
                else max(
                    serve_mega.mega_gate_tolerance(),
                    decode_ingest.precision_gate_tolerance(effective),
                )
            )
            dev = float(
                np.max(np.abs(mega_margins - fused_margins))
                if len(windows)
                else 0.0
            )
            gate = {
                "max_abs_dev": dev,
                "tolerance": tol,
                "ok": bool(dev <= tol),
                "rows_checked": len(windows),
            }
        except Exception as e:
            record["error"] = f"{type(e).__name__}: {e}"
            obs.metrics.count("serve.mega_unavailable")
            events.event("serve.mega_unavailable", error=record["error"])
            logging.getLogger(__name__).warning(
                "serve.mega unavailable (%s); serving the fused "
                "program", record["error"],
            )
            return
        record["gate"] = gate
        if not gate["ok"]:
            obs.metrics.count("serve.mega_gate_disabled")
            events.event("serve.mega_gate", **gate)
            logging.getLogger(__name__).warning(
                "serve.mega_gate refused the rung: max abs margin dev "
                "%.3e > gate %.3e; serving the fused program",
                gate["max_abs_dev"], gate["tolerance"],
            )
            return
        self._mega_program = program
        self._mega_stride = stride
        self._rung = "mega"
        record["used"] = "mega"
        events.event("serve.mega_promoted", lowering=record["lowering"])

    @property
    def mode(self) -> str:
        if self._program is None:
            return "host-extractor"
        return "fused-linear" if self._fused_linear else "featurize+host"

    @property
    def rung(self) -> str:
        """The engine rung currently serving: the ``mega`` kernel
        (ops/serve_mega.py — promoted at warmup behind its parity
        gate), the ``fused`` program, or the ``host`` floor."""
        return self._rung


def windows_from_recording(
    recording,
    channel_indices: Sequence[int],
    guessed: int,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    balance: Optional[BalanceState] = None,
) -> Tuple[List[np.ndarray], np.ndarray, np.ndarray]:
    """One recording -> per-epoch serving requests.

    Returns ``(windows, targets, resolutions)``: the kept markers'
    raw ``(n_channels, pre+post)`` windows (unscaled int16 when the
    recording is INT_16 — the same bytes ``stage_raw`` ships to the
    device, sliced per epoch), their 0/1 targets under the shared
    cross-file ``balance`` state, and the per-channel resolutions.
    This is the bridge the pipeline's ``serve=`` mode uses to drive a
    batch session through the service: window content (including the
    zero padding past the end of the recording) matches the fused
    batch path's gather exactly, which is what makes served
    predictions bit-identical to the batch run.
    """
    raw, resolutions, n_samples = device_ingest.stage_raw(
        recording, list(channel_indices)
    )
    plan = device_ingest.plan_ingest(
        recording.markers, guessed, n_samples,
        pre=pre, post=post, balance=balance,
    )
    win = pre + post
    padded = np.pad(raw, ((0, 0), (0, win)))
    windows = [
        padded[:, p - pre:p - pre + win]
        for p in plan.positions[: plan.n_kept]
    ]
    return windows, plan.targets, resolutions
