"""Async micro-batching with admission control, deadlines, a watchdog.

The serving front end between callers and the fused program
(serve/engine.py). Design is robustness-first — the failure modes are
named and each has an explicit owner:

- **Bounded admission queue** — a burst past ``queue_depth`` is shed
  at the door with :class:`ShedError` carrying evidence (depth, limit,
  age of the oldest queued request). Never an unbounded queue, never a
  silent drop: a shed caller knows it was shed and why.
- **Per-request deadlines** — every request carries an
  :class:`io.deadline.Deadline`. A request whose budget is spent while
  queued fails fast with the time it waited; the remaining budget is
  threaded through batch execution (``deadline_scope``) so retry
  ladders underneath — including :mod:`io.remote`'s backoff — stop
  instead of sleeping past it.
- **Deadline-aware retries** — a failed micro-batch (a chaos
  injection, a transient backend error) retries with backoff, but a
  request is only re-attempted while its remaining budget covers the
  next backoff; otherwise it fails NOW with its full attempt history.
- **Watchdog** — a wedged batcher thread (an execute call that never
  returns) is detected by heartbeat age; every queued and in-flight
  request is failed fast with :class:`ServiceWedgedError` and new
  submissions are rejected, so a wedge costs callers milliseconds,
  not forever.
- **Graceful drain** — closing stops admissions (rejected with
  :class:`ServiceClosedError`) while everything already admitted
  completes.

Chaos points ``serve.request`` (one admitted request, fired inside
the batcher) and ``serve.batch`` (one micro-batch execution) land in
the retry machinery above, so ``faults=`` specs can prove the
no-wedge contract (tests/test_serve.py, tools/serve_bench.py).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, List, Optional

from .. import obs
from ..io import deadline as deadline_mod
from ..obs import chaos, domain as run_domain, events
from ..obs import metrics_export


class ServeError(RuntimeError):
    """Base class for serving-path failures."""


class ShedError(ServeError):
    """Admission control rejected the request (queue full).

    ``evidence`` carries the structured shed record when one exists —
    for a multi-tenant service the per-tenant depth/quota/oldest-age
    the 429 body surfaces (gateway/server.py) — alongside the
    human-readable message."""

    def __init__(self, message: str, evidence: Optional[dict] = None):
        super().__init__(message)
        self.evidence = evidence or {}


class ServiceClosedError(ServeError):
    """The service is draining or stopped; no new admissions."""


class ServiceWedgedError(ServeError):
    """The batcher thread wedged; the request was failed fast by the
    watchdog instead of hanging its caller."""


class RequestFailedError(ServeError):
    """The request exhausted its retry/deadline budget; the message
    carries the per-attempt history."""


class ServeFuture:
    """Resolve-once future for one serving request.

    Resolution is guarded by a per-future lock: the batcher finishing
    a slow batch genuinely races the watchdog (and ``stop()``) failing
    the same request, and exactly ONE of them may win — the loser's
    return value steers the outcome accounting, so check-then-act
    without the lock would let both sides count.
    """

    __slots__ = ("_event", "_value", "_error", "_lock")

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def resolve(self, value: Any) -> bool:
        """First resolution wins (the watchdog may race a slow batch);
        returns whether this call was the one that resolved it."""
        with self._lock:
            if self._event.is_set():
                return False
            self._value = value
            self._event.set()
            return True

    def fail(self, error: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._error = error
            self._event.set()
            return True

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block for the outcome. The watchdog guarantees every
        admitted request resolves, so a bare ``result()`` cannot hang
        past a wedge; ``timeout`` is an extra caller-side bound."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still unresolved")
        if self._error is not None:
            raise self._error
        return self._value


class Request:
    """One admitted serving request."""

    __slots__ = (
        "window", "resolutions", "deadline", "future", "submitted_at",
        "attempts", "history", "tenant",
    )

    def __init__(self, window, resolutions, deadline, tenant=None):
        self.window = window
        self.resolutions = resolutions
        self.deadline: deadline_mod.Deadline = deadline
        self.future = ServeFuture()
        self.submitted_at = time.monotonic()
        self.attempts = 0
        self.history: List[str] = []
        #: owning tenant (multiplexed services, serve/multiplex.py);
        #: None for single-model services. Deliberately NOT part of
        #: batch_key: mixed-tenant requests must coalesce into ONE
        #: bucket — the whole point of the multiplexed engine is that
        #: serve_flush_us fills buckets ACROSS tenants
        self.tenant: Optional[str] = tenant

    def batch_key(self):
        """Requests coalesce only when the program can run them as one
        stream: same dtype, same per-channel resolutions. The tenant
        is NOT here — the multiplexed program gathers each row's
        tenant weights by index, so a bucket mixes tenants freely."""
        res = self.resolutions
        return (self.window.dtype.str, res.tobytes())


class Result:
    """A successful prediction, with its serving provenance."""

    __slots__ = ("prediction", "margin", "latency_s", "batch_size",
                 "attempts")

    def __init__(self, prediction, margin, latency_s, batch_size,
                 attempts):
        self.prediction = prediction
        self.margin = margin
        self.latency_s = latency_s
        self.batch_size = batch_size
        self.attempts = attempts

    def __repr__(self) -> str:
        return (
            f"Result(prediction={self.prediction}, "
            f"latency_s={self.latency_s:.4f}, "
            f"batch_size={self.batch_size}, attempts={self.attempts})"
        )


class AdmissionQueue:
    """Bounded FIFO with explicit shedding and batch coalescing.

    ``queue.Queue`` hides its deque; coalescing (pop a run of requests
    sharing a batch key) and retry re-admission (which must not be
    shed — the request was already accepted once) both need direct
    access, so this is a small purpose-built structure.
    """

    def __init__(self, depth: int, tenant_quota: Optional[int] = None):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        if tenant_quota is not None and tenant_quota < 1:
            raise ValueError(
                f"tenant_quota must be >= 1, got {tenant_quota}"
            )
        self.depth = int(depth)
        #: per-tenant queued-request cap (multiplexed services): one
        #: noisy tenant fills its quota and sheds — with ITS evidence —
        #: while the shared queue keeps admitting everyone else. None
        #: (single-model services) checks only the global depth.
        self.tenant_quota = (
            None if tenant_quota is None else int(tenant_quota)
        )
        self._items: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        #: human-readable evidence for the most recent shed decision
        self._last_shed_evidence = ""
        #: structured twin of the evidence line (tenant, depths, ages)
        #: — what a multi-tenant 429 body carries
        self._last_shed_details: dict = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def last_shed_evidence(self) -> str:
        """Human-readable evidence for the most recent shed decision
        (the plan executor embeds it in :class:`ShedError` subclasses
        too — the shed-with-evidence contract is shared machinery)."""
        with self._lock:
            return self._last_shed_evidence

    @property
    def last_shed_details(self) -> dict:
        """Structured evidence for the most recent shed decision —
        ``{"reason", "queue_depth", "depth_limit", "oldest_age_s"}``
        plus ``{"tenant", "tenant_depth", "tenant_quota"}`` when a
        per-tenant quota did the shedding."""
        with self._lock:
            return dict(self._last_shed_details)

    def _tenant_depth(self, tenant) -> int:
        """Queued requests owned by ``tenant`` (caller holds the
        lock)."""
        return sum(1 for item in self._items if item.tenant == tenant)

    def offer(self, request: Request, block_s: float = 0.0) -> bool:
        """Admit one request; False = full (the caller sheds). With
        ``block_s`` the caller cooperates with backpressure by waiting
        (on the pop-notified condition — no polling) for space.

        With a ``tenant_quota`` configured, a tenant-owned request is
        additionally refused while that tenant already has ``quota``
        requests queued — the noisy-neighbor guard: tenant A's burst
        sheds against A's OWN quota (with A's depth and oldest-age as
        evidence) while the rest of the queue keeps admitting B.
        """
        deadline = time.monotonic() + block_s

        def admissible() -> bool:
            if len(self._items) >= self.depth:
                return False
            if (
                self.tenant_quota is not None
                and request.tenant is not None
                and self._tenant_depth(request.tenant)
                >= self.tenant_quota
            ):
                return False
            return True

        with self._not_full:
            while not admissible():
                remaining = deadline - time.monotonic()
                if remaining <= 0.0:
                    now = time.monotonic()
                    if len(self._items) >= self.depth:
                        oldest_age = now - self._items[0].submitted_at
                        self._last_shed_details = {
                            "reason": "queue_full",
                            "queue_depth": len(self._items),
                            "depth_limit": self.depth,
                            "oldest_age_s": round(oldest_age, 3),
                        }
                        self._last_shed_evidence = (
                            f"queue at depth {self.depth}, oldest "
                            f"queued request is {oldest_age:.3f}s old"
                        )
                    else:
                        tenant = request.tenant
                        tenant_items = [
                            item for item in self._items
                            if item.tenant == tenant
                        ]
                        oldest_age = (
                            now - tenant_items[0].submitted_at
                            if tenant_items else 0.0
                        )
                        self._last_shed_details = {
                            "reason": "tenant_quota",
                            "tenant": tenant,
                            "tenant_depth": len(tenant_items),
                            "tenant_quota": self.tenant_quota,
                            "queue_depth": len(self._items),
                            "depth_limit": self.depth,
                            "oldest_age_s": round(oldest_age, 3),
                        }
                        self._last_shed_evidence = (
                            f"tenant {tenant!r} at its quota of "
                            f"{self.tenant_quota} queued requests "
                            f"(queue holds {len(self._items)}/"
                            f"{self.depth}); {tenant!r}'s oldest "
                            f"queued request is {oldest_age:.3f}s old"
                        )
                    return False
                self._not_full.wait(remaining)
            self._items.append(request)
            self._not_empty.notify()
            return True

    def readmit(self, request: Request) -> None:
        """Put a retrying request back WITHOUT the depth check: it was
        admitted once and must not be shed mid-retry (the bound on
        re-admissions is the retry budget itself)."""
        with self._lock:
            self._items.append(request)
            self._not_empty.notify()

    def collect(
        self, max_batch: int, wait_s: float, coalesce_s: float,
        claim=None, flush_s: float = 0.0,
    ) -> List[Request]:
        """Pop the next micro-batch: up to ``max_batch`` consecutive
        requests sharing a batch key. Waits up to ``wait_s`` for the
        first request, then up to ``coalesce_s`` more for the batch to
        fill — latency spent deliberately to buy throughput, bounded
        so an idle trickle still flows.

        ``flush_s`` (the ``serve_flush_us=`` knob, seconds here) is a
        further bounded coalescing window AFTER a request is waiting:
        the pop holds until the queue holds a full ``max_batch`` or
        the window closes, waiting on the offer-notified condition —
        no polling. Under closed-loop load the default dispatch races
        the submitters and batches stay small (mean_batch_size ~2.6
        at concurrency 16 in BENCH_pr8); a bounded window lets queued
        compatible requests fill the bucket before the program runs.
        0 (the default) skips the window entirely — byte-identically
        the pre-knob behavior.

        ``claim(batch)`` runs under the queue lock, in the same
        critical section that pops the items: the batcher registers
        the batch as in-flight there, so a drain watcher can never
        observe requests in neither the queue nor the in-flight set.
        """
        with self._not_empty:
            if not self._items:
                self._not_empty.wait(wait_s)
            if not self._items:
                return []
            if flush_s > 0.0:
                # the bounded fill window: condition-notified (every
                # offer/readmit signals _not_empty), so a full bucket
                # dispatches the moment its last request lands. The
                # predicate counts the HEAD-KEY RUN, not the raw queue
                # length: the pop below stops at the first batch-key
                # boundary, so key-incompatible arrivals can never
                # satisfy the wait — counting them would spend the
                # whole window and still dispatch a tiny batch (or
                # skip a wait that a same-key run could still fill)
                def head_run() -> int:
                    key = self._items[0].batch_key()
                    n = 0
                    for item in self._items:
                        if item.batch_key() != key:
                            break
                        n += 1
                    return n

                fill_deadline = time.monotonic() + flush_s
                # the wait releases the lock, and a shutdown/watchdog
                # drain_pending() may empty the queue meanwhile —
                # guard before indexing the head
                while self._items and head_run() < max_batch:
                    remaining = fill_deadline - time.monotonic()
                    if remaining <= 0.0:
                        break
                    self._not_empty.wait(remaining)
        if coalesce_s > 0.0:
            fill_deadline = time.monotonic() + coalesce_s
            while time.monotonic() < fill_deadline:
                with self._lock:
                    if len(self._items) >= max_batch:
                        break
                time.sleep(0.001)
        batch: List[Request] = []
        with self._lock:
            while self._items and len(batch) < max_batch:
                if batch and (
                    self._items[0].batch_key() != batch[0].batch_key()
                ):
                    break  # different stream config: next batch's job
                batch.append(self._items.popleft())
            if claim is not None and batch:
                claim(batch)
            if batch:
                self._not_full.notify(len(batch))
        return batch

    def remove(self, request: Request) -> bool:
        """Withdraw one still-queued request (the plan executor's
        cancel-if-queued). True = it was queued and is now gone; False
        = a worker already popped it (or it was never here). The pop
        path and this share one lock, so a request is removed XOR
        collected — never both."""
        with self._lock:
            try:
                self._items.remove(request)
            except ValueError:
                return False
            self._not_full.notify()
            return True

    def drain_pending(self) -> List[Request]:
        """Remove and return everything queued (watchdog / shutdown)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
            self._not_full.notify_all()
            return items


class MicroBatcher:
    """The batcher thread plus its watchdog.

    ``execute(windows, resolutions) -> (predictions, margins)`` is the
    engine seam (injectable for tests — a wedged executor is how the
    watchdog is proven). A ``tenant_aware`` batcher (multiplexed
    services) calls ``execute(windows, resolutions, tenants)`` instead
    — the per-request tenant names ride to the engine, which gathers
    each row's tenant weights by index — and keeps per-tenant outcome
    counters plus a per-tenant latency reservoir.
    """

    def __init__(
        self,
        execute: Callable,
        max_batch: int,
        queue_depth: int,
        coalesce_s: float = 0.002,
        flush_us: int = 0,
        max_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        watchdog_s: float = 5.0,
        name: str = "serve",
        tenant_aware: bool = False,
        tenant_quota: Optional[int] = None,
    ):
        if flush_us < 0:
            raise ValueError(f"flush_us must be >= 0, got {flush_us}")
        self._execute = execute
        self.tenant_aware = bool(tenant_aware)
        self.max_batch = int(max_batch)
        self.queue = AdmissionQueue(queue_depth, tenant_quota=tenant_quota)
        self.coalesce_s = float(coalesce_s)
        #: the bounded batch-fill window in seconds (serve_flush_us=;
        #: 0 = dispatch races the submitters, the pre-knob behavior)
        self.flush_s = int(flush_us) / 1e6
        self.max_attempts = int(max_attempts)
        self.retry_backoff_s = float(retry_backoff_s)
        self.watchdog_s = float(watchdog_s)
        self.name = name
        self._stop = threading.Event()
        self.wedged = threading.Event()
        self._heartbeat = time.monotonic()
        self._in_flight: List[Request] = []
        self._in_flight_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        #: bounded latency reservoir for percentiles (seconds)
        self.latencies: "collections.deque" = collections.deque(
            maxlen=8192
        )
        #: per-tenant latency reservoirs (tenant-aware batchers only;
        #: bounded like the global one, guarded by the counters lock)
        self.tenant_latencies: dict = {}
        #: fixed-bucket latency histograms (obs/metrics_export.py):
        #: unlike the reservoirs these never evict, so two replicas'
        #: histograms merge by exact integer addition — the /metrics
        #: exposition and the per-tenant SLO math read these
        self.latency_hist = metrics_export.LatencyHistogram()
        self.tenant_latency_hists: dict = {}
        self.counters = collections.Counter()
        self._counters_lock = threading.Lock()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        # both service threads adopt the starter's per-plan fault
        # domain: the serve.request/serve.batch chaos points and the
        # batcher's counters/spans stay inside the plan that owns this
        # service when the multi-tenant executor runs several at once
        domain = run_domain.capture()
        self._thread = threading.Thread(
            target=lambda: self._adopted(domain, self._run),
            name=f"eeg-tpu-{self.name}-batcher",
            daemon=True,
        )
        self._thread.start()
        self._watchdog_thread = threading.Thread(
            target=lambda: self._adopted(domain, self._watchdog_run),
            name=f"eeg-tpu-{self.name}-watchdog", daemon=True,
        )
        self._watchdog_thread.start()

    @staticmethod
    def _adopted(domain, body) -> None:
        with run_domain.adopt(domain):
            body()

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop.set()
        for t in (self._thread, self._watchdog_thread):
            if t is not None:
                t.join(timeout=join_timeout_s)

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until nothing is queued or in flight (drain). True =
        drained; False = the timeout (or a wedge) cut the wait."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.wedged.is_set():
                return False
            with self._in_flight_lock:
                in_flight = len(self._in_flight)
            if in_flight == 0 and len(self.queue) == 0:
                return True
            time.sleep(0.005)
        return False

    def _count(self, key: str, n: int = 1) -> None:
        with self._counters_lock:
            self.counters[key] += n
        obs.metrics.count(f"serve.{key}", n)

    def _count_tenant(self, tenant, key: str, n: int = 1) -> None:
        """Per-tenant attribution counter (``tenant.<name>.<key>``) —
        local to the batcher's counters (the global ``serve.*`` metric
        already counted the event; per-tenant keys are bounded by the
        128-lane tenant cap, not by traffic)."""
        if tenant is None:
            return
        with self._counters_lock:
            self.counters[f"tenant.{tenant}.{key}"] += n

    def _tenant_latency(self, tenant, latency: float) -> None:
        if tenant is None:
            return
        with self._counters_lock:
            reservoir = self.tenant_latencies.get(tenant)
            if reservoir is None:
                reservoir = collections.deque(maxlen=8192)
                self.tenant_latencies[tenant] = reservoir
            reservoir.append(latency)
            hist = self.tenant_latency_hists.get(tenant)
            if hist is None:
                hist = metrics_export.LatencyHistogram()
                self.tenant_latency_hists[tenant] = hist
            hist.observe(latency * 1e3)

    def snapshot(self):
        """(counters copy, latency list) under the lock — the safe
        read surface for a LIVE service's stats (the batcher thread
        keeps appending while monitors read)."""
        with self._counters_lock:
            return dict(self.counters), list(self.latencies)

    def tenant_latency_snapshot(self) -> dict:
        """Per-tenant latency reservoir copies under the lock (empty
        for tenant-unaware batchers)."""
        with self._counters_lock:
            return {
                tenant: list(reservoir)
                for tenant, reservoir in self.tenant_latencies.items()
            }

    def histogram_snapshot(self) -> metrics_export.LatencyHistogram:
        """A point-in-time copy of the global latency histogram,
        taken under the counters lock (the /metrics scrape surface)."""
        with self._counters_lock:
            return metrics_export.LatencyHistogram.from_snapshot(
                self.latency_hist.snapshot()
            )

    def tenant_histogram_snapshot(self) -> dict:
        """Per-tenant latency histogram copies under the lock (empty
        for tenant-unaware batchers)."""
        with self._counters_lock:
            return {
                tenant: metrics_export.LatencyHistogram.from_snapshot(
                    hist.snapshot()
                )
                for tenant, hist in self.tenant_latency_hists.items()
            }

    def evict_tenant(self, tenant: str) -> None:
        """Drop every per-tenant accounting structure for ``tenant``
        — the latency reservoir, the latency histogram, and the
        ``tenant.<name>.*`` counters. Called by the multiplexed
        service's ``remove_tenant`` so a departed tenant's state does
        not accumulate for the service's lifetime (add/remove churn
        across many tenants would otherwise grow these dicts without
        bound)."""
        prefix = f"tenant.{tenant}."
        with self._counters_lock:
            self.tenant_latencies.pop(tenant, None)
            self.tenant_latency_hists.pop(tenant, None)
            for key in [
                k for k in self.counters if k.startswith(prefix)
            ]:
                del self.counters[key]

    # -- the batcher loop ----------------------------------------------

    def _claim(self, batch: List[Request]) -> None:
        """Runs inside the queue's pop critical section (see
        AdmissionQueue.collect): requests move atomically from queued
        to in-flight, so wait_idle can't declare a drain complete
        while a batch sits in the batcher's hands."""
        with self._in_flight_lock:
            self._in_flight = list(batch)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._heartbeat = time.monotonic()
            batch = self.queue.collect(
                self.max_batch, wait_s=0.05,
                coalesce_s=self.coalesce_s, claim=self._claim,
                flush_s=self.flush_s,
            )
            if not batch:
                continue
            self._heartbeat = time.monotonic()
            try:
                self._process(batch)
            finally:
                with self._in_flight_lock:
                    self._in_flight = []

    def _process(self, batch: List[Request]) -> None:
        # 1. queued-too-long: a request whose budget died in the queue
        # fails NOW with the time it waited — running it would waste a
        # batch slot producing an answer nobody is waiting for
        live: List[Request] = []
        for req in batch:
            if req.deadline.expired:
                waited = time.monotonic() - req.submitted_at
                self._count("deadline_exceeded")
                self._count_tenant(req.tenant, "deadline_exceeded")
                events.event(
                    "serve.deadline_exceeded", queued_s=round(waited, 4)
                )
                req.future.fail(deadline_mod.DeadlineExceededError(
                    f"deadline ({req.deadline.budget_s:.3f}s budget) "
                    f"exceeded after {waited:.3f}s in the admission "
                    f"queue; request was never executed"
                ))
                continue
            # 2. per-request chaos: one admitted request fails inside
            # the batcher — must retry or fail with evidence, never
            # hang or silently drop
            try:
                chaos.maybe_fire("serve.request")
            except Exception as e:
                self._retry_or_fail(req, e)
                continue
            live.append(req)
        if not live:
            return
        # 2b. tenant-scoped batch chaos (multiplexed services): the
        # point ``serve.batch.tenant.<name>`` fails ONE tenant's rows
        # out of the mixed bucket — they retry or fail with evidence
        # individually — while every other tenant's rows execute
        # untouched. This is the isolation contract made testable: a
        # fault plan scoped to tenant A must leave tenant B's batch
        # statistics pinned identical to a B-only run
        # (tests/test_multitenant.py).
        if self.tenant_aware:
            failed_tenants = {}
            for tenant in {r.tenant for r in live if r.tenant}:
                try:
                    chaos.maybe_fire(f"serve.batch.tenant.{tenant}")
                except Exception as e:
                    failed_tenants[tenant] = e
            if failed_tenants:
                survivors = []
                for req in live:
                    if req.tenant in failed_tenants:
                        self._retry_or_fail(
                            req, failed_tenants[req.tenant]
                        )
                    else:
                        survivors.append(req)
                live = survivors
                if not live:
                    return
        # 3. execute, with deadline-aware retries: the scope threads
        # the batch's tightest budget through everything underneath
        # (io/remote backoff ladders included)
        attempt_deadline = min(live, key=lambda r: r.deadline.remaining())
        while True:
            self._heartbeat = time.monotonic()
            try:
                with deadline_mod.deadline_scope(attempt_deadline.deadline):
                    with events.span(
                        "serve.batch", size=len(live),
                    ) as span_rec:
                        chaos.maybe_fire("serve.batch")
                        if self.tenant_aware:
                            predictions, margins = self._execute(
                                [r.window for r in live],
                                live[0].resolutions,
                                [r.tenant for r in live],
                            )
                        else:
                            predictions, margins = self._execute(
                                [r.window for r in live],
                                live[0].resolutions,
                            )
                        if span_rec is not None:
                            span_rec["attrs"]["attempt"] = (
                                live[0].attempts + 1
                            )
            except Exception as e:
                self._count("batch_failures")
                for req in live:
                    req.history.append(
                        f"attempt {req.attempts + 1}: "
                        f"{type(e).__name__}: {e}"
                    )
                    req.attempts += 1
                survivors = []
                for req in live:
                    if req.attempts >= self.max_attempts:
                        self._fail_exhausted(req, e)
                    elif not req.deadline.can_cover(self.retry_backoff_s):
                        self._fail_deadline(req)
                    else:
                        survivors.append(req)
                if not survivors:
                    return
                live = survivors
                time.sleep(self.retry_backoff_s)
                attempt_deadline = min(
                    live, key=lambda r: r.deadline.remaining()
                )
                continue
            now = time.monotonic()
            self._count("batches")
            delivered = 0
            for i, req in enumerate(live):
                latency = now - req.submitted_at
                won = req.future.resolve(Result(
                    prediction=float(predictions[i]),
                    margin=(
                        None if margins is None else float(margins[i])
                    ),
                    latency_s=latency,
                    batch_size=len(live),
                    attempts=req.attempts + 1,
                ))
                if not won:
                    # the watchdog (or a drain-timeout stop) already
                    # failed this future: the caller never saw this
                    # answer, so it must not inflate 'completed' or
                    # the latency reservoir
                    continue
                delivered += 1
                self._count_tenant(req.tenant, "completed")
                self._tenant_latency(req.tenant, latency)
                with self._counters_lock:
                    # appended under the lock so a live stats_block()
                    # can snapshot the reservoir without racing the
                    # deque's iteration
                    self.latencies.append(latency)
                    self.latency_hist.observe(latency * 1e3)
            if delivered:
                self._count("completed", delivered)
            # per-request spans: one retroactive span per served
            # request, so a run report shows request-level latency
            # (no-op without an active recorder)
            rec = events.active_recorder()
            if rec is not None:
                for req in live:
                    with rec.span(
                        "serve.request",
                        latency_s=round(now - req.submitted_at, 5),
                        batch_size=len(live),
                        attempts=req.attempts + 1,
                    ):
                        pass
            return

    def _retry_or_fail(self, req: Request, error: Exception) -> None:
        """One request failed individually: re-admit while the retry
        and deadline budgets allow, else fail with the history."""
        req.attempts += 1
        req.history.append(
            f"attempt {req.attempts}: {type(error).__name__}: {error}"
        )
        if req.attempts >= self.max_attempts:
            self._fail_exhausted(req, error)
        elif req.deadline.expired:
            self._fail_deadline(req)
        else:
            self._count("retries")
            self._count_tenant(req.tenant, "retries")
            events.event("serve.retry", attempts=req.attempts)
            self.queue.readmit(req)

    def _fail_exhausted(self, req: Request, error: Exception) -> None:
        self._count("failed")
        self._count_tenant(req.tenant, "failed")
        req.future.fail(RequestFailedError(
            f"request failed after {req.attempts} attempts "
            f"(budget {self.max_attempts}); attempts: {req.history}"
        ))

    def _fail_deadline(self, req: Request) -> None:
        self._count("deadline_exceeded")
        self._count_tenant(req.tenant, "deadline_exceeded")
        req.future.fail(deadline_mod.DeadlineExceededError(
            f"deadline ({req.deadline.budget_s:.3f}s budget) cannot "
            f"cover another attempt after {req.attempts} failed; "
            f"attempts: {req.history}"
        ))

    # -- the watchdog ---------------------------------------------------

    def _watchdog_run(self) -> None:
        poll = max(0.01, self.watchdog_s / 4.0)
        while not self._stop.is_set():
            time.sleep(poll)
            if self.wedged.is_set():
                # the trip already happened, but a submitter that was
                # blocked in offer() at trip time can still land a
                # request in the drained queue — keep sweeping so no
                # admitted future is ever left unresolved
                for req in self.queue.drain_pending():
                    req.future.fail(ServiceWedgedError(
                        "request failed fast: service is wedged "
                        "(watchdog tripped earlier)"
                    ))
                continue
            with self._in_flight_lock:
                in_flight = list(self._in_flight)
            busy = bool(in_flight) or len(self.queue) > 0
            age = time.monotonic() - self._heartbeat
            if busy and age > self.watchdog_s:
                self.wedged.set()
                self._count("watchdog_trips")
                evidence = (
                    f"batcher heartbeat is {age:.1f}s old "
                    f"(watchdog_s={self.watchdog_s}); "
                    f"{len(in_flight)} in flight, "
                    f"{len(self.queue)} queued"
                )
                events.event("serve.wedged", heartbeat_age_s=round(age, 2))
                import logging

                logging.getLogger(__name__).error(
                    "serve.watchdog tripped: %s — failing all pending "
                    "requests fast", evidence,
                )
                for req in in_flight + self.queue.drain_pending():
                    req.future.fail(ServiceWedgedError(
                        f"request failed fast by the watchdog: "
                        f"{evidence}"
                    ))
