"""Online inference serving for the P300 pipeline.

The batch reproduction answers queries by running the whole pipeline
per invocation; this package is the resident alternative — the
ROADMAP's "millions of users" subsystem:

- ``engine``   the fused serving program: raw epoch-window bytes ->
               scaled samples -> baseline-corrected epochs -> DWT
               features -> prediction, compiled once and shared by
               every micro-batch size (reuses the batch path's
               featurizer, which is what makes served predictions
               bit-identical to the batch pipeline);
- ``batcher``  the async micro-batching front end: bounded admission
               queue with explicit load shedding, per-request
               deadlines threaded through deadline-aware retries, a
               watchdog that fails requests fast when the batcher
               wedges, graceful drain;
- ``service``  the resident wrapper (:class:`InferenceService`):
               load a saved classifier once, serve until drained,
               export the ``serve`` telemetry block;
- ``lifecycle`` the model lifecycle manager: streaming partial-fit
               over labeled feedback (``submit(..., label=)`` /
               ``feedback()``), a shadow-scored candidate promoted
               behind a windowed-statistics gate and rolled back on
               regression (zero-recompile hot swap), and windowed
               drift detection (``serve.drift``);
- ``pipeline`` the ``serve=true`` query mode: drive a batch session
               through the service epoch-by-epoch, statistics pinned
               bit-identical to the batch ``load_clf=`` run;
- ``multiplex`` multi-tenant serving: N tenants' weight vectors
               stacked into the columns of ONE resident 128-lane
               matrix, served by ONE compiled program that gathers
               each row's tenant column by index — mixed-tenant
               micro-batches, zero-recompile tenant add/swap, per-
               tenant quotas and attribution, per-batch snapshot
               isolation.

See docs/serving.md for knobs, semantics, and the parity contract.
"""

from .batcher import (  # noqa: F401
    RequestFailedError,
    Result,
    ServeError,
    ServeFuture,
    ServiceClosedError,
    ServiceWedgedError,
    ShedError,
)
from .engine import ServingEngine, windows_from_recording  # noqa: F401
from .lifecycle import (  # noqa: F401
    LifecycleConfig,
    LifecycleManager,
    parse_swap_gate,
)
from .multiplex import (  # noqa: F401
    MultiplexedEngine,
    MultiplexedService,
    TenantStack,
)
from .service import InferenceService, ServeConfig  # noqa: F401
