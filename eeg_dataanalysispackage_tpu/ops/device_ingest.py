"""On-device ingest: raw int16 recording -> corrected epochs in one XLA graph.

The reference's ingest is a host-side chain — int16 demux with
resolution scaling, per-marker window copy, float32 baseline
correction (OffLineDataProvider.java:167-265) — and this framework's
default path reproduces it bit-exactly on the host
(epochs/extractor.py, native/eeg_host.cc). This module is the
TPU-first alternative: the *unscaled int16 samples* are staged to HBM
(half the bytes of float32, and no per-epoch duplication for
overlapping windows) and scaling + window gather + baseline correction
run as one jitted graph, ready to fuse straight into the DWT feature
matmul downstream.

Division of labor:

- host: marker metadata only — stimulus digits, window validity
  (Java's copyOfRange rules), and the order-dependent class-balance
  scan (which depends only on the target/non-target sequence, never on
  sample values) — producing a static-capacity ``IngestPlan``;
- device: everything touching the waveform.

Numerics: the device path is float32 end-to-end like the reference's
``Baseline.correct(float[], int)``; only the baseline sum's rounding
order differs (tree reduction vs sequential fold), so parity with the
bit-exact host path is to float32 tolerance (pinned in
tests/test_device_ingest.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..epochs import extractor as extractor_mod
from ..epochs.extractor import BalanceState
from ..io.brainvision import Marker, Recording
from ..utils import constants


def _round_capacity(n: int, multiple: int) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def stage_raw(
    recording: Recording,
    channel_indices: Sequence[int],
    sample_multiple: int = 16384,
):
    """Host-side staging of a recording's channels for device ingest.

    Returns (raw (C, S_padded), resolutions (C,), n_samples). Uses
    unscaled int16 when the recording is INT_16 (half the float32
    transfer bytes); other formats fall back to the already scaled
    float32 channels with unit resolutions — same graph either way.

    The sample axis is zero-padded up to a multiple of
    ``sample_multiple``: together with the epoch-capacity bucketing,
    every jitted ingest shape is a bucket size, so recordings of
    different lengths reuse the compiled program instead of retracing
    per file. The padding is semantically free — window validity is
    decided against the *true* ``n_samples``, and windows overhanging
    the end read zeros exactly as Java's copyOfRange zero-pad does.
    """
    try:
        raw = recording.raw_int16(channel_indices)
        res = recording.resolutions(channel_indices)
    except TypeError:
        raw = recording.read_channels(channel_indices).astype(np.float32)
        res = np.ones(len(channel_indices), dtype=np.float32)
    n_samples = raw.shape[1]
    padded = _round_capacity(n_samples, sample_multiple)
    if padded != n_samples:
        raw = np.pad(raw, ((0, 0), (0, padded - n_samples)))
    return raw, res, n_samples


@dataclasses.dataclass
class IngestPlan:
    """Host-side metadata for one recording's device ingest.

    Arrays are padded to ``capacity`` (a bucketed static size; with
    :func:`stage_raw`'s sample-axis bucketing, jit recompiles only
    when a recording overflows the current buckets); ``mask`` marks
    the real rows.
    """

    positions: np.ndarray  # (capacity,) int32 marker positions (kept rows)
    mask: np.ndarray  # (capacity,) bool — True for real epochs
    targets: np.ndarray  # (n_kept,) float64 of {0.0, 1.0}
    stimulus_indices: np.ndarray  # (n_kept,) int

    @property
    def capacity(self) -> int:
        return self.positions.shape[0]

    @property
    def n_kept(self) -> int:
        return int(self.mask.sum())


def plan_ingest(
    markers: Sequence[Marker],
    guessed_number: int,
    n_samples: int,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    balance: Optional[BalanceState] = None,
    capacity_multiple: int = 64,
) -> IngestPlan:
    """Marker metadata -> static-capacity ingest plan.

    Reference semantics (OffLineDataProvider.java:200-265): every
    marker is considered; windows starting out of range are dropped
    (the swallowed AIOOBE — start < 0 or start > n_samples); the label
    is 1.0 iff stimulus_index + 1 == guessed_number; the global
    balance scan decides retention.
    """
    positions = np.array([m.position for m in markers], dtype=np.int64)
    stim_idx = np.array([m.stimulus_index() for m in markers], dtype=int)

    valid = extractor_mod.valid_window_starts(positions, pre, n_samples)
    positions, stim_idx = positions[valid], stim_idx[valid]

    is_target = (stim_idx + 1) == guessed_number
    balance = balance or BalanceState()
    keep = balance.scan(is_target)

    kept = positions[keep]
    if kept.size and kept.max() > np.iinfo(np.int32).max:
        raise ValueError(
            f"marker position {int(kept.max())} exceeds int32 range; "
            "corrupt .vmrk? The host path (epochs/extractor.py) stays "
            "int64 — use it for recordings this long."
        )
    capacity = _round_capacity(kept.shape[0], capacity_multiple)
    padded = np.zeros(capacity, dtype=np.int32)
    padded[: kept.shape[0]] = kept
    mask = np.zeros(capacity, dtype=bool)
    mask[: kept.shape[0]] = True
    return IngestPlan(
        positions=padded,
        mask=mask,
        targets=is_target[keep].astype(np.float64),
        stimulus_indices=stim_idx[keep],
    )


@functools.lru_cache(maxsize=None)
def make_device_epocher(
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
):
    """Jitted (raw int16 (C, S), resolutions (C,), positions (cap,),
    mask (cap,)) -> (cap, C, post) float32 corrected epochs.

    Padded rows come back zeroed. Windows running past the end of the
    recording zero-pad (Java Arrays.copyOfRange semantics); validity
    of starts is the planner's job.
    """
    win = pre + post

    @jax.jit
    def epoch(raw_i16, resolutions, positions, mask):
        scaled = raw_i16.astype(jnp.float32) * resolutions[:, None]
        padded = jnp.pad(scaled, ((0, 0), (0, win)))
        starts = jnp.clip(positions - pre, 0, raw_i16.shape[1])
        idx = starts[:, None] + jnp.arange(win, dtype=positions.dtype)
        windows = padded[:, idx]  # (C, cap, win)
        base = jnp.mean(windows[..., :pre], axis=-1)
        corrected = (windows - base[..., None])[..., pre:]
        out = jnp.transpose(corrected, (1, 0, 2))  # (cap, C, post)
        return out * mask[:, None, None].astype(out.dtype)

    return epoch


@functools.lru_cache(maxsize=None)
def make_device_ingest_featurizer(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    channels: Sequence[int] = (1, 2, 3),
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    precision: str = "f32",
):
    """Fused jitted (raw int16, resolutions, positions, mask) ->
    (cap, n_channels*feature_size) float32 L2-normalized features.

    One XLA program from raw samples to DWT features: scaling, window
    gather, baseline correction, the cascade matmul, and normalization
    all fuse — no epoch tensor ever materializes in HBM. ``channels``
    are 1-based positions within the already-gathered channel rows
    (the WaveletTransform convention).

    ``precision="bf16"`` runs the cascade contraction on bfloat16
    epochs (the ``einsum_bf16`` stream-dtype rule — half the HBM
    bytes on the dominant read): the baseline correction still happens
    in f32 FIRST, so the cast rounds residual-scale values, not
    int16-range DC. Callers own the accuracy gate
    (ops/decode_ingest.bf16_feature_gate; the serving engine gates at
    warmup) — the ~1e-7 ladder contract is f32-only.
    """
    from . import dwt as dwt_xla

    if precision not in ("f32", "bf16"):
        raise ValueError(
            f"unknown precision {precision!r}; use 'f32' or 'bf16'"
        )
    epocher = make_device_epocher(pre, post)
    extract = dwt_xla.make_batched_extractor(
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        channels=channels,
        dtype=jnp.bfloat16 if precision == "bf16" else jnp.float32,
    )

    @jax.jit
    def ingest_features(raw, resolutions, positions, mask):
        epochs = epocher(raw, resolutions, positions, mask)
        feats = extract(epochs).astype(jnp.float32)
        return feats * mask[:, None].astype(feats.dtype)

    return ingest_features


@functools.lru_cache(maxsize=None)
def ingest_matrix(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    window_len: Optional[int] = None,
    fold_baseline: bool = True,
) -> np.ndarray:
    """(window_len, feature_size) float32 operator E composing the
    per-window reference chain into one matrix.

    For a raw window ``x`` of ``window_len`` samples starting at
    ``position - pre``, the reference chain — baseline subtract the
    mean of the first ``pre`` samples (Baseline.java:29-57), slice the
    analysis window, run the cascade — is linear, so it composes:

        features = (x - mean(x[:pre])) @ W_pad = x @ E,
        E = W_pad - (1/pre) * ones[:pre] (x) colsum(W)

    with W_pad the cascade matrix placed at rows
    ``[pre + skip, pre + skip + epoch_size)``. Rows beyond the real
    window are zero, so callers may over-read past the 787 live
    samples (e.g. to an alignment-friendly 800) without masking.

    ``fold_baseline=False`` returns just ``W_pad``: the float32
    kernels subtract the window mean explicitly instead, because real
    EEG carries DC offsets near the int16 range and the folded form's
    ``x @ W_pad - mean * colsum(W)`` cancels catastrophically in f32
    (observed 4.9e-5 feature error on the reference fixture vs
    <1e-6 with explicit subtraction).
    """
    from . import dwt as dwt_xla

    live = pre + skip_samples + epoch_size
    wl = live if window_len is None else window_len
    if wl < live:
        raise ValueError(f"window_len {wl} < live window {live}")
    W = np.asarray(
        dwt_xla.cascade_matrix(wavelet_index, epoch_size, feature_size)
    )
    E = np.zeros((wl, feature_size), dtype=np.float64)
    E[pre + skip_samples : live] = W
    if fold_baseline:
        E[:pre] -= W.sum(axis=0) / pre
    return E.astype(np.float32)


# phase-formulation group size: how many strides one lane-tile-aligned
# row holds. Guarded — odd strides give G=128, where the (ROW, G*K)
# operator tables reach GB scale and the einsum pays ~2G x the MACs.
_PHASE_MAX_GROUP = 16


def _phase_group(stride: int) -> int:
    return math.lcm(stride, 128) // stride


def default_fused_backend() -> str:
    """Platform default for the irregular fused-ingest backend
    (``fe=dwt-<i>-fused`` with no explicit suffix). CPU gets
    ``decode`` (ops/decode_ingest.py): XLA:CPU lowers the element
    gather to ~5 ns/element scalar loads, and the decode rung's
    slice-scan cut measured ~8.6x the gather rung's throughput with a
    ~3.5x faster compile (docs/performance.md). Accelerators resolve
    through the RECORDED decision path
    (``decode_ingest.accelerator_decision``): ``block`` — 1.15M eps =
    21x the element gather on the r4 chip (tools/sweep_results/r4,
    parity 3e-7) — until a staged sweep lands an on-chip bank128
    timing beating block by the pre-registered 2x
    (docs/chip_playbook.md), at which point the same artifacts flip
    the default to ``decode`` (the rung that routes to the bank128
    VMEM kernel) with the evidence in the decision record."""
    if jax.devices()[0].platform == "cpu":
        return "decode"
    from . import decode_ingest

    return decode_ingest.default_accelerator_backend()


def resolve_regular_formulation(formulation: str, stride: int) -> str:
    """'auto' -> the platform/stride default: reshape on CPU
    (subtract-first accuracy, no lane tiling); phase on accelerators
    when the stride is 2^k-friendly (small group size), else conv."""
    if formulation == "auto":
        if jax.devices()[0].platform == "cpu":
            return "reshape"
        return "phase" if _phase_group(stride) <= _PHASE_MAX_GROUP else "conv"
    if formulation not in ("reshape", "conv", "phase", "partial", "bank"):
        raise ValueError(
            f"unknown regular-ingest formulation {formulation!r}"
        )
    return formulation


def make_regular_ingest_featurizer(
    stride: int,
    n_epochs: int,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    n_channels: int = 3,
    formulation: str = "auto",
):
    """Fused int16 ingest for a *regular stimulus train* (fixed
    stimulus-onset asynchrony ``stride``, the shipped P300 paradigm's
    steady state and the continuous-streaming config).

    Jitted (raw int16 (C, S), resolutions (C,), first_position) ->
    (n_epochs, C*feature_size) features. Epoch k's marker sits at
    ``first_position + k*stride``; window formation is *static*, so
    int16 scaling, windowing, baseline correction, DWT, and
    normalization run as one XLA program with **no gather**, reading
    ~2x fewer HBM bytes per epoch than the float32-epoch path.

    ``formulation`` selects how windows are formed on TPU (identical
    semantics, different layout behavior — measured on v5e,
    `docs/ingest_kernel.md`):

    - ``"reshape"``: `(C, n·Δ) -> (C, n, Δ)`, subtract-first, then one
      explicit 2-D matmul of the live analysis columns against the
      cascade operator (channels flattened into rows — no transposed
      einsum output, and the dead window columns never convert to
      f32). Most accurate (baseline subtracted before the
      contraction); on TPU Δ=800 is still not lane-tile aligned, so
      the reshape relays the stream lane-by-lane — the aligned
      formulations below exist for that.
    - ``"conv"``: the window/contraction expressed as a strided
      `conv_general_dilated` over the flat stream (window_strides=Δ),
      baseline via a second 1-tap-bank conv, combined two-term
      (`z@W - mean(z)·colsum(W)`). No reshape exists; XLA's conv
      lowering handles alignment. To keep the two-term f32
      cancellation harmless, a per-channel DC proxy (mean of the
      stream's first samples) is subtracted from the stream first —
      algebraically a no-op (baseline correction is invariant to any
      per-channel constant) that shrinks both cancelling terms from
      int16-range DC to residual scale. Caveat: the proxy is one
      constant per channel, so *slow baseline drift* across a long
      recording re-grows the cancelling terms (error scales with
      drift amplitude, ~5e-5 at full int16-range drift).
    - ``"phase"``: tile-aligned group reshape. Rows of
      ``lcm(Δ, 128)`` samples hold exactly ``G`` strides, so the
      reshape `(C, M·ROW) -> (C, M, ROW)` never crosses lane tiles
      (a free relayout); each window is contracted from its row pair
      via phase-shifted block operators, and the DC proxy is the
      *per-row* mean — constant over every window it covers, hence
      exactly invariant — so accuracy matches subtract-first even
      under baseline drift. One compile serves all phases (operator
      tables are per-phase arguments, not constants).
    - ``"partial"``: phase's tile-aligned geometry with a SINGLE pass
      over the stream — each row is contracted once against the
      concatenated operator ``[E4a|B4a|E4b|B4b]`` and neighbor
      partials combine afterwards, removing phase's row-pair operand
      (za/zb) that dominates its compiled byte count (cost-model
      cross-check, docs/ingest_kernel.md). DC proxy is per-channel
      global (must be shared by both rows of a window), so accuracy
      is conv-class under drift rather than phase-exact.
    - ``"bank"``: the regular train routed through the chip-proven
      bank128 Pallas kernel (``ops/ingest_pallas.py``) — windows cut
      in VMEM by dynamic sublane slabs + the 128-variant operator
      bank, so the slab/operand materializations the r4 chip cost
      report measured at 16.4x the design bytes for ``phase`` never
      reach HBM. Block-formulation two-term numerics (5e-5 class);
      works for ANY stride (no group-size constraint — odd strides
      that force ``conv`` elsewhere are fine here). Planning is
      position-static, so the featurizer stays traceable under an
      outer jit.
    - ``"auto"``: reshape on CPU (no lane tiling, subtract-first
      accuracy), phase on accelerators — unless the stride makes
      ``G = lcm(Δ,128)/Δ`` large (odd strides give G=128: ~GB-scale
      operator tables and ~256x MACs), in which case conv. (``bank``
      stays opt-in until its chip timing lands — staged in
      tools/collect_chip_runs_r4b.sh.)

    Requires ``stride >= pre + skip + epoch_size`` (787 default) so a
    window never crosses into the next epoch's row; the general
    overlapping/irregular case is ``ops/ingest_pallas.py``.

    ``'auto'`` is resolved HERE, before the lru_cache boundary of the
    private builder: the resolution consults the default platform, so
    caching on the literal ``'auto'`` would pin whichever platform was
    live at the first call — a later platform switch (e.g. a
    CPU-override child) would silently reuse a featurizer built for
    the old one. The returned callable carries the resolved name as
    ``.formulation``.
    """
    formulation = resolve_regular_formulation(formulation, stride)
    return _make_regular_ingest_featurizer(
        stride, n_epochs, wavelet_index, epoch_size, skip_samples,
        feature_size, pre, n_channels, formulation,
    )


@functools.lru_cache(maxsize=None)
def _make_regular_ingest_featurizer(
    stride: int,
    n_epochs: int,
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    pre: int,
    n_channels: int,
    formulation: str,
):
    """Cached builder behind :func:`make_regular_ingest_featurizer`.

    ``formulation`` must be a concrete, already-resolved name (never
    ``'auto'`` — resolving here would pin the first caller's platform
    into the cache key). No parameter defaults: the public wrapper
    owns the signature.
    """
    if formulation == "auto":
        raise ValueError(
            "internal: 'auto' must be resolved by "
            "make_regular_ingest_featurizer before the cache boundary"
        )
    win = pre + skip_samples + epoch_size
    if stride < win:
        raise ValueError(
            f"regular ingest needs stride >= {win}; got {stride} "
            "(use the Pallas irregular-position kernel instead)"
        )
    if (
        formulation in ("phase", "partial")
        and _phase_group(stride) > _PHASE_MAX_GROUP
    ):
        raise ValueError(
            f"{formulation} formulation with stride {stride} needs group "
            f"size {_phase_group(stride)} > {_PHASE_MAX_GROUP}: its "
            "operator tables would reach GB scale; use formulation='conv'"
        )
    from . import dwt as dwt_xla

    E_np = ingest_matrix(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        window_len=stride, fold_baseline=False,
    )
    # the live rows of E: the cascade operator W at window-relative
    # rows [pre+skip, pre+skip+epoch_size). Every other E row is zero,
    # so contracting only the live columns is exact — and it lets the
    # reshape formulation read 612 of the 800 window columns (live +
    # pre-stimulus) instead of all of them.
    W_np = E_np[pre + skip_samples : pre + skip_samples + epoch_size]

    @jax.jit
    def _ingest_reshape(raw_i16, resolutions, first_position):
        W = jnp.asarray(W_np)
        C = raw_i16.shape[0]
        start = first_position - pre
        rows = jax.lax.dynamic_slice_in_dim(
            raw_i16, start, n_epochs * stride, axis=1
        ).reshape(C, n_epochs, stride)
        # only the columns the math consumes are converted/scaled: the
        # pre-stimulus head (baseline mean) and the live analysis
        # window (the contraction); the dead columns between and after
        # them never leave int16
        scale = resolutions[:, None, None]
        pre_f = rows[:, :, :pre].astype(jnp.float32) * scale
        live = rows[
            :, :, pre + skip_samples : pre + skip_samples + epoch_size
        ].astype(jnp.float32) * scale
        # explicit baseline subtraction (not folded into W): real EEG
        # DC offsets make the folded form cancel catastrophically
        base = jnp.mean(pre_f, axis=2, keepdims=True)
        # one explicit 2-D matmul over (C*n, epoch_size): the bct,tk
        # einsum's transposed (n, c, k) output forces a relayout on
        # every backend; flattening channels into rows keeps the dot
        # on the fast GEMM path (measured 3x on the CPU fallback) and
        # the only transpose left is the tiny (C, n, K) feature tensor
        z = (live - base).reshape(C * n_epochs, epoch_size)
        y = jax.lax.dot_general(
            z, W, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
        )
        feats = jnp.transpose(
            y.reshape(C, n_epochs, feature_size), (1, 0, 2)
        )
        return dwt_xla.safe_l2_normalize(
            feats.reshape(n_epochs, C * feature_size)
        )

    if formulation != "conv":
        _ingest_conv = None
    else:
        # conv formulation: kernel banks as (out_features, in=1, taps)
        _W_colsum = E_np.sum(axis=0)
        _M_np = np.zeros((1, 1, stride), np.float32)
        _M_np[0, 0, :pre] = 1.0 / pre

        @jax.jit
        def _ingest_conv(raw_i16, resolutions, first_position):
            C = raw_i16.shape[0]
            start = first_position - pre
            x = jax.lax.dynamic_slice_in_dim(
                raw_i16, start, n_epochs * stride, axis=1
            )
            xf = x.astype(jnp.float32) * resolutions[:, None]
            # per-channel DC proxy: baseline correction is invariant
            # to subtracting any per-channel constant, and doing it
            # here (fused into the conv operand read) shrinks the
            # two-term cancellation from int16-range DC to residual
            prefix = min(8192, n_epochs * stride)
            dc = jnp.mean(xf[:, :prefix], axis=1, keepdims=True)
            lhs = (xf - dc)[:, None, :]  # channels as conv batch dim
            yW = jax.lax.conv_general_dilated(
                lhs, jnp.asarray(E_np.T[:, None, :]),
                window_strides=(stride,), padding="VALID",
                dimension_numbers=("NCH", "OIH", "NCH"),
                precision=jax.lax.Precision.HIGHEST,
            )  # (C, K, n)
            yM = jax.lax.conv_general_dilated(
                lhs, jnp.asarray(_M_np),
                window_strides=(stride,), padding="VALID",
                dimension_numbers=("NCH", "OIH", "NCH"),
                precision=jax.lax.Precision.HIGHEST,
            )  # (C, 1, n)
            feats = yW - yM * jnp.asarray(_W_colsum)[None, :, None]
            feats = jnp.transpose(feats, (2, 0, 1)).reshape(
                n_epochs, C * feature_size
            )
            return dwt_xla.safe_l2_normalize(feats)

    if formulation in ("phase", "partial"):
        # shared group geometry: ROW = lcm(stride, 128) samples hold
        # exactly G strides, so (C, M·ROW) -> (C, M, ROW) is a
        # tile-aligned (free) reshape.
        _G = _phase_group(stride)
        _ROW = _G * stride
        _W_np = ingest_matrix(
            wavelet_index, epoch_size, skip_samples, feature_size, pre,
            window_len=win, fold_baseline=False,
        )  # (win, K): the window-relative cascade operator
        _M_groups = -(-n_epochs // _G)  # ceil
        _colsum_np = _W_np.sum(axis=0)

        def _plan_slab(raw_i16, start):
            """(phase, s0) for the aligned slab, or None if the
            recording is too short (caller falls back to reshape).
            Mod by STRIDE, not ROW: keeps every window's start inside
            its own row (offsets phase + j*stride < ROW) and shrinks
            the table-cache key space. The slab's absolute start s0
            needs no alignment — the reshape is relative to the slab."""
            phase = start % stride
            s0 = start - phase
            need = s0 + (_M_groups + 1) * _ROW
            if s0 < 0 or need > raw_i16.shape[1]:
                return None
            return phase, s0

        def _group_tables_np(phase: int):
            # phase < stride (the wrappers mod by stride), so every
            # window's first tap lands inside its own row:
            # off <= (stride-1) + (G-1)*stride < _ROW; only the tail
            # may cross into the next row (the E4b/B4b halves).
            assert 0 <= phase < stride
            K = feature_size
            E4a = np.zeros((_ROW, _G * K), np.float32)
            E4b = np.zeros((_ROW, _G * K), np.float32)
            B4a = np.zeros((_ROW, _G), np.float32)
            B4b = np.zeros((_ROW, _G), np.float32)
            for j in range(_G):
                off = phase + j * stride
                cut = min(win, _ROW - off)  # taps before the row edge
                E4a[off : off + cut, j * K : (j + 1) * K] = _W_np[:cut]
                if cut < win:
                    E4b[: win - cut, j * K : (j + 1) * K] = _W_np[cut:]
                bcut = min(pre, _ROW - off)
                B4a[off : off + bcut, j] = 1.0 / pre
                if bcut < pre:
                    B4b[: pre - bcut, j] = 1.0 / pre
            return E4a, E4b, B4a, B4b

    if formulation != "phase":
        _run_phase = None
    else:
        # phase formulation: windows are cut by per-phase block
        # operators over each row PAIR, and the per-row mean is an
        # exactly-invariant DC proxy (subtract-first accuracy even
        # under electrode drift).

        # bounded: tables are ~3.5 MB per phase (stride 800) and a
        # service ingesting many recordings must not accumulate them.
        # NUMPY in the cache, never jnp: a jnp conversion executed
        # while an outer jit is tracing (the dryrun's
        # jit(vmap(featurizer)) pattern) would cache a TRACER, and the
        # module-level featurizer cache would then poison every later
        # call in the process with UnexpectedTracerError. The jitted
        # ingest converts its numpy arguments per-call, trace-safely.
        @functools.lru_cache(maxsize=8)
        def _phase_tables(phase: int):
            return _group_tables_np(phase)

        @jax.jit
        def _ingest_phase(raw_i16, resolutions, s0, E4a, E4b, B4a, B4b):
            C = raw_i16.shape[0]
            K = feature_size
            slab = jax.lax.dynamic_slice_in_dim(
                raw_i16, s0, (_M_groups + 1) * _ROW, axis=1
            )
            xf = slab.astype(jnp.float32) * resolutions[:, None]
            rows = xf.reshape(C, _M_groups + 1, _ROW)
            ra, rb = rows[:, :-1], rows[:, 1:]
            # per-row DC proxy: constant over every window the row
            # pair carries, so baseline invariance makes this exact
            d = jnp.mean(ra, axis=2, keepdims=True)
            za, zb = ra - d, rb - d
            hi = jax.lax.Precision.HIGHEST
            yW = (
                jnp.einsum("cms,sk->cmk", za, E4a, precision=hi)
                + jnp.einsum("cms,sk->cmk", zb, E4b, precision=hi)
            ).reshape(C, _M_groups, _G, K)
            pm = (
                jnp.einsum("cms,sj->cmj", za, B4a, precision=hi)
                + jnp.einsum("cms,sj->cmj", zb, B4b, precision=hi)
            )  # (C, M, G)
            colsum = jnp.asarray(_colsum_np)
            feats = yW - pm[..., None] * colsum[None, None, None, :]
            out = jnp.transpose(feats, (1, 2, 0, 3)).reshape(
                _M_groups * _G, C * K
            )[:n_epochs]
            return dwt_xla.safe_l2_normalize(out)

        def _run_phase(raw_i16, resolutions, start):
            plan = _plan_slab(raw_i16, start)
            if plan is None:
                return None  # slab out of range; caller falls back
            phase, s0 = plan
            return _ingest_phase(
                raw_i16, resolutions, s0, *_phase_tables(phase)
            )

    if formulation != "partial":
        _run_partial = None
    else:
        # partial formulation: each row is contracted ONCE against a
        # concatenated operator [E4a|B4a|E4b|B4b] and neighbor
        # partials combine afterwards — the phase formulation's
        # row-pair operand (each row read as `ra` for its group and
        # `rb` for the previous one, za/zb materialized) becomes a
        # single pass over the stream. The cost-model cross-check in
        # docs/ingest_kernel.md is the motivation: phase's compiled
        # bytes are dominated by the pair materialization.
        #
        # Numerics: the DC proxy must be the SAME constant for both
        # rows a window spans (the proxy enters via rows m and m+1,
        # combined later), so it is per-channel global (the stream
        # prefix mean, like conv) rather than per-row — baseline
        # correction is exactly invariant to it, and both cancelling
        # terms sit at (residual + drift) scale: conv-class accuracy
        # (~5e-5 under full int16-range drift), vs phase's
        # subtract-first exactness. Trade bytes for the last decimal.
        # numpy in the cache for the same tracer-poisoning reason as
        # _phase_tables above
        @functools.lru_cache(maxsize=8)
        def _partial_tables(phase: int):
            E4a, E4b, B4a, B4b = _group_tables_np(phase)
            return np.concatenate(
                [E4a, B4a, E4b, B4b], axis=1
            )  # (ROW, 2(G*K + G))

        @jax.jit
        def _ingest_partial(raw_i16, resolutions, s0, CAT):
            C = raw_i16.shape[0]
            K = feature_size
            GK = _G * K
            slab = jax.lax.dynamic_slice_in_dim(
                raw_i16, s0, (_M_groups + 1) * _ROW, axis=1
            )
            xf = slab.astype(jnp.float32) * resolutions[:, None]
            prefix = min(8192, (_M_groups + 1) * _ROW)
            dc = jnp.mean(xf[:, :prefix], axis=1, keepdims=True)
            rows = (xf - dc).reshape(C, _M_groups + 1, _ROW)
            hi = jax.lax.Precision.HIGHEST
            P = jnp.einsum("cms,se->cme", rows, CAT, precision=hi)
            Pa = P[..., :GK]
            Ba = P[..., GK : GK + _G]
            Pb = P[..., GK + _G : 2 * GK + _G]
            Bb = P[..., 2 * GK + _G :]
            yW = (Pa[:, :-1] + Pb[:, 1:]).reshape(C, _M_groups, _G, K)
            pm = Ba[:, :-1] + Bb[:, 1:]  # (C, M, G)
            colsum = jnp.asarray(_colsum_np)
            feats = yW - pm[..., None] * colsum[None, None, None, :]
            out = jnp.transpose(feats, (1, 2, 0, 3)).reshape(
                _M_groups * _G, C * K
            )[:n_epochs]
            return dwt_xla.safe_l2_normalize(out)

        def _run_partial(raw_i16, resolutions, start):
            plan = _plan_slab(raw_i16, start)
            if plan is None:
                return None  # slab out of range; caller falls back
            phase, s0 = plan
            return _ingest_partial(
                raw_i16, resolutions, s0, _partial_tables(phase)
            )

    if formulation != "bank":
        _run_bank = None
    else:
        # bank formulation: the regular train routed through the
        # chip-proven bank128 Pallas kernel (ops/ingest_pallas.py) —
        # windows are cut in VMEM (dynamic sublane slabs + the
        # 128-variant operator bank), so the f32 slab and dot-operand
        # materializations the r4 chip cost report measured at 16.4x
        # the design bytes for phase never reach HBM. Planning is
        # position-static (positions = first + k*stride, no data
        # dependence), so the runner is traceable inside an outer jit
        # (the bench's scan) AND eager-safe through the axon tunnel:
        # host planning consumes only concrete ints, and every device
        # op lives inside the jitted _bank_run.
        from . import ingest_pallas as _ip  # lazy: _ip imports us
        from . import pallas_support as _ps

        _BCHUNK = 65536
        _BTILE = 32
        _Wvm_np, _fold_np, _bank_slab_rows = _ip.bank128_banks(
            wavelet_index, epoch_size, skip_samples, feature_size, pre
        )

        # numpy in the cache, never jnp (same tracer-poisoning
        # rationale as _phase_tables); routed through the shared plan
        # cache so steady-state steps re-plan nothing and the bench's
        # plan_cache field counts the hits
        from . import plan_cache as _pc

        _bank_plan_cache = _pc.cache("regular_bank_plan")

        def _bank_tables(first: int, S: int):
            key = _pc.digest(
                extra=(
                    "regular_bank", first, S, stride, n_epochs,
                    wavelet_index, epoch_size, skip_samples,
                    feature_size, pre, n_channels,
                ),
            )
            return _bank_plan_cache.get_or_build(
                key, lambda: _build_bank_tables(first, S)
            )

        def _build_bank_tables(first: int, S: int):
            positions = (
                first + np.arange(n_epochs, dtype=np.int64) * stride
            )
            window = _ip.kernel_window(
                "bank128", pre, skip_samples, epoch_size
            )
            plan = _ip.bucket_plan_8(
                _ip.plan_pallas_tiles(
                    positions, pre=pre, window=window,
                    chunk=_BCHUNK, tile_b=_BTILE,
                )
            )
            half = _BCHUNK // 2
            needed = (int(plan.half_idx.max(initial=0)) + 2) * half
            # 8-chunk sample bucket, matching ingest_features_pallas:
            # pad_to is a static jit key (and the ~9MB bank is baked
            # per executable), so coarse buckets keep recordings of
            # different lengths on one compiled kernel
            sample_bucket = 8 * _BCHUNK
            pad_to = ((max(S, needed) + sample_bucket - 1)
                      // sample_bucket) * sample_bucket
            blocks, shifts_rows, inv = _ip.bank_plan_arrays(
                plan, n_channels
            )
            return plan.half_idx, blocks, shifts_rows, inv, pad_to

        @functools.partial(
            jax.jit, static_argnames=("pad_to", "interpret")
        )
        def _bank_run(raw_i16, resolutions, half_idx, blocks,
                      shifts_rows, inv, *, pad_to, interpret):
            C, S = raw_i16.shape
            if pad_to != S:
                raw_i16 = jnp.pad(raw_i16, ((0, 0), (0, pad_to - S)))
            rows = _ip.bank_ingest_rows(
                raw_i16.reshape(C, -1, _ip._BANK_BLK),
                half_idx, blocks, shifts_rows,
                # trace-time constants: baked into the executable, no
                # per-call host->device upload of the ~9MB bank (the
                # _ingest_reshape/E_np pattern)
                jnp.asarray(_Wvm_np), jnp.asarray(_fold_np),
                tile_b=_BTILE, chunk=_BCHUNK,
                feature_size=feature_size,
                slab_rows=_bank_slab_rows,
                interpret=interpret,
            )  # (n_tiles*_BTILE*C, K), unscaled
            return _ip.bank_finish(rows, resolutions, inv)

        def _run_bank(raw_i16, resolutions, start):
            if raw_i16.shape[0] != n_channels:
                raise ValueError(
                    f"bank formulation built for {n_channels} "
                    f"channels; got raw with {raw_i16.shape[0]}"
                )
            first = start + pre
            half_idx, blocks, shifts_rows, inv, pad_to = _bank_tables(
                int(first), int(raw_i16.shape[1])
            )
            return _bank_run(
                raw_i16,
                jnp.asarray(resolutions, jnp.float32),
                jnp.asarray(half_idx),
                jnp.asarray(blocks),
                jnp.asarray(shifts_rows),
                jnp.asarray(inv),
                pad_to=pad_to,
                # resolved per call: the featurizer cache is
                # process-wide and must not pin the first caller's
                # platform (the 'auto'-resolution staleness class)
                interpret=_ps.default_interpret(),
            )

    _ingest_jit = {
        "conv": _ingest_conv,
        "reshape": _ingest_reshape,
        "phase": None,  # dispatched in the wrapper (slab bounds)
        "partial": None,  # dispatched in the wrapper (slab bounds)
        "bank": None,  # dispatched in the wrapper (host tile planning)
    }[formulation]

    def ingest(raw_i16, resolutions, first_position):
        # host-side bounds check: dynamic_slice CLAMPS out-of-range
        # starts, which would silently shift every window
        first = int(first_position)
        start = first - pre
        end = start + n_epochs * stride
        if start < 0 or end > raw_i16.shape[1]:
            raise ValueError(
                f"regular ingest window [{start}, {end}) out of range "
                f"for recording of {raw_i16.shape[1]} samples"
            )
        if formulation == "bank":
            return _run_bank(raw_i16, resolutions, start)
        if formulation in ("phase", "partial"):
            runner = _run_phase if formulation == "phase" else _run_partial
            out = runner(raw_i16, resolutions, start)
            if out is not None:
                return out
            # recording too short for the aligned slab (needs up to
            # ROW of tail slack): the subtract-first reshape path is
            # equally exact, just slower on TPU — fine at this size
            return _ingest_reshape(raw_i16, resolutions, first)
        return _ingest_jit(raw_i16, resolutions, first)

    ingest.formulation = formulation
    # inner jitted programs, exposed for compiled-HLO/cost inspection
    # (tools/cost_report.py; same pattern as parallel/*._sharded_jit)
    ingest._jit = _ingest_jit  # None for phase/partial (wrapper dispatches)
    ingest._phase_jit = _ingest_phase if formulation == "phase" else None
    ingest._phase_tables = _phase_tables if formulation == "phase" else None
    ingest._partial_jit = (
        _ingest_partial if formulation == "partial" else None
    )
    ingest._partial_tables = (
        _partial_tables if formulation == "partial" else None
    )
    ingest._phase_geometry = (
        (_M_groups, _ROW) if formulation in ("phase", "partial") else None
    )
    return ingest


@functools.lru_cache(maxsize=None)
def _shift_variant_banks(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    pre: int,
    slab: int,
    n_variants: int,
):
    """Operator banks for the block-gather irregular ingest.

    ``Wv`` (slab, n_variants*K): variant v holds the window operator
    shifted down by v rows (window taps at slab rows [v, v+win)).
    ``Mv`` (slab, n_variants): variant v's pre-stimulus mean taps.
    """
    W = ingest_matrix(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        window_len=pre + skip_samples + epoch_size, fold_baseline=False,
    )
    win, K = W.shape
    assert n_variants - 1 + win <= slab
    Wv = np.zeros((slab, n_variants * K), np.float32)
    Mv = np.zeros((slab, n_variants), np.float32)
    for v in range(n_variants):
        Wv[v : v + win, v * K : (v + 1) * K] = W
        Mv[v : v + pre, v] = 1.0 / pre
    return Wv, Mv, W.sum(axis=0)


@functools.lru_cache(maxsize=None)
def make_block_ingest_featurizer(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    chunk_epochs: int = 32768,
):
    """Irregular-marker fused int16 ingest with NO element gather.

    Same signature and semantics as
    :func:`make_device_ingest_featurizer` (raw int16 (C, S),
    resolutions, positions, mask -> (cap, C*K) features), but the
    window formation is TPU-layout-native where the gather
    formulation's per-element index gather measured ~0% of roofline:

    - the stream is viewed as 128-lane blocks (tile rows); each
      window start splits into ``block = start // 128`` and
      ``shift = start % 128``;
    - per window, 8 consecutive block-rows (1024 samples >= 787 live
      + 127 max shift) are gathered — whole-tile row gathers, the
      layout-friendly kind;
    - the residual shift never moves data: a 128-variant operator
      bank (:func:`_shift_variant_banks`) computes every shift's
      features in one MXU contraction and a one-hot matmul selects
      each window's variant — gather converted to dense FLOPs, which
      this op has idle (~6.3M MACs/epoch, microseconds per million
      epochs on the MXU).
    - baseline: per-window slab mean as the DC proxy (exactly
      invariant), then the two-term pre-mean correction — both terms
      at residual scale, so f32-safe.

    Windows overhanging the recording end read zeros (Java
    copyOfRange semantics, matching the gather path).

    The per-window intermediates cost ~25 KB/epoch of HBM (the
    (C, n, BLK, K) variant tensor + the gathered slab), so a whole
    long recording featurized in one call could exhaust HBM where the
    element-gather path would not. Capacities above ``chunk_epochs``
    therefore run as a ``lax.map`` over fixed-size position chunks —
    same compiled body per chunk, HBM bounded at
    ``chunk_epochs * ~25 KB`` regardless of recording length. At or
    below ``chunk_epochs`` (every bench size and the shipped
    paradigm's recordings) the single-chunk body is emitted directly,
    unchanged.
    """
    from . import dwt as dwt_xla

    SLAB_BLOCKS = 8
    BLK = 128
    slab = SLAB_BLOCKS * BLK  # 1024
    win = pre + skip_samples + epoch_size
    if BLK - 1 + win > slab:
        raise ValueError("window too long for the 8-block slab")
    Wv_np, Mv_np, colsum_np = _shift_variant_banks(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        slab, BLK,
    )

    def _featurize(blocks, resolutions, starts):
        """(C, n_blocks, BLK) tile rows + (m,) window starts ->
        (m, C*K) normalized features (no mask)."""
        C = blocks.shape[0]
        K = feature_size
        b0 = starts // BLK
        shift = starts % BLK  # (m,)
        bidx = b0[:, None] + jnp.arange(SLAB_BLOCKS, dtype=b0.dtype)
        gathered = blocks[:, bidx]  # (C, m, 8, BLK) — row gathers
        xw = gathered.reshape(C, -1, slab).astype(jnp.float32) * (
            resolutions[:, None, None]
        )
        # per-window slab mean: a per-window constant, which baseline
        # correction cancels exactly — keeps both terms below small
        d = jnp.mean(xw, axis=-1, keepdims=True)
        z = xw - d
        hi = jax.lax.Precision.HIGHEST
        y = jnp.einsum(
            "cnt,tv->cnv", z, jnp.asarray(Wv_np), precision=hi
        ).reshape(C, -1, BLK, K)
        pm = jnp.einsum(
            "cnt,tv->cnv", z, jnp.asarray(Mv_np), precision=hi
        )  # (C, m, BLK)
        onehot = (
            shift[:, None] == jnp.arange(BLK, dtype=shift.dtype)[None, :]
        ).astype(jnp.float32)  # (m, BLK)
        yk = jnp.einsum("cnvk,nv->cnk", y, onehot, precision=hi)
        pmn = jnp.einsum("cnv,nv->cn", pm, onehot, precision=hi)
        feats = yk - pmn[..., None] * jnp.asarray(colsum_np)[None, None, :]
        out = jnp.transpose(feats, (1, 0, 2)).reshape(-1, C * K)
        return dwt_xla.safe_l2_normalize(out)

    @jax.jit
    def ingest_features(raw, resolutions, positions, mask):
        C, S = raw.shape
        cap = positions.shape[0]
        # pad so every gathered slab exists: tail of slab zeros, then
        # round the block count up
        S_pad = ((S + slab + BLK - 1) // BLK) * BLK
        padded = jnp.pad(raw, ((0, 0), (0, S_pad - S)))
        blocks = padded.reshape(C, S_pad // BLK, BLK)
        starts = jnp.clip(positions - pre, 0, S)
        if cap <= chunk_epochs:
            out = _featurize(blocks, resolutions, starts)
        else:
            n_chunks = -(-cap // chunk_epochs)
            pad_rows = n_chunks * chunk_epochs - cap
            # padded starts gather block 0 — valid rows, masked off
            chunked = jnp.pad(starts, (0, pad_rows)).reshape(
                n_chunks, chunk_epochs
            )
            out = jax.lax.map(
                lambda s: _featurize(blocks, resolutions, s), chunked
            ).reshape(n_chunks * chunk_epochs, -1)[:cap]
        return out * mask[:, None].astype(out.dtype)

    return ingest_features


@dataclasses.dataclass
class BlockClassPlan:
    """Host gather plan for the alignment-classed block ingest.

    Windows are grouped by *alignment class* — the residual in-block
    shift ``(position - pre) % 128`` — so every window in a class
    shares ONE (slab, K) operator and the whole class contracts as a
    single MXU matmul, instead of every window paying the 128-variant
    bank (128x the MACs) the traced block formulation needs because
    its shifts are data-dependent. All arrays are numpy (host): a plan
    is pure marker metadata, built once per (marker layout, staged
    shape, geometry) and memoized in ``ops/plan_cache``.
    """

    class_b0: np.ndarray  # (V, max_m) int32 first gathered block per slot
    row_of: np.ndarray  # (capacity,) int32 kernel row of each epoch
    Wc: np.ndarray  # (V, slab, K) f32 per-class window operator
    Mc: np.ndarray  # (V, slab) f32 per-class pre-stimulus mean taps
    colsum: np.ndarray  # (K,) f32 window-operator column sums

    @property
    def n_classes(self) -> int:
        return self.class_b0.shape[0]

    @property
    def slots_per_class(self) -> int:
        return self.class_b0.shape[1]


def _block_class_operators(
    classes: np.ndarray,
    V: int,
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    pre: int,
):
    """(Wc, Mc, colsum) for one class SET — the shifted (V, slab, K)
    operators every class contracts against. Keyed on the class set
    and the DWT geometry, NOT the marker layout, and memoized
    separately from the per-layout plan: the operator tables are the
    plan's only MB-scale arrays (V=128 -> ~8 MB), and dense layouts
    all share the single all-128-classes entry, so per-layout cache
    entries stay at the KB scale ``ops/plan_cache`` sizes its
    capacity by."""
    from . import plan_cache as _pc

    BLK = 128
    SLAB_BLOCKS = 8
    slab = SLAB_BLOCKS * BLK
    win = pre + skip_samples + epoch_size
    classes = np.asarray(classes, np.int32)

    def build():
        W = ingest_matrix(
            wavelet_index, epoch_size, skip_samples, feature_size, pre,
            window_len=win, fold_baseline=False,
        )
        K = feature_size
        Wc = np.zeros((V, slab, K), np.float32)
        Mc = np.zeros((V, slab), np.float32)
        for i, v in enumerate(classes):
            Wc[i, v : v + win, :] = W
            Mc[i, v : v + pre] = 1.0 / pre
        return Wc, Mc, W.sum(axis=0).astype(np.float32)

    key = _pc.digest(
        classes,
        extra=(
            "block_class_ops", V, wavelet_index, epoch_size,
            skip_samples, feature_size, pre,
        ),
    )
    # entries here are MB-scale (unlike the KB-scale layout plans the
    # shared default capacity is sized for), so this cache gets its
    # own small bound: 16 x <=8.4 MB keeps worst-case host RAM for
    # operator tables near 100 MB even with many distinct class sets
    return _pc.cache("block_class_operators", capacity=16).get_or_build(
        key, build
    )


def plan_block_classes(
    positions: np.ndarray,
    mask: np.ndarray,
    n_samples: int,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    class_multiple: int = 8,
    slot_multiple: int = 8,
) -> BlockClassPlan:
    """Build the alignment-class gather plan for one marker layout.

    ``positions``/``mask`` are an IngestPlan's static-capacity arrays;
    ``n_samples`` is the staged stream length (``raw.shape[1]``) the
    window starts clip against — the same clip the traced block
    featurizer applies, so the two formulations cut identical windows.
    Class count and slots-per-class round up to ``class_multiple`` /
    ``slot_multiple`` so near-identical layouts land on one compiled
    shape. Padded slots gather block 0 and are never selected by
    ``row_of``; padded classes carry zero operators.
    """
    BLK = 128
    SLAB_BLOCKS = 8
    slab = SLAB_BLOCKS * BLK
    win = pre + skip_samples + epoch_size
    # same build-time guard as the traced block featurizer: the worst
    # in-block shift is BLK-1, and shift + window must fit the slab —
    # without this, a long-epoch geometry only fails when a recording
    # happens to contain a badly-aligned marker (an opaque numpy
    # broadcast error mid-run instead of a deterministic ValueError)
    if BLK - 1 + win > slab:
        raise ValueError("window too long for the 8-block slab")
    positions = np.asarray(positions)
    mask = np.asarray(mask, dtype=bool)
    capacity = positions.shape[0]

    starts = np.clip(positions.astype(np.int64) - pre, 0, n_samples)
    real = np.nonzero(mask)[0]
    shifts = (starts[real] % BLK).astype(np.int32)
    b0 = (starts[real] // BLK).astype(np.int32)

    classes, inv_class = np.unique(shifts, return_inverse=True)
    V_real = len(classes)
    V = max(
        class_multiple,
        -(-max(V_real, 1) // class_multiple) * class_multiple,
    )
    counts = (
        np.bincount(inv_class, minlength=max(V_real, 1))
        if real.size
        else np.zeros(1, np.int64)
    )
    max_m = max(
        slot_multiple,
        int(-(-max(int(counts.max(initial=1)), 1) // slot_multiple))
        * slot_multiple,
    )

    class_b0 = np.zeros((V, max_m), np.int32)
    row_of = np.zeros(capacity, np.int32)
    if real.size:
        order = np.argsort(inv_class, kind="stable")
        sorted_cls = inv_class[order]  # nondecreasing class ids
        # slot within class = rank in the class-sorted order minus the
        # class's start offset
        slot = np.arange(real.size) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        class_b0[sorted_cls, slot] = b0[order]
        row_of[real[order]] = sorted_cls * max_m + slot

    # the MB-scale operator tables are keyed on the class SET (not
    # the layout) and shared across plans — see _block_class_operators
    Wc, Mc, colsum = _block_class_operators(
        classes, V, wavelet_index, epoch_size, skip_samples,
        feature_size, pre,
    )
    return BlockClassPlan(
        class_b0=class_b0,
        row_of=row_of,
        Wc=Wc,
        Mc=Mc,
        colsum=colsum,
    )


def cached_block_class_plan(
    positions: np.ndarray,
    mask: np.ndarray,
    n_samples: int,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
) -> BlockClassPlan:
    """:func:`plan_block_classes` behind the shared plan cache, keyed
    on (marker-layout digest, staged shape, geometry): the same
    recording featurized again does zero host re-planning."""
    from . import plan_cache as _pc

    positions = np.asarray(positions)
    mask = np.asarray(mask, dtype=bool)
    key = _pc.digest(
        positions,
        mask,
        extra=(
            "block_class", int(n_samples), wavelet_index, epoch_size,
            skip_samples, feature_size, pre,
        ),
    )
    return _pc.cache("block_class_plan").get_or_build(
        key,
        lambda: plan_block_classes(
            positions, mask, n_samples,
            wavelet_index=wavelet_index,
            epoch_size=epoch_size,
            skip_samples=skip_samples,
            feature_size=feature_size,
            pre=pre,
        ),
    )


@functools.lru_cache(maxsize=None)
def make_classed_block_ingest_featurizer(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    chunk_epochs: int = 32768,
):
    """Irregular-marker fused int16 ingest, windows batched by
    alignment class (the host-planned fast form of
    :func:`make_block_ingest_featurizer`).

    Same (raw int16 (C, S), resolutions, positions, mask) ->
    (capacity, C*K) contract and identical numerics to the traced
    block featurizer — same slab gather, same per-slab DC proxy, same
    two-term baseline correction — but ``positions``/``mask`` must be
    CONCRETE host arrays (an IngestPlan's metadata, the usual case):
    the host groups windows by their in-block shift
    (:func:`plan_block_classes`, memoized in ``ops/plan_cache``), so

    - each class contracts against its single (slab, K) shifted
      operator as one batched matmul — ~128x fewer MACs than the
      128-variant bank, and no (C, n, 128, K) variant tensor ever
      exists (the traced formulation's dominant HBM intermediate);
    - steady-state calls over an unchanged layout reuse the cached
      plan: zero host re-planning per step.

    Per-class contraction is bitwise-identical to bank-then-select
    (the selected variant's column block IS the class operator), so
    parity with the traced block featurizer is exact.

    When classes x slots exceeds ``chunk_epochs`` the slot axis runs
    as a ``lax.map`` over fixed-size chunks (bounded HBM on long
    recordings, same policy as the traced featurizer).
    """
    from . import dwt as dwt_xla

    BLK = 128
    SLAB_BLOCKS = 8
    slab = SLAB_BLOCKS * BLK
    # same guard as the traced featurizer: fail at BUILD time, not
    # only when a recording happens to contain a badly-aligned marker
    if BLK - 1 + pre + skip_samples + epoch_size > slab:
        raise ValueError("window too long for the 8-block slab")

    def _featurize_classes(blocks, resolutions, cb0, Wc, Mc, colsum):
        """(C, nb, BLK) tile rows + (V, m) class plan -> per-class
        feature tensor (C, V, m, K)."""
        C = blocks.shape[0]
        bidx = cb0[:, :, None] + jnp.arange(SLAB_BLOCKS, dtype=cb0.dtype)
        gathered = blocks[:, bidx]  # (C, V, m, 8, BLK) — row gathers
        xw = gathered.reshape(
            C, cb0.shape[0], cb0.shape[1], slab
        ).astype(jnp.float32) * resolutions[:, None, None, None]
        # per-window slab mean: exactly invariant DC proxy, keeps the
        # two-term correction at residual scale (the block-ingest
        # f32-safety analysis)
        d = jnp.mean(xw, axis=-1, keepdims=True)
        z = xw - d
        hi = jax.lax.Precision.HIGHEST
        y = jnp.einsum("cvms,vsk->cvmk", z, Wc, precision=hi)
        pm = jnp.einsum("cvms,vs->cvm", z, Mc, precision=hi)
        return y - pm[..., None] * colsum[None, None, None, :]

    @jax.jit
    def _run(raw, resolutions, cb0, Wc, Mc, colsum, row_of, mask):
        C, S = raw.shape
        V, max_m = cb0.shape
        S_pad = ((S + slab + BLK - 1) // BLK) * BLK
        padded = jnp.pad(raw, ((0, 0), (0, S_pad - S)))
        blocks = padded.reshape(C, S_pad // BLK, BLK)
        if V * max_m <= chunk_epochs:
            feats = _featurize_classes(
                blocks, resolutions, cb0, Wc, Mc, colsum
            )
        else:
            mchunk = max(8, (chunk_epochs // V) // 8 * 8)
            n_chunks = -(-max_m // mchunk)
            pad_m = n_chunks * mchunk - max_m
            # padded slots gather block 0 — valid rows, never selected
            cbp = jnp.pad(cb0, ((0, 0), (0, pad_m)))
            per_chunk = jnp.transpose(
                cbp.reshape(V, n_chunks, mchunk), (1, 0, 2)
            )
            feats = jax.lax.map(
                lambda cb: _featurize_classes(
                    blocks, resolutions, cb, Wc, Mc, colsum
                ),
                per_chunk,
            )  # (n_chunks, C, V, mchunk, K)
            feats = jnp.transpose(feats, (1, 2, 0, 3, 4)).reshape(
                C, V, n_chunks * mchunk, -1
            )[:, :, :max_m]
        K = feats.shape[-1]
        out = jnp.transpose(feats, (1, 2, 0, 3)).reshape(
            V * max_m, C * K
        )
        out = dwt_xla.safe_l2_normalize(out)
        return out[row_of] * mask[:, None].astype(out.dtype)

    def featurize(raw_i16, resolutions, positions, mask):
        plan = cached_block_class_plan(
            np.asarray(positions),
            np.asarray(mask),
            int(raw_i16.shape[1]),
            wavelet_index=wavelet_index,
            epoch_size=epoch_size,
            skip_samples=skip_samples,
            feature_size=feature_size,
            pre=pre,
        )
        return _run(
            raw_i16,
            jnp.asarray(resolutions, jnp.float32),
            jnp.asarray(plan.class_b0),
            jnp.asarray(plan.Wc),
            jnp.asarray(plan.Mc),
            jnp.asarray(plan.colsum),
            jnp.asarray(plan.row_of),
            jnp.asarray(np.asarray(mask, dtype=bool)),
        )

    # host planner + inner jitted program, exposed so callers that
    # loop on device (the bench's scan) can plan once and time _run
    featurize.plan = lambda positions, mask, n_samples: (
        cached_block_class_plan(
            np.asarray(positions), np.asarray(mask), int(n_samples),
            wavelet_index=wavelet_index, epoch_size=epoch_size,
            skip_samples=skip_samples, feature_size=feature_size,
            pre=pre,
        )
    )
    featurize._run = _run
    return featurize


def ingest_recording(
    recording: Recording,
    guessed_number: int,
    channel_indices: Sequence[int],
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    balance: Optional[BalanceState] = None,
    device=None,
):
    """Whole-recording device ingest.

    Returns (epochs, plan): ``epochs`` is a (capacity, n_channels,
    post) float32 device array (padded rows zeroed, ``plan.mask``
    marks real ones), ``plan`` carries targets/stimulus indices.

    Non-INT_16 recordings (e.g. IEEE_FLOAT_32) stage the already
    scaled float32 channels instead of raw int16 — same graph, unit
    resolutions, just without the 2x transfer saving.
    """
    raw, res, n_samples = stage_raw(recording, channel_indices)
    plan = plan_ingest(
        recording.markers,
        guessed_number,
        n_samples,
        pre=pre,
        post=post,
        balance=balance,
    )
    put = (lambda x: jax.device_put(x, device)) if device else jax.device_put
    epochs = make_device_epocher(pre, post)(
        put(raw), put(res), put(plan.positions), put(plan.mask)
    )
    return epochs, plan
