"""On-device decode+window ingest: int16 stream -> features, no gather.

The irregular-ingest gap this module closes (ROADMAP item 4): the
fused hot path's math runs at ~1M eps on this machine (``einsum_512``)
while every irregular-marker ingest rung sits 10-60x below it —
``block_ingest`` ~17k eps, the XLA element gather ~32k — because XLA
lowers the marker-window gather to per-ELEMENT loads (~5 ns/element on
CPU regardless of row width; measured while building this module, see
docs/performance.md "roofline" section). The math was never the
ceiling; the window *cut* was.

This module is the ``decode`` rung of the fused degradation ladder
(io/provider.FUSED_DEGRADATION_LADDER): raw unscaled int16 samples are
staged once and ONE jitted program decodes (int16 -> f32 resolution
scale), windows, baseline-corrects, and featurizes every kept marker —
no host float64 epoch ever materializes, and no XLA gather runs. Two
formulations share the contract:

- ``slice`` (the classed-block XLA twin, CPU/interpreter default):
  windows are cut by ``lax.dynamic_slice`` inside a ``lax.scan`` over
  small tiles — each window is a real memcpy instead of 612x3 scalar
  gathers — and each tile's windows contract against the cascade
  operator as one flattened 2-D matmul (the ``_ingest_reshape``
  layout trick). Measured on the 2-core CPU fallback: ~280k eps
  steady state vs 32k for the element gather and 17k for the classed
  block formulation, and the program compiles ~3.5x faster (the e2e
  cold lever). Tile size ``DEFAULT_TILE`` amortizes scan-step
  overhead; larger tiles regress (the stack materialization stops
  fitting cache).
- ``bank128`` (accelerators): the chip-proven Pallas kernel
  (ops/ingest_pallas.py) — windows cut in VMEM by dynamic sublane
  slabs, the 128-variant operator bank absorbing the in-row shift.
  ``precision="bf16"`` routes to its ``bank128_bf16`` twin.

Numerics: the slice formulation is subtract-first (explicit pre-mean
baseline before the contraction), the same shape as the XLA gather
rung — parity measured at ~6e-7 (inside the ladder's ~1e-7-class
contract; pinned in tests/test_decode_ingest.py). The bf16 path
carries its own documented gate (``BF16_GATE_TOL``): features are
compared against an f32 reference per run and the path auto-disables
above the gate (pipeline/builder.py records the decision).

Host planning (clip + tile packing) is trivial but real work per
marker layout; it is memoized in ``ops/plan_cache`` under
``decode_window_plan`` so steady-state re-ingest of an unchanged
recording re-plans nothing (and the bench's ``plan_cache`` field can
attribute warm-plan speedups).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import constants
from . import device_ingest
from . import dwt as dwt_xla

#: windows per scan step for the slice formulation. CPU-tuned: TB=4
#: was the best of {2, 4, 8, 16} on the 2-core fallback (280k eps;
#: TB=16 halves throughput — the stacked tile stops fitting cache).
DEFAULT_TILE = 4


def default_splits() -> int:
    """How many independent scans the slice program splits its tiles
    across. A single ``lax.scan`` is inherently serial; XLA:CPU runs
    INDEPENDENT scan thunks concurrently, so splitting the tile axis
    puts the idle cores to work (measured on the 2-core fallback:
    1 split 266k eps, 2 splits 376k — 1.41x; 4 splits plateaued).
    Always a power of two capped at 4: the planner's geometric
    capacity bucket (64*2^k) makes power-of-two tile counts, so a
    non-power split (3 on a 3-core host) would never divide them and
    _slice_program would silently fall back to one serial scan."""
    import os

    cores = os.cpu_count() or 1
    if cores >= 4:
        return 4
    return 2 if cores >= 2 else 1

#: the decode formulation family (single source for the library, the
#: bench, and tests).
DECODE_FORMULATIONS = ("slice", "bank128")

#: the feature precision ladder, loosest last (single source for the
#: builder, the IR, the serving engine, and this module's validation):
#: f32 is the ~1e-7 ladder-rung contract; bf16 computes the cascade
#: contraction on bfloat16 operands; int8 and int4 quantize the
#: finished f32 feature rows per subband (int4 lives in ops/quant.py:
#: 4-bit levels, two nibbles per byte in the shipped representation).
#: Every non-f32 rung runs behind a per-run measured-deviation gate
#: with per-run auto-disable.
PRECISIONS = ("f32", "bf16", "int8", "int4")

#: env override for the platform-resolved formulation.
ENV_FORMULATION = "EEG_TPU_DECODE_FORMULATION"

#: bf16 feature gate: max abs deviation of bf16-path features vs the
#: f32 reference on the SAME rows before the path auto-disables. The
#: bound is the bf16 feature tier's envelope (einsum_bf16 measured
#: ~2e-3 typical, 1.7e-3 worst-case under full-range DC in the bank
#: kernel's r4 analysis; L2-normalized rows keep deviations O(2^-8)
#: relative). Distinct from — and three orders looser than — the f32
#: ladder-rung contract (~1e-7), which bf16 deliberately does not
#: promise. Override for experiments via EEG_TPU_BF16_GATE_TOL.
BF16_GATE_TOL = 5e-3

#: int8 feature gate: max abs deviation of the int8-quantized feature
#: rows vs the f32 reference on the SAME rows before the rung
#: auto-disables. The bound follows from the quantizer itself:
#: symmetric per-(channel, subband) scales put the worst rounding
#: error at scale/2 = group_max/254, and L2-normalized rows keep
#: group_max <= 1, so the arithmetic envelope is ~4e-3; 2e-2 leaves
#: the same headroom-over-envelope factor the bf16 gate carries
#: (energy-subband classifiers — arXiv:1307.7897 — are the workload
#: this aggressive rung is plausibly safe for; the gate decides per
#: run). Override for experiments via EEG_TPU_INT8_GATE_TOL.
INT8_GATE_TOL = 2e-2


#: the standing r4 chip evidence the accelerator default is judged
#: against (tools/sweep_results/r4): the classed-block rung measured
#: 1.15M eps on the v5e chip = 21x the 54.8k element gather, so block
#: holds the accelerator default until the bank128 kernel's own chip
#: timing lands and beats it by the pre-registered margin.
CHIP_BLOCK_EPS = 1_151_915.7  # tools/sweep_results/r4/block_ingest.json
CHIP_GATHER_EPS = 54_841.8  # tools/sweep_results/r4/xla_ingest.json

#: the pre-registered flip threshold (docs/chip_playbook.md, r4b
#: decision table): bank128 must beat block by >= this ratio on chip
#: before the accelerator `-fused` default routes to the decode rung.
BANK128_FLIP_RATIO = 2.0

#: sweep-artifact filename stems that carry a bank128 chip timing
#: (tools/collect_chip_runs_r4b.sh writes bank128_*.json; the r4-era
#: list wrote pallas_ingest.json, which defaults to the bank kernel).
_BANK128_ARTIFACTS = ("bank128_*.json", "pallas_ingest*.json")


def _sweep_results_root() -> str:
    """Where the chip-run artifacts live; ``EEG_TPU_SWEEP_RESULTS``
    overrides (tests point it at fabricated trees)."""
    import os

    override = os.environ.get("EEG_TPU_SWEEP_RESULTS")
    if override:
        return override
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "tools", "sweep_results",
    )


def accelerator_decision(root: str | None = None) -> dict:
    """The decode rung's accelerator decision path, as DATA: harvest
    the best on-chip bank128 timing from the staged sweep artifacts
    and judge it against the block rung's standing chip number at the
    pre-registered threshold (docs/chip_playbook.md). Returns the
    record ``{"backend", "bank128_eps", "source", "block_eps",
    "threshold_eps", "reason"}`` — ``backend`` is what a bare
    ``fe=dwt-<i>-fused`` resolves to on accelerators
    (``device_ingest.default_fused_backend`` consults this), and the
    whole record is auditable: the flip happens when (and only when) a
    measured-silicon artifact says the bank kernel earns it, never
    from a hardcoded guess. With no bank128 chip artifact on disk
    (the r4b collection never landed — the tunnel died first), the
    decision is ``block`` with that absence as the recorded reason.
    """
    import glob
    import json
    import os

    base = root or _sweep_results_root()
    best_eps = None
    best_src = None
    for pattern in _BANK128_ARTIFACTS:
        for path in glob.glob(os.path.join(base, "*", pattern)):
            try:
                if os.path.getsize(path) == 0:
                    continue
                with open(path) as f:
                    rec = json.loads(f.read().strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                continue
            if rec.get("platform") not in ("tpu", "axon"):
                continue
            eps = rec.get("epochs_per_s")
            if not isinstance(eps, (int, float)) or eps <= 0:
                continue
            if best_eps is None or eps > best_eps:
                best_eps, best_src = float(eps), path
    threshold = BANK128_FLIP_RATIO * CHIP_BLOCK_EPS
    decision = {
        "bank128_eps": best_eps,
        "source": (
            os.path.relpath(best_src, os.path.dirname(base))
            if best_src
            else None
        ),
        "block_eps": CHIP_BLOCK_EPS,
        "threshold_eps": threshold,
    }
    if best_eps is None:
        decision.update(
            backend="block",
            reason=(
                "no on-chip bank128 timing in sweep artifacts; the "
                "block rung's measured 21x-gather chip figure stands"
            ),
        )
    elif best_eps >= threshold:
        decision.update(
            backend="decode",
            reason=(
                f"bank128 measured {best_eps:.0f} eps on chip >= "
                f"{BANK128_FLIP_RATIO:g}x block ({threshold:.0f}); "
                f"the decode rung (bank128 routing) takes the default"
            ),
        )
    else:
        decision.update(
            backend="block",
            reason=(
                f"bank128 measured {best_eps:.0f} eps on chip < "
                f"{BANK128_FLIP_RATIO:g}x block ({threshold:.0f}); "
                f"block stands"
            ),
        )
    return decision


@functools.lru_cache(maxsize=None)
def default_accelerator_backend() -> str:
    """The cached accelerator resolution of :func:`accelerator_decision`
    (one artifact walk per process; the decision itself is cheap but
    globs the sweep tree)."""
    return accelerator_decision()["backend"]


def default_formulation() -> str:
    """Platform default: ``slice`` on CPU (scan+dynamic_slice — the
    memcpy window cut XLA:CPU needs), ``bank128`` on accelerators
    (windows cut in VMEM; the only formulation proven to compile
    through the axon remote helper). ``EEG_TPU_DECODE_FORMULATION``
    overrides."""
    import os

    forced = os.environ.get(ENV_FORMULATION)
    if forced:
        if forced not in DECODE_FORMULATIONS:
            raise ValueError(
                f"unknown decode formulation {forced!r} in "
                f"{ENV_FORMULATION}; supported: {DECODE_FORMULATIONS}"
            )
        return forced
    return "slice" if jax.devices()[0].platform == "cpu" else "bank128"


def bucket_capacity(cap: int) -> int:
    """Pad a plan capacity up to 64 x a power of two (64, 128, 256,
    512, ...). ``plan_ingest`` buckets to 64-MULTIPLES, which still
    gives every recording of a multi-file session its own jit shape
    (448 vs 512 vs ...) — and the cold e2e number is compile-bound,
    so per-recording recompiles of the decode program were its
    dominant ingest cost. Geometric bucketing bounds the padded
    compute below 2x (the kernel is cheap; the compile is not) and
    collapses a session's recordings onto one compiled shape."""
    b = 64
    while b < cap:
        b *= 2
    return b


def plan_decode_windows(
    positions: np.ndarray,
    mask: np.ndarray,
    n_samples: int,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    window: int = 787,
    tile: int = DEFAULT_TILE,
) -> np.ndarray:
    """Host tile plan for the slice formulation: clipped window starts
    padded to the geometric capacity bucket (:func:`bucket_capacity`)
    and reshaped to ``(n_tiles, tile)`` int32 — padded rows start at 0
    and are masked/sliced off downstream. Memoized in
    ``ops/plan_cache`` keyed on the layout digest + geometry — the
    same zero-re-planning contract the block and Pallas planners
    carry."""
    from . import plan_cache as _pc

    positions = np.asarray(positions)
    mask = np.asarray(mask, dtype=bool)
    key = _pc.digest(
        positions,
        mask,
        extra=("decode_window", int(n_samples), pre, window, tile),
    )

    def build():
        cap = positions.shape[0]
        if cap % tile:
            raise ValueError(
                f"decode plan needs capacity % tile == 0; got "
                f"{cap} % {tile} (plan_ingest's 64-multiple bucketing "
                f"satisfies any tile that divides 64)"
            )
        # the same clip the gather/block rungs apply, so all rungs cut
        # identical windows (overhang past the end reads the zero pad
        # — Java copyOfRange semantics)
        starts = np.clip(
            positions.astype(np.int64) - pre, 0, int(n_samples)
        ).astype(np.int32)
        starts = starts * mask  # padded rows slice at offset 0
        bucket = bucket_capacity(cap)
        if bucket != cap:
            starts = np.pad(starts, (0, bucket - cap))
        return starts.reshape(bucket // tile, tile)

    return _pc.cache("decode_window_plan").get_or_build(key, build)


@functools.lru_cache(maxsize=None)
def _slice_program(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    pre: int,
    tile: int,
    bf16: bool,
    donate_stream: bool,
    splits: int = 1,
):
    """The jitted slice-formulation program, cached per geometry.

    (raw int16 (C, S_pad), resolutions (C,), start tiles (nt, tile),
    mask (cap,)) -> (cap, C*K) float32 masked features. The scan body
    cuts ``tile`` windows as dynamic slices (memcpys), stacks them,
    and contracts the live columns as ONE flattened (tile*C, 512)
    matmul — the layout every CPU/TPU backend keeps on the fast GEMM
    path (the ``_ingest_reshape`` finding). The tile axis is divided
    over ``splits`` INDEPENDENT scans so XLA:CPU's concurrent thunk
    execution spreads them across cores (:func:`default_splits`);
    results concatenate in tile order, so the output is identical for
    any split count. ``bf16`` casts the centered operand and the
    operator to bfloat16 with f32 accumulation: mean-centering
    happens in f32 FIRST, so the cast rounds residual-scale values,
    not int16-range DC (the bank-kernel ordering argument).
    """
    win = pre + skip_samples + epoch_size
    W_np = np.asarray(
        dwt_xla.cascade_matrix(wavelet_index, epoch_size, feature_size),
        np.float32,
    )

    @functools.partial(
        jax.jit, donate_argnums=(0,) if donate_stream else ()
    )
    def run(raw_i16, resolutions, start_tiles, mask):
        C = raw_i16.shape[0]
        K = feature_size
        nt, tb = start_tiles.shape
        # NO in-program pad: a jnp.pad of the whole stream would copy
        # the 10s-of-MB int16 block on EVERY call (measured ~4x the
        # program's entire compute). The host wrapper guarantees every
        # slice exists (see featurize()'s conditional tail pad).
        W = jnp.asarray(W_np, jnp.bfloat16 if bf16 else jnp.float32)

        def body(_, srow):
            segs = [
                lax.dynamic_slice(raw_i16, (0, srow[t]), (C, win))
                for t in range(tb)
            ]
            seg = (
                jnp.stack(segs).astype(jnp.float32)
                * resolutions[None, :, None]
            )  # (tile, C, win) f32, scaled
            # explicit subtract-first baseline (Baseline.java:29-57):
            # folding it into W cancels catastrophically on real EEG
            # DC offsets (the ingest_matrix fold_baseline analysis)
            base = jnp.mean(seg[:, :, :pre], axis=2)
            z = seg[:, :, pre + skip_samples:] - base[..., None]
            zt = z.reshape(tb * C, epoch_size)
            if bf16:
                y = lax.dot_general(
                    zt.astype(jnp.bfloat16), W,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            else:
                y = lax.dot_general(
                    zt, W, (((1,), (0,)), ((), ())),
                    precision=lax.Precision.HIGHEST,
                )
            return _, y.reshape(tb, C * K)

        ns = splits if nt % splits == 0 else 1
        # unroll the scan body only when the per-scan step count is
        # large: +20% steady-state on the CPU fallback (step dispatch
        # amortized), but the duplicated body inflates compile time —
        # which dominates the COLD pipeline number at its small
        # per-recording step counts, where unrolling would give back
        # the compile win that moves it
        u = 4 if (nt // ns) >= 256 else 1

        def one_scan(tiles):
            _, ys = lax.scan(body, 0, tiles, unroll=u)
            return ys

        if ns > 1:
            grouped = start_tiles.reshape(ns, nt // ns, tb)
            ys = jnp.concatenate(
                [one_scan(grouped[i]) for i in range(ns)], axis=0
            )
        else:
            ys = one_scan(start_tiles)
        feats = dwt_xla.safe_l2_normalize(
            ys.reshape(nt * tb, C * K)
        )
        return feats * mask[:, None].astype(feats.dtype)

    return run


def make_decode_ingest_featurizer(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    tile: int = DEFAULT_TILE,
    formulation: str | None = None,
    precision: str = "f32",
    donate_stream: bool = False,
):
    """Callable (raw int16 (C, S), resolutions, positions, mask) ->
    (capacity, C*K) float32 features — the ``decode`` rung's plug-in
    counterpart of ``make_classed_block_ingest_featurizer`` (same
    contract: concrete IngestPlan positions/mask, padded rows zeroed).

    ``formulation`` None resolves per call via
    :func:`default_formulation` (never cached — the
    'auto'-resolution staleness class device_ingest documents).
    ``precision="bf16"`` computes the cascade matmul in bfloat16 with
    f32 accumulation; ``precision="int8"`` / ``"int4"`` compute f32
    features and quantize the finished rows per subband
    (:func:`quantize_dequantize_int8` and ``quant.int4_feature_path``
    — the rungs below bf16, loosest last). Callers gate every non-f32
    rung per run (:func:`feature_precision_gate` /
    pipeline/builder.py).
    ``donate_stream`` donates the staged int16 stream buffer to the
    program (the overlap path's ping/pong staging — the stream is
    dead after the on-device scale); skipped on CPU, where XLA cannot
    alias it and would warn per call.
    """
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}; use one of {PRECISIONS}"
        )
    if 64 % tile:
        raise ValueError(
            f"tile {tile} must divide the planner's 64-row capacity "
            f"bucket"
        )
    win = pre + skip_samples + epoch_size

    def featurize(raw_i16, resolutions, positions, mask):
        form = formulation or default_formulation()
        positions = np.asarray(positions)
        mask = np.asarray(mask, dtype=bool)
        if form == "bank128":
            out = _bank_featurize(
                raw_i16, resolutions, positions, mask,
                wavelet_index, epoch_size, skip_samples, feature_size,
                # int8/int4 quantize FINISHED f32 rows; the kernel
                # itself runs the f32 formulation (bf16 keeps its twin)
                pre, "bf16" if precision == "bf16" else "f32",
            )
            if precision == "int8":
                out = int8_feature_path(out, feature_size)
            elif precision == "int4":
                from . import quant

                out = quant.int4_feature_path(out, feature_size)
            return out
        donate = donate_stream and jax.default_backend() != "cpu"
        run = _slice_program(
            wavelet_index, epoch_size, skip_samples, feature_size,
            pre, tile, precision == "bf16", donate,
            splits=default_splits(),
        )
        S = int(raw_i16.shape[1])
        tiles = plan_decode_windows(
            positions, mask, S, pre=pre, window=win, tile=tile,
        )
        cap = mask.shape[0]
        bucket = tiles.size
        mask_b = (
            mask if bucket == cap else np.pad(mask, (0, bucket - cap))
        )
        raw_dev = jnp.asarray(raw_i16)
        if tiles.size and int(tiles.max()) + win > S:
            # rare: the staged tail slack (stage_raw's 16384-sample
            # bucketing) is thinner than one window — extend with
            # zeros so an overhanging window reads zeros (Java
            # copyOfRange semantics) instead of dynamic_slice's clamp
            # silently SHIFTING it. Host-side and per recording: the
            # in-program jnp.pad alternative copies the whole stream
            # every call (measured ~4x the program's compute).
            raw_dev = jnp.pad(
                raw_dev, ((0, 0), (0, int(tiles.max()) + win - S))
            )
        out = run(
            raw_dev,
            jnp.asarray(resolutions, jnp.float32),
            jnp.asarray(tiles),
            jnp.asarray(mask_b),
        )
        # bucket padding never leaves this wrapper: callers see the
        # plan's own capacity, like every other rung
        out = out if bucket == cap else out[:cap]
        if precision == "int8":
            # quantize the finished rows (padded/masked rows are zero
            # and stay zero — abs-max scales never see them as peaks)
            out = int8_feature_path(out, feature_size)
        elif precision == "int4":
            from . import quant

            out = quant.int4_feature_path(out, feature_size)
        return out

    featurize.tile = tile
    featurize.precision = precision
    return featurize


def _bank_featurize(
    raw_i16, resolutions, positions, mask,
    wavelet_index, epoch_size, skip_samples, feature_size, pre,
    precision,
):
    """The accelerator formulation: kept markers through the
    chip-proven bank128 Pallas kernel (windows cut in VMEM), scattered
    back into the capacity rows so the decode rung's contract matches
    the slice twin's exactly. ``precision="bf16"`` ships the operator
    bank pre-cast (the kernel's ``bank128_bf16`` twin)."""
    from . import ingest_pallas

    kept = positions[mask]
    C = np.asarray(raw_i16).shape[0]
    K = feature_size
    cap = positions.shape[0]
    if kept.size == 0:
        return jnp.zeros((cap, C * K), jnp.float32)
    feats = ingest_pallas.ingest_features_pallas(
        np.asarray(raw_i16),
        np.asarray(resolutions, np.float32),
        kept,
        wavelet_index=wavelet_index,
        epoch_size=epoch_size,
        skip_samples=skip_samples,
        feature_size=feature_size,
        pre=pre,
        mode="bank128_bf16" if precision == "bf16" else "bank128",
    )  # (n_kept, C*K), marker order
    out = jnp.zeros((cap, C * K), feats.dtype)
    return out.at[np.nonzero(mask)[0]].set(feats)


def subband_group_bounds(feature_size: int):
    """The per-subband column groups of one channel's ``feature_size``
    DWT coefficients, as ``((lo, hi), ...)`` half-open bounds.

    The eegdsp cascade layout is ``[aK | dK | ... | d1]``: the
    approximation coefficient first, then detail bands of doubling
    width — for the shipped K=16 that is groups (0,1), (1,2), (2,4),
    (4,8), (8,16). Subbands carry very different energy (the
    1/f-shaped EEG spectrum), which is why the int8 rung scales each
    group independently instead of one scale per row: a coarse
    approximation coefficient near 1.0 would otherwise eat the whole
    int8 range and crush the fine detail bands to zero.
    """
    if feature_size < 1:
        raise ValueError(f"feature_size must be >= 1, got {feature_size}")
    bounds = [(0, 1)]
    lo = 1
    while lo < feature_size:
        hi = min(feature_size, lo * 2)
        bounds.append((lo, hi))
        lo = hi
    return tuple(bounds)


def quantize_dequantize_int8(rows, feature_size: int):
    """The int8 feature-quantization rung's core (traceable): symmetric
    per-(row, channel, subband) scales, round-to-nearest into int8,
    immediate dequantization back to f32.

    ``rows`` is the fused path's ``(n, C*K)`` float32 feature matrix
    (channel-major, K = ``feature_size``). Returns ``(dequantized
    rows (n, C*K) f32, scales (n_groups, n, C) f32)``. The round trip
    IS the rung: downstream consumers (classifiers, the margin) keep
    their f32 contract while every value has passed through 8 bits —
    the representation a quantized serving deployment would ship.
    Scales are per ROW deliberately: a batch-wide max would couple one
    request's quantization grid to whatever rides in its micro-batch
    (a served window's features — and a margin near the decision
    threshold — would change with concurrent traffic), so each row
    quantizes against its own subband peaks and the output is
    row-independent: bit-identical whatever batch it rides in, the
    same contract the mega rung carries. Deterministic (no stochastic
    rounding — a re-run of the same content must produce
    byte-identical features, the cache contract), and zero rows stay
    exactly zero (an all-zero group's clamped scale just divides
    zeros).
    """
    import jax.numpy as jnp

    n = rows.shape[0]
    K = int(feature_size)
    C = rows.shape[1] // K
    x = rows.reshape(n, C, K)
    outs = []
    scales = []
    for lo, hi in subband_group_bounds(K):
        g = x[:, :, lo:hi]
        s = jnp.max(jnp.abs(g), axis=2) / 127.0  # (n, C)
        s = jnp.maximum(s, 1e-30)  # all-zero group: 0/s stays 0
        q = jnp.clip(jnp.round(g / s[..., None]), -127.0, 127.0)
        outs.append(q.astype(jnp.int8).astype(jnp.float32)
                    * s[..., None])
        scales.append(s)
    return (
        jnp.concatenate(outs, axis=2).reshape(n, C * K),
        jnp.stack(scales),
    )


@functools.lru_cache(maxsize=None)
def _int8_path_program(feature_size: int):
    @jax.jit
    def run(rows):
        dq, _ = quantize_dequantize_int8(rows, feature_size)
        return dq

    return run


def int8_feature_path(rows, feature_size: int):
    """Jitted quantize→dequantize pass over finished feature rows —
    the int8 rung the decode featurizer (and the serving engine's
    int8 program) applies after the f32 math."""
    return _int8_path_program(int(feature_size))(rows)


def int8_gate_tolerance() -> float:
    """The documented int8 feature gate (``INT8_GATE_TOL``), with the
    experiment override ``EEG_TPU_INT8_GATE_TOL`` — same logged-never-
    silent fallback policy as :func:`bf16_gate_tolerance`."""
    import logging
    import os

    raw = os.environ.get("EEG_TPU_INT8_GATE_TOL")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logging.getLogger(__name__).warning(
                "EEG_TPU_INT8_GATE_TOL=%r is not a float; using the "
                "default gate %g", raw, INT8_GATE_TOL,
            )
    return INT8_GATE_TOL


def precision_gate_tolerance(precision: str) -> float:
    """The measured-deviation gate for one non-f32 precision rung
    (env-overridable per rung)."""
    if precision == "bf16":
        return bf16_gate_tolerance()
    if precision == "int8":
        return int8_gate_tolerance()
    if precision == "int4":
        from . import quant

        return quant.int4_gate_tolerance()
    raise ValueError(
        f"precision {precision!r} has no accuracy gate (f32 IS the "
        f"reference)"
    )


def bf16_gate_tolerance() -> float:
    """The documented bf16 feature gate (``BF16_GATE_TOL``), with the
    experiment override ``EEG_TPU_BF16_GATE_TOL``. An unparseable
    override is LOGGED before falling back — the gate's whole policy
    is "recorded, never silent", and an ignored typo'd experiment
    knob judging against the default would be exactly that."""
    import logging
    import os

    raw = os.environ.get("EEG_TPU_BF16_GATE_TOL")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logging.getLogger(__name__).warning(
                "EEG_TPU_BF16_GATE_TOL=%r is not a float; using the "
                "default gate %g", raw, BF16_GATE_TOL,
            )
    return BF16_GATE_TOL


def feature_precision_gate(
    rows: np.ndarray,
    f32_rows: np.ndarray,
    precision: str = "bf16",
    tolerance: float | None = None,
) -> dict:
    """The per-run accuracy gate shared by every non-f32 precision
    rung: max abs deviation of the rung's feature rows against the
    f32 reference rows on the SAME windows, judged against that rung's
    documented tolerance. Returns the decision record the run report
    embeds: ``{"precision", "max_abs_dev", "tolerance", "ok",
    "rows_checked"}``.
    """
    tol = (
        precision_gate_tolerance(precision)
        if tolerance is None
        else float(tolerance)
    )
    rows = np.asarray(rows, np.float32)
    f32_rows = np.asarray(f32_rows, np.float32)
    if rows.shape != f32_rows.shape:
        raise ValueError(
            f"gate rows misaligned: {rows.shape} vs {f32_rows.shape}"
        )
    dev = (
        float(np.max(np.abs(rows - f32_rows)))
        if rows.size
        else 0.0
    )
    return {
        "precision": str(precision),
        "max_abs_dev": dev,
        "tolerance": tol,
        "ok": bool(dev <= tol),
        "rows_checked": int(rows.shape[0]),
    }


def bf16_feature_gate(
    bf16_rows: np.ndarray,
    f32_rows: np.ndarray,
    tolerance: float | None = None,
) -> dict:
    """The bf16 spelling of :func:`feature_precision_gate` (the PR 8
    surface, kept verbatim for its callers and pins)."""
    return feature_precision_gate(
        bf16_rows, f32_rows, precision="bf16", tolerance=tolerance
    )
