"""Cached host gather plans for the fused-ingest hot path.

The irregular-marker fused-ingest formulations all split the work the
same way: the host derives a *gather plan* from marker metadata (tile
packing for the Pallas kernel, alignment-class grouping for the block
formulation, offset/shift encodings for the bank kernel) and the
device consumes the plan's arrays. Planning is pure host work — numpy
sorts, bincounts, and operator-table writes — and it is a function of
nothing but the marker layout, the staged shapes/dtype, and the DWT
geometry. A steady-state service re-ingesting the same recording (or
re-running a step over an unchanged marker layout) therefore should
pay for planning exactly once.

This module is the shared memo for those planners: a small named-LRU
keyed on a content digest of the planner inputs — (marker layout
hash, shapes, dtype, geometry) — with hit/miss counters that the
bench surfaces as the per-variant ``plan_cache`` field, so a BENCH
trajectory can attribute a throughput move to warm plans rather than
guessing.

Entries are host-side numpy plans (never jax arrays: caching a
traced/device value here would leak tracers across jit boundaries —
the poisoning class ``device_ingest._phase_tables`` documents). The
capacity bounds memory for long-running services ingesting many
distinct recordings; ``EEG_TPU_PLAN_CACHE_SIZE`` overrides it.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Tuple

import numpy as np

#: default per-cache entry bound; plans are small (KBs to a few MBs of
#: int32/f32 numpy), so 128 layouts ~ tens of MB worst case.
_DEFAULT_CAPACITY = 128


def _capacity() -> int:
    try:
        return max(1, int(os.environ.get("EEG_TPU_PLAN_CACHE_SIZE", "")))
    except ValueError:
        return _DEFAULT_CAPACITY


class PlanCache:
    """One named, thread-safe, bounded LRU of host gather plans.

    ``capacity`` overrides the shared default bound for caches whose
    entries are much larger than the KB-scale plans the default is
    sized for (e.g. the MB-scale block-class operator tables)."""

    def __init__(self, name: str, capacity: int = None):
        self.name = name
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key: str, builder: Callable[[], object]):
        """Return the cached plan for ``key``, building (and caching)
        it on a miss. The builder runs outside the lock — planning can
        be slow, and two racing builders for the same key are merely
        redundant, not wrong (plans are pure functions of the key)."""
        with self._lock:
            if key in self._entries:
                self.hits += 1
                self._entries.move_to_end(key)
                return self._entries[key]
        value = builder()
        with self._lock:
            self.misses += 1
            self._entries[key] = value
            self._entries.move_to_end(key)
            cap = self.capacity or _capacity()
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
        return value

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "entries": len(self._entries),
            }

    def clear(self) -> None:
        """Drop entries AND counters (test/bench isolation)."""
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0


_registry_lock = threading.Lock()
_registry: Dict[str, PlanCache] = {}


def cache(name: str, capacity: int = None) -> PlanCache:
    """The process-wide cache for ``name`` (created on first use;
    ``capacity`` applies only at creation)."""
    with _registry_lock:
        if name not in _registry:
            _registry[name] = PlanCache(name, capacity=capacity)
        return _registry[name]


def digest(*arrays: np.ndarray, extra: Tuple = ()) -> str:
    """Content key for planner inputs: dtype + shape + raw bytes of
    every array, plus the repr of the static ``extra`` tuple (shapes,
    geometry ints, dtype names). blake2b keeps hashing a ~100K-marker
    layout well under a millisecond — noise next to re-planning."""
    h = hashlib.blake2b(digest_size=20)
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(repr(extra).encode())
    return h.hexdigest()


def stats() -> Dict[str, object]:
    """Aggregate + per-cache counters — the bench's ``plan_cache``
    payload field. Always carries ``hits``/``misses`` (zeros when no
    planner ran), so the field is schema-stable across variants."""
    with _registry_lock:
        caches = list(_registry.values())
    per = {c.name: c.stats() for c in caches}
    return {
        "hits": sum(s["hits"] for s in per.values()),
        "misses": sum(s["misses"] for s in per.values()),
        "caches": per,
    }


def clear() -> None:
    """Reset every registered cache (entries and counters)."""
    with _registry_lock:
        caches = list(_registry.values())
    for c in caches:
        c.clear()


# -- cross-process persistence ------------------------------------------
#
# Each bench variant runs in its own fresh child (bench.py's
# resilience contract), so without persistence every recorded
# block_ingest/pallas_ingest line shows ``hits: 0`` — the cache's
# effectiveness was structurally unmeasurable. When
# ``EEG_TPU_PLAN_CACHE_FILE`` names a file, a process can load the
# previous process's plans at startup and save the union at exit
# (tools/ingest_bench.py does both), so a repeat bench run — or a
# later variant of the same run that plans the same layout — reports
# real hit counts. The file is a local, trusted pickle (plans are
# plain numpy containers produced by this package); loading ignores a
# missing or unreadable file and counts nothing.

ENV_FILE = "EEG_TPU_PLAN_CACHE_FILE"


def persist_path(path: str = None) -> str:
    """The persistence file in effect (explicit > env), or None."""
    return path or os.environ.get(ENV_FILE) or None


def save_file(path: str = None) -> str:
    """Pickle every registered cache's entries to ``path`` (atomic
    tmp + ``os.replace``); returns the path, or None when persistence
    is off or the write failed (never fatal)."""
    import pickle
    import tempfile

    path = persist_path(path)
    if path is None:
        return None
    with _registry_lock:
        caches = list(_registry.items())
    payload = {}
    for name, c in caches:
        with c._lock:
            # capacity rides along: a warm-started process must not
            # recreate a deliberately small cache (the MB-scale
            # block-class operator table's capacity=16) at the roomy
            # shared default
            payload[name] = {
                "capacity": c.capacity,
                "entries": dict(c._entries),
            }
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=".plan-cache-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except (OSError, pickle.PicklingError):
        return None
    return path


def load_file(path: str = None) -> int:
    """Populate the registered caches from ``path``; returns the
    number of entries loaded (0 on a missing/corrupt file — a warm
    start is best-effort). Loaded entries count as neither hits nor
    misses; the capacity bound applies normally."""
    import pickle

    path = persist_path(path)
    if path is None or not os.path.exists(path):
        return 0
    try:
        with open(path, "rb") as f:
            payload = pickle.load(f)
        if not isinstance(payload, dict):
            return 0
    except Exception:
        return 0
    loaded = 0
    for name, record in payload.items():
        if not isinstance(record, dict) or "entries" not in record:
            continue
        entries = record["entries"]
        if not isinstance(entries, dict):
            continue
        c = cache(name, capacity=record.get("capacity"))
        if c.capacity is None:
            # the cache may predate this load (created by a planner
            # import with no explicit bound); adopt the saved bound so
            # a warm start never voids a deliberately small capacity
            c.capacity = record.get("capacity")
        with c._lock:
            for key, value in entries.items():
                c._entries[key] = value
                c._entries.move_to_end(key)
                loaded += 1
            cap = c.capacity or _capacity()
            while len(c._entries) > cap:
                c._entries.popitem(last=False)
    return loaded
