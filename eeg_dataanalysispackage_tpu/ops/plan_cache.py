"""Cached host gather plans for the fused-ingest hot path.

The irregular-marker fused-ingest formulations all split the work the
same way: the host derives a *gather plan* from marker metadata (tile
packing for the Pallas kernel, alignment-class grouping for the block
formulation, offset/shift encodings for the bank kernel) and the
device consumes the plan's arrays. Planning is pure host work — numpy
sorts, bincounts, and operator-table writes — and it is a function of
nothing but the marker layout, the staged shapes/dtype, and the DWT
geometry. A steady-state service re-ingesting the same recording (or
re-running a step over an unchanged marker layout) therefore should
pay for planning exactly once.

This module is the shared memo for those planners: a small named-LRU
keyed on a content digest of the planner inputs — (marker layout
hash, shapes, dtype, geometry) — with hit/miss counters that the
bench surfaces as the per-variant ``plan_cache`` field, so a BENCH
trajectory can attribute a throughput move to warm plans rather than
guessing.

Entries are host-side numpy plans (never jax arrays: caching a
traced/device value here would leak tracers across jit boundaries —
the poisoning class ``device_ingest._phase_tables`` documents). The
capacity bounds memory for long-running services ingesting many
distinct recordings; ``EEG_TPU_PLAN_CACHE_SIZE`` overrides it.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Tuple

import numpy as np

#: default per-cache entry bound; plans are small (KBs to a few MBs of
#: int32/f32 numpy), so 128 layouts ~ tens of MB worst case.
_DEFAULT_CAPACITY = 128


def _capacity() -> int:
    try:
        return max(1, int(os.environ.get("EEG_TPU_PLAN_CACHE_SIZE", "")))
    except ValueError:
        return _DEFAULT_CAPACITY


class PlanCache:
    """One named, thread-safe, bounded LRU of host gather plans.

    ``capacity`` overrides the shared default bound for caches whose
    entries are much larger than the KB-scale plans the default is
    sized for (e.g. the MB-scale block-class operator tables)."""

    def __init__(self, name: str, capacity: int = None):
        self.name = name
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key: str, builder: Callable[[], object]):
        """Return the cached plan for ``key``, building (and caching)
        it on a miss. The builder runs outside the lock — planning can
        be slow, and two racing builders for the same key are merely
        redundant, not wrong (plans are pure functions of the key)."""
        with self._lock:
            if key in self._entries:
                self.hits += 1
                self._entries.move_to_end(key)
                return self._entries[key]
        value = builder()
        with self._lock:
            self.misses += 1
            self._entries[key] = value
            self._entries.move_to_end(key)
            cap = self.capacity or _capacity()
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
        return value

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "entries": len(self._entries),
            }

    def clear(self) -> None:
        """Drop entries AND counters (test/bench isolation)."""
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0


_registry_lock = threading.Lock()
_registry: Dict[str, PlanCache] = {}


def cache(name: str, capacity: int = None) -> PlanCache:
    """The process-wide cache for ``name`` (created on first use;
    ``capacity`` applies only at creation)."""
    with _registry_lock:
        if name not in _registry:
            _registry[name] = PlanCache(name, capacity=capacity)
        return _registry[name]


def digest(*arrays: np.ndarray, extra: Tuple = ()) -> str:
    """Content key for planner inputs: dtype + shape + raw bytes of
    every array, plus the repr of the static ``extra`` tuple (shapes,
    geometry ints, dtype names). blake2b keeps hashing a ~100K-marker
    layout well under a millisecond — noise next to re-planning."""
    h = hashlib.blake2b(digest_size=20)
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(repr(extra).encode())
    return h.hexdigest()


def stats() -> Dict[str, object]:
    """Aggregate + per-cache counters — the bench's ``plan_cache``
    payload field. Always carries ``hits``/``misses`` (zeros when no
    planner ran), so the field is schema-stable across variants."""
    with _registry_lock:
        caches = list(_registry.values())
    per = {c.name: c.stats() for c in caches}
    return {
        "hits": sum(s["hits"] for s in per.values()),
        "misses": sum(s["misses"] for s in per.values()),
        "caches": per,
    }


def clear() -> None:
    """Reset every registered cache (entries and counters)."""
    with _registry_lock:
        caches = list(_registry.values())
    for c in caches:
        c.clear()
