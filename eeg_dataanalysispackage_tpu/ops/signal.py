"""Signal-processing utilities (reference: Utils/SignalProcessing.java).

- :func:`decimate` — stride subsampling, ``output[i] = input[i*factor]``
  (SignalProcessing.java:29-36; unused in the reference's main path
  since ``DOWN_SMPL_FACTOR=1`` but part of its public surface);
- :func:`normalize` — in the reference an in-place L2 divide
  (SignalProcessing.java:38-52); here the bit-exact sequential host
  form lives in ``ops.dwt_host.l2_normalize_seq`` and the guarded
  device form in ``ops.dwt.safe_l2_normalize`` — both re-exported;
- :func:`fft_bandpass` — rfft-mask-irfft band-pass for the streaming
  front end (jnp.fft replaces the JTransforms jar on the reference's
  classpath, SURVEY.md section 2.2).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .dwt import safe_l2_normalize
from .dwt_host import l2_normalize_seq

__all__ = [
    "decimate",
    "normalize",
    "l2_normalize_seq",
    "safe_l2_normalize",
    "bandpass_mask",
    "fft_bandpass",
]


def bandpass_mask(n: int, fs: float, low: float, high: float) -> np.ndarray:
    """rfft-domain 0/1 mask keeping [low, high] Hz (inclusive edges)."""
    freqs = np.fft.rfftfreq(n, d=1.0 / fs)
    return ((freqs >= low) & (freqs <= high)).astype(np.float32)


def decimate(signal: np.ndarray, factor: int) -> np.ndarray:
    """Stride subsample over the last axis: keep every ``factor``-th
    sample, output length ``n // factor`` (SignalProcessing.java:29-36)."""
    if factor < 1:
        raise ValueError(f"decimation factor must be >= 1, got {factor}")
    n = signal.shape[-1] // factor
    return signal[..., : n * factor : factor]


def normalize(features: np.ndarray) -> np.ndarray:
    """L2-normalize over the last axis with the reference's exact
    arithmetic (alias of :func:`l2_normalize_seq`)."""
    return l2_normalize_seq(np.asarray(features, dtype=np.float64))


def fft_bandpass(
    signal, fs: float, low: float, high: float, axis: int = -1
):
    """Zero out rfft bins outside [low, high] Hz over ``axis``.

    Traceable (jnp) — usable inside jitted programs; the streaming
    extractor applies the same mask per window
    (parallel/streaming.py)."""
    x = jnp.asarray(signal)
    n = x.shape[axis]
    mask = bandpass_mask(n, fs, low, high)
    shape = [1] * x.ndim
    shape[axis] = mask.size
    spec = jnp.fft.rfft(x, axis=axis) * jnp.asarray(mask).reshape(shape)
    return jnp.fft.irfft(spec, n=n, axis=axis).astype(x.dtype)
