"""Pallas TPU kernel: fused int16 ingest for IRREGULAR marker positions.

The full reference ingest+feature chain — int16 -> resolution scale ->
marker-window gather -> float32 baseline correction -> analysis-window
slice -> 6-level DWT cascade -> channel concat -> L2 normalize
(OffLineDataProvider.java:167-265 + WaveletTransform.java:108-141) —
as ONE Pallas kernel over the raw recording. This is the fusion XLA
cannot do: ``ops/device_ingest.py``'s XLA formulation must materialize
a window gather (dynamic-slice chains over HBM); here the raw int16
stream is tiled into VMEM once and windows are cut *in VMEM*.

Design (see docs/ingest_kernel.md for the roofline discussion):

- Host planner (:func:`plan_pallas_tiles`): sort windows by start,
  greedily pack up to ``tile_b`` epochs whose windows fit in one
  ``chunk`` of the stream, aligned to half-chunk boundaries so the
  kernel's two half-chunk BlockSpecs (standard pipelined DMA — no
  manual descriptors, automatic double buffering, and a revisited
  half-chunk is NOT re-fetched) cover every tile. Any window fits
  some aligned chunk because ``window <= chunk/2``.
- Kernel: per grid step, the two int16 half-chunks are joined and
  scaled to float32 once; each epoch's 8-aligned window (787 live
  samples + slack; ``DEFAULT_WINDOW`` = 792) is a dynamic lane-slice
  from VMEM, baseline-
  corrected against the mean of its first ``pre`` samples (explicit
  subtraction — folding the baseline into the operator cancels
  catastrophically on real EEG DC offsets), and packed into a
  (tile_b*C, window) scratch; one MXU contraction against the padded
  cascade operator (:func:`..ops.device_ingest.ingest_matrix` with
  ``fold_baseline=False``; rows past 787 are zero, so the slack needs
  no masking) yields all features, which are normalized on the VPU
  and written as one (tile_b, C*K) block.
- Padded tile rows point at offset 0 and are dropped on unsort.

Interpret mode runs the same kernel on CPU for hermetic tests; on TPU
it compiles to Mosaic.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import constants
from . import device_ingest
from . import dwt as dwt_xla


@dataclasses.dataclass
class PallasTilePlan:
    """Host-side tiling of sorted epoch windows into VMEM chunks."""

    half_idx: np.ndarray  # (n_tiles,) int32 — first half-chunk index
    offsets: np.ndarray  # (n_tiles, tile_b) int32 — window start - half_idx*half
    src_rows: np.ndarray  # (n_tiles, tile_b) int32 — original epoch index (-1 pad)
    chunk: int
    tile_b: int

    @property
    def n_tiles(self) -> int:
        return self.half_idx.shape[0]


DEFAULT_WINDOW = 792  # ((100 + 175 + 512) + 7) // 8 * 8 — 787 live + slack


def kernel_window(
    mode: str,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    skip_samples: int = 175,
    epoch_size: int = 512,
) -> int:
    """Kernel segment width for a mode (single source for the library
    and the bench): ``exact`` pads the live window to 8; ``aligned8``
    additionally covers the residual 0..7 shift; ``bank128`` (and its
    ``bank128_bf16`` twin) rounds the live-window+127-shift slab up
    to whole 128-lane rows."""
    live = pre + skip_samples + epoch_size
    if mode in BANK_MODES:
        return _bank_slab_rows(live) * _BANK_BLK
    if mode == "aligned8":
        return -(-(live + _ALIGN - 1) // _ALIGN) * _ALIGN
    if mode == "exact":
        return ((live + 7) // 8) * 8
    raise ValueError(f"unknown pallas ingest mode {mode!r}")


#: bank128 mode: lanes per row / residual-shift variant count.
_BANK_BLK = 128

#: the bank-kernel mode family — single source for the library, the
#: bench, and the provider (a new bank mode added here propagates)
BANK_MODES = ("bank128", "bank128_bf16")


def bank_wvm_dtype(mode: str):
    """Operand dtype the ``Wvm`` bank ships in for a bank mode."""
    return jnp.bfloat16 if mode == "bank128_bf16" else jnp.float32


def _bank_slab_rows(live_window: int) -> int:
    """128-lane rows per epoch slab: the live window plus the worst
    in-row shift (127) must fit."""
    return -(-(live_window + _BANK_BLK - 1) // _BANK_BLK)


def aligned8_banks(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
):
    """(Wv, Mv, colsum, window8) for the aligned8 kernel — the shared
    constructor the featurizer and the bench both use, so a geometry
    change cannot leave the bench timing a stale kernel shape."""
    window8 = kernel_window("aligned8", pre, skip_samples, epoch_size)
    Wv, Mv, colsum = device_ingest._shift_variant_banks(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        window8, _ALIGN,
    )
    return Wv, Mv, colsum, window8


def bank128_banks(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
):
    """(Wvm, fold, slab_rows) for the bank128 kernel — the shared
    constructor the featurizer and the bench both use (same role as
    :func:`aligned8_banks` for the aligned8 kernel).

    ``Wvm`` (slab, 128*K + 128) is ``[Wv | Mv]``: variant v's window
    operator (taps at slab rows [v, v+win)) next to its pre-stimulus
    mean taps, so one contraction yields every shift's features AND
    pre-means. ``fold`` ((128*K + 128), K) is the static select/fold
    matrix: feature rows carry identity blocks, pre-mean rows carry
    ``-colsum``, so ``dot(masked, fold) = yk - pk*colsum`` — the
    two-term baseline correction fused into the select dot."""
    live = pre + skip_samples + epoch_size
    slab_rows = _bank_slab_rows(live)
    slab = slab_rows * _BANK_BLK
    Wv, Mv, colsum = device_ingest._shift_variant_banks(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        slab, _BANK_BLK,
    )
    K = feature_size
    NVK = _BANK_BLK * K
    Wvm = np.concatenate([Wv, Mv], axis=1)
    fold = np.zeros((NVK + _BANK_BLK, K), np.float32)
    for v in range(_BANK_BLK):
        fold[v * K : (v + 1) * K, :] = np.eye(K, dtype=np.float32)
    fold[NVK:, :] = -colsum
    return Wvm, fold, slab_rows


def bucket_plan_8(plan: "PallasTilePlan") -> "PallasTilePlan":
    """Pad a tile plan's tile count up to a multiple of 8 (jit-cache
    bucketing; padded tiles point at block 0 with ``src_rows`` -1 and
    are dropped by :func:`plan_unsort_index`). Shared by the
    irregular featurizer and the regular 'bank' formulation."""
    n_tiles = plan.half_idx.shape[0]
    bucket = ((n_tiles + 7) // 8) * 8
    if bucket == n_tiles:
        return plan
    pad_t = bucket - n_tiles
    tile_b = plan.tile_b
    return PallasTilePlan(
        np.concatenate([plan.half_idx, np.zeros(pad_t, np.int32)]),
        np.concatenate(
            [plan.offsets, np.zeros((pad_t, tile_b), np.int32)]
        ),
        np.concatenate(
            [plan.src_rows, np.full((pad_t, tile_b), -1, np.int32)]
        ),
        plan.chunk,
        tile_b,
    )


def bank_plan_arrays(plan: "PallasTilePlan", n_channels: int):
    """(blocks, shifts_rows, inv) for the bank kernel from a
    (bucketed) tile plan — the one place the offset -> row-block +
    in-row-shift encoding lives (featurizer, regular 'bank'
    formulation, and the bank train step all consume this)."""
    blocks = (plan.offsets // _BANK_BLK).astype(np.int32)
    shifts_rows = np.repeat(
        (plan.offsets % _BANK_BLK).astype(np.int32).reshape(-1),
        n_channels,
    )[:, None]
    return blocks, shifts_rows, plan_unsort_index(plan)


def bank_finish(rows, resolutions, inv):
    """Shared linear tail of every bank-kernel consumer: per-channel
    resolution scale, (n, C*K) packing, L2 normalize, unsort. ``rows``
    is the kernel's (N*C, K) output; C = len(resolutions)."""
    C = resolutions.shape[0]
    res_rows = jnp.tile(
        jnp.asarray(resolutions, jnp.float32), rows.shape[0] // C
    )[:, None]
    feats = dwt_xla.safe_l2_normalize(
        (rows * res_rows).reshape(rows.shape[0] // C, -1)
    )
    return feats[jnp.asarray(inv)]


def plan_unsort_index(plan: "PallasTilePlan") -> np.ndarray:
    """Unsort index for kernel-row outputs: row ``t*tile_b + e``
    holds epoch ``src_rows[t, e]``; the returned ``inv`` maps epoch
    order -> kernel row, dropping padded rows."""
    flat_src = plan.src_rows.reshape(-1)
    real = flat_src >= 0
    inv = np.empty(int(real.sum()), dtype=np.int64)
    inv[flat_src[real]] = np.nonzero(real)[0]
    return inv


def plan_pallas_tiles(
    positions: np.ndarray,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    window: int = DEFAULT_WINDOW,
    chunk: int = 65536,
    tile_b: int = 32,
) -> PallasTilePlan:
    """Pack marker windows into (chunk, tile_b) kernel tiles.

    ``positions`` are marker sample positions (window starts at
    ``position - pre``); callers guarantee validity (the ingest
    planner's job, device_ingest.plan_ingest). Windows are sorted,
    then packed greedily: a tile's base is the half-chunk containing
    its first window; epochs join while their window still fits the
    base-aligned chunk and the tile has room.
    """
    if window > chunk // 2:
        raise ValueError(f"window {window} must be <= chunk/2 {chunk // 2}")
    half = chunk // 2
    starts = np.asarray(positions, dtype=np.int64) - pre
    if starts.size and starts.min() < 0:
        raise ValueError("window start < 0; filter invalid markers first")
    order = np.argsort(starts, kind="stable")

    tiles_half: list[int] = []
    tiles_rows: list[list[int]] = []
    tiles_offs: list[list[int]] = []
    for idx in order:
        s = int(starts[idx])
        k = s // half
        fits = (
            tiles_half
            and len(tiles_rows[-1]) < tile_b
            and s + window <= tiles_half[-1] * half + chunk
        )
        if not fits:
            tiles_half.append(k)
            tiles_rows.append([])
            tiles_offs.append([])
        tiles_rows[-1].append(int(idx))
        tiles_offs[-1].append(s - tiles_half[-1] * half)

    n_tiles = max(1, len(tiles_half))
    half_idx = np.zeros(n_tiles, dtype=np.int32)
    offsets = np.zeros((n_tiles, tile_b), dtype=np.int32)
    src_rows = np.full((n_tiles, tile_b), -1, dtype=np.int32)
    for t, (k, rows, offs) in enumerate(
        zip(tiles_half, tiles_rows, tiles_offs)
    ):
        half_idx[t] = k
        offsets[t, : len(offs)] = offs
        src_rows[t, : len(rows)] = rows
    return PallasTilePlan(half_idx, offsets, src_rows, chunk, tile_b)


def cached_plan_pallas_tiles(
    positions: np.ndarray,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    window: int = DEFAULT_WINDOW,
    chunk: int = 65536,
    tile_b: int = 32,
    bucket: bool = True,
) -> PallasTilePlan:
    """:func:`plan_pallas_tiles` (+ :func:`bucket_plan_8` when
    ``bucket``) behind the shared host-plan cache (``ops/plan_cache``),
    keyed on the marker-layout digest and the tile geometry: a
    steady-state consumer re-featurizing the same recording does zero
    host re-planning — the greedy sort/pack runs once per layout."""
    from . import plan_cache as _pc

    positions = np.asarray(positions)
    key = _pc.digest(
        positions,
        extra=("pallas_tiles", pre, window, chunk, tile_b, bucket),
    )

    def build():
        plan = plan_pallas_tiles(
            positions, pre=pre, window=window, chunk=chunk, tile_b=tile_b
        )
        return bucket_plan_8(plan) if bucket else plan

    return _pc.cache("pallas_tile_plan").get_or_build(key, build)


def _make_kernel(
    n_channels: int, tile_b: int, window: int, chunk: int, pre: int
):
    half = chunk // 2

    def kernel(half_ref, offs_ref, a_ref, b_ref, res_ref, e_ref, o_ref,
               chunk_ref, xa_ref):
        i = pl.program_id(0)
        chunk_ref[:, :half] = a_ref[:].astype(jnp.float32) * res_ref[:]
        chunk_ref[:, half:] = b_ref[:].astype(jnp.float32) * res_ref[:]
        for e in range(tile_b):
            off = offs_ref[i, e]
            seg = chunk_ref[:, pl.ds(off, window)]
            # explicit f32 baseline subtraction (Baseline.java:29-57);
            # not folded into E — DC offsets would cancel in f32
            base = jnp.mean(seg[:, :pre], axis=1, keepdims=True)
            xa_ref[e * n_channels : (e + 1) * n_channels, :] = seg - base
        y = lax.dot_general(
            xa_ref[:],
            e_ref[:],
            (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (tile_b*C, K)
        feats = y.reshape(tile_b, n_channels * y.shape[-1])
        # the shared zero-guarded normalize keeps the XLA and Pallas
        # device backends parity-locked on the epsilon
        o_ref[:] = dwt_xla.safe_l2_normalize(feats)

    return kernel


#: aligned8 mode: residual-shift variant count (one sublane's worth).
_ALIGN = 8


def _make_kernel_aligned(
    n_channels: int, tile_b: int, window8: int, chunk: int,
    feature_size: int,
):
    """The ``aligned8`` kernel: every dynamic lane slice lands on an
    8-aligned (sublane) offset.

    The exact kernel's ``pl.ds(off, window)`` at an *arbitrary* sample
    offset is the one construct the Mosaic-compiled twin
    (``ops/dwt_pallas.py``, chip-proven round 2) does not use, making
    it the prime remote-compile-crash suspect. Here the host rounds
    each window start down to a multiple of 8 and the kernel cuts a
    ``window8``-wide segment at that aligned offset (``pl.multiple_of``
    hint); the residual shift (0..7) never moves data — an 8-variant
    operator bank (``device_ingest._shift_variant_banks``: variant v =
    the window operator shifted down v rows) computes all 8 shifts'
    features in one MXU contraction and a per-epoch one-hot sum
    selects the right one on the VPU. Baseline correction follows the
    block formulation's f32-safe shape: per-epoch segment mean as the
    exactly-invariant DC proxy pre-contraction, then the two-term
    pre-mean correction post-selection, all terms at residual scale.
    """
    half = chunk // 2
    K = feature_size

    def kernel(half_ref, offs_ref, shifts_ref, a_ref, b_ref, res_ref,
               wv_ref, mv_ref, cs_ref, o_ref, chunk_ref, xa_ref):
        i = pl.program_id(0)
        chunk_ref[:, :half] = a_ref[:].astype(jnp.float32) * res_ref[:]
        chunk_ref[:, half:] = b_ref[:].astype(jnp.float32) * res_ref[:]
        for e in range(tile_b):
            off8 = pl.multiple_of(offs_ref[i, e], _ALIGN)
            seg = chunk_ref[:, pl.ds(off8, window8)]
            # per-epoch segment mean: a constant the baseline algebra
            # cancels exactly; keeps the two cancelling terms below at
            # residual scale (f32-safe, same analysis as block ingest)
            d = jnp.mean(seg, axis=1, keepdims=True)
            xa_ref[e * n_channels : (e + 1) * n_channels, :] = seg - d
        yv = lax.dot_general(
            xa_ref[:], wv_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (tile_b*C, 8*K) — all 8 shifts' features
        pv = lax.dot_general(
            xa_ref[:], mv_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (tile_b*C, 8) — all 8 shifts' pre-means
        sh = shifts_ref[i]  # (tile_b,)
        onehot = (
            sh[:, None]
            == lax.broadcasted_iota(jnp.int32, (tile_b, _ALIGN), 1)
        ).astype(jnp.float32)
        yb = yv.reshape(tile_b, n_channels, _ALIGN, K)
        pb = pv.reshape(tile_b, n_channels, _ALIGN)
        yk = jnp.sum(yb * onehot[:, None, :, None], axis=2)
        pk = jnp.sum(pb * onehot[:, None, :], axis=2)
        feats = yk - pk[..., None] * cs_ref[:]
        o_ref[:] = dwt_xla.safe_l2_normalize(
            feats.reshape(tile_b, n_channels * K)
        )

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "tile_b", "chunk", "window", "feature_size", "interpret", "pre",
    ),
)
def _ingest_tiles(
    raw_i16,
    resolutions,
    half_idx,
    offsets,
    E,
    *,
    tile_b: int,
    chunk: int,
    window: int,
    feature_size: int,
    interpret: bool,
    pre: int = constants.PRESTIMULUS_SAMPLES,
):
    C = raw_i16.shape[0]
    n_tiles = half_idx.shape[0]
    half = chunk // 2
    K = C * feature_size
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # half_idx, offsets
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((C, half), lambda i, hi, off: (0, hi[i])),
            pl.BlockSpec((C, half), lambda i, hi, off: (0, hi[i] + 1)),
            pl.BlockSpec((C, 1), lambda i, hi, off: (0, 0)),
            pl.BlockSpec((window, feature_size), lambda i, hi, off: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, K), lambda i, hi, off: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, chunk), jnp.float32),
            pltpu.VMEM((tile_b * C, window), jnp.float32),
        ],
    )
    return pl.pallas_call(
        _make_kernel(C, tile_b, window, chunk, pre),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles * tile_b, K), jnp.float32),
        interpret=interpret,
    )(half_idx, offsets, raw_i16, raw_i16, resolutions[:, None], E)


def _make_kernel_bank(
    n_channels: int, tile_b: int, chunk: int, feature_size: int,
    slab_rows: int, bank_bf16: bool = False,
):
    """The ``bank128`` kernel: the only formulation whose every
    construct is proven to compile through the axon remote-compile
    helper (tools/pallas_sublane_probe.py, run on chip r4).

    The exact kernel's dynamic lane slice and the select's lane-split
    reshape both crash the helper (r4 bisect k4/k4b, probe s5), so
    windows are cut as dynamic SUBLANE slices over a rows-of-128
    layout — ``slab_rows`` whole 128-lane rows starting at the row
    containing the window start — and the residual in-row shift
    (0..127) never moves data: a 128-variant operator bank
    (``device_ingest._shift_variant_banks``, the block_ingest trick
    moved into VMEM) computes every shift's features and pre-means in
    ONE MXU contraction against ``[Wv | Mv]``, and a reshape-free
    mask/fold select — lane-iota//K compare + a static 0/1 fold
    matrix whose pre-mean rows carry ``-colsum`` — projects out each
    epoch's shift AND applies the two-term baseline correction in one
    more dot. Output rows are (epoch, channel) pairs; the per-channel
    resolution scale, the (tile_b, C*K) packing, and the L2 normalize
    happen outside in XLA (linear, so commuting them out is exact —
    all three are cheap on (n, C*K) features).
    """
    rows = chunk // _BANK_BLK
    hrows = rows // 2
    K = feature_size
    NVK = _BANK_BLK * K

    def kernel(half_ref, blks_ref, a_ref, b_ref, sh_ref, wvm_ref,
               fold_ref, o_ref, ch_ref, xa_ref):
        del half_ref
        i = pl.program_id(0)
        ch_ref[:, :hrows, :] = a_ref[:].astype(jnp.float32)
        ch_ref[:, hrows:, :] = b_ref[:].astype(jnp.float32)
        for e in range(tile_b):
            blk = blks_ref[i, e]
            for c in range(n_channels):
                xa_ref[e * n_channels + c, :, :] = ch_ref[
                    c, pl.ds(blk, slab_rows), :
                ]
        flat = xa_ref[:].reshape(
            tile_b * n_channels, slab_rows * _BANK_BLK
        )
        # per-slab mean: a per-epoch constant the two-term baseline
        # algebra cancels exactly; keeps both cancelling terms at
        # residual scale (f32-safe, same analysis as block ingest)
        d = jnp.mean(flat, axis=1, keepdims=True)
        xc = flat - d
        if bank_bf16:
            # the bank arrives pre-cast to bf16 (half the VMEM, no
            # per-step cast); mean-centering happens in f32 FIRST, so
            # the bf16 cast rounds residual-scale values, not
            # int16-range DC — the same ordering argument as the bf16
            # feature tier. f32 accumulation via
            # preferred_element_type.
            yv = lax.dot_general(
                xc.astype(jnp.bfloat16),
                wvm_ref[:],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            yv = lax.dot_general(
                xc, wvm_ref[:], (((1,), (0,)), ((), ())),
                precision=lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )  # (tile_b*C, NVK + NV): all shifts' features | pre-means
        lane = lax.broadcasted_iota(
            jnp.int32, (tile_b * n_channels, NVK + _BANK_BLK), 1
        )
        v_of_lane = jnp.where(lane < NVK, lane // K, lane - NVK)
        mask = (sh_ref[:] == v_of_lane).astype(jnp.float32)
        o_ref[:] = lax.dot_general(
            yv * mask, fold_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (tile_b*C, K) = yk - pk*colsum via the fold matrix

    return kernel


#: bank128: max tiles per pallas_call — the scalar-prefetched
#: ``blocks`` array lives in SMEM (1 MiB on v5e; the r4 chip compile
#: diagnostic showed a 2 MiB prefetch rejected), so one call handles
#: at most 2048 tiles (2048*33*4B = 270 KiB of scalars) and callers
#: split larger runs into equal groups.
_BANK_MAX_TILES = 2048


def bank_ingest_rows(
    raw_rows_i16,
    half_idx,
    blocks,
    shifts_rows,
    Wvm,
    fold,
    *,
    tile_b: int,
    chunk: int,
    feature_size: int,
    slab_rows: int,
    interpret: bool,
    bank_bf16: bool = False,
):
    """Chunked driver for :func:`_ingest_tiles_bank`: splits the tile
    axis into SMEM-sized groups (static Python loop — jit/scan safe)
    and concatenates the row outputs. The last group may be smaller
    (one extra compiled shape, vs up to 2047 dead padded tiles)."""
    n_tiles = half_idx.shape[0]
    C = raw_rows_i16.shape[0]
    if chunk % (2 * _BANK_BLK):
        # half-chunks must be whole 128-lane rows or the two
        # BlockSpec fetches land off the planner's sample offsets —
        # silently wrong features, so fail loudly
        raise ValueError(
            f"bank128 needs chunk % {2 * _BANK_BLK} == 0; got {chunk}"
        )
    # ragged last group: the SMEM cap only bounds tiles PER CALL, so
    # a remainder group just compiles one extra (smaller) shape
    # instead of paying up to _BANK_MAX_TILES-1 dead padded tiles
    groups = [
        (g, min(g + _BANK_MAX_TILES, n_tiles))
        for g in range(0, max(n_tiles, 1), _BANK_MAX_TILES)
    ]
    outs = [
        _ingest_tiles_bank(
            raw_rows_i16,
            half_idx[g0:g1],
            blocks[g0:g1],
            shifts_rows[g0 * tile_b * C : g1 * tile_b * C],
            Wvm,
            fold,
            tile_b=tile_b,
            chunk=chunk,
            feature_size=feature_size,
            slab_rows=slab_rows,
            interpret=interpret,
            bank_bf16=bank_bf16,
        )
        for g0, g1 in groups
    ]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tile_b", "chunk", "feature_size", "slab_rows", "interpret",
        "bank_bf16",
    ),
)
def _ingest_tiles_bank(
    raw_rows_i16,
    half_idx,
    blocks,
    shifts_rows,
    Wvm,
    fold,
    *,
    tile_b: int,
    chunk: int,
    feature_size: int,
    slab_rows: int,
    interpret: bool,
    bank_bf16: bool = False,
):
    C = raw_rows_i16.shape[0]
    n_tiles = half_idx.shape[0]
    rows = chunk // _BANK_BLK
    hrows = rows // 2
    K = feature_size
    NVK = _BANK_BLK * K
    slab = slab_rows * _BANK_BLK
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # half_idx, blocks
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (C, hrows, _BANK_BLK), lambda i, hi, blk: (0, hi[i], 0)
            ),
            pl.BlockSpec(
                (C, hrows, _BANK_BLK),
                lambda i, hi, blk: (0, hi[i] + 1, 0),
            ),
            pl.BlockSpec(
                (tile_b * C, 1), lambda i, hi, blk: (i, 0)
            ),
            pl.BlockSpec(
                (slab, NVK + _BANK_BLK), lambda i, hi, blk: (0, 0)
            ),
            pl.BlockSpec(
                (NVK + _BANK_BLK, K), lambda i, hi, blk: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (tile_b * C, K), lambda i, hi, blk: (i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((C, rows, _BANK_BLK), jnp.float32),
            pltpu.VMEM((tile_b * C, slab_rows, _BANK_BLK), jnp.float32),
        ],
    )
    return pl.pallas_call(
        _make_kernel_bank(
            C, tile_b, chunk, feature_size, slab_rows, bank_bf16
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n_tiles * tile_b * C, K), jnp.float32
        ),
        interpret=interpret,
    )(
        half_idx, blocks, raw_rows_i16, raw_rows_i16, shifts_rows,
        Wvm, fold,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "tile_b", "chunk", "window8", "feature_size", "interpret",
    ),
)
def _ingest_tiles_aligned(
    raw_i16,
    resolutions,
    half_idx,
    offsets8,
    shifts,
    Wv,
    Mv,
    colsum,
    *,
    tile_b: int,
    chunk: int,
    window8: int,
    feature_size: int,
    interpret: bool,
):
    C = raw_i16.shape[0]
    n_tiles = half_idx.shape[0]
    half = chunk // 2
    K = C * feature_size
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # half_idx, offsets8, shifts
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((C, half), lambda i, hi, off, sh: (0, hi[i])),
            pl.BlockSpec((C, half), lambda i, hi, off, sh: (0, hi[i] + 1)),
            pl.BlockSpec((C, 1), lambda i, hi, off, sh: (0, 0)),
            pl.BlockSpec(
                (window8, _ALIGN * feature_size),
                lambda i, hi, off, sh: (0, 0),
            ),
            pl.BlockSpec((window8, _ALIGN), lambda i, hi, off, sh: (0, 0)),
            pl.BlockSpec((1, feature_size), lambda i, hi, off, sh: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, K), lambda i, hi, off, sh: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, chunk), jnp.float32),
            pltpu.VMEM((tile_b * C, window8), jnp.float32),
        ],
    )
    return pl.pallas_call(
        _make_kernel_aligned(C, tile_b, window8, chunk, feature_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles * tile_b, K), jnp.float32),
        interpret=interpret,
    )(
        half_idx, offsets8, shifts, raw_i16, raw_i16,
        resolutions[:, None], Wv, Mv, colsum,
    )


def ingest_features_pallas(
    raw_i16: np.ndarray,
    resolutions: np.ndarray,
    positions: np.ndarray,
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    chunk: int = 65536,
    tile_b: int = 32,
    interpret: bool | None = None,
    mode: str | None = None,
) -> jnp.ndarray:
    """(C, S) int16 raw + (n,) marker positions -> (n, C*K) features.

    The Pallas counterpart of
    ``device_ingest.make_device_ingest_featurizer``; positions must be
    pre-validated (plan_ingest). Output rows are in input marker
    order.

    ``mode``:

    - ``"exact"``: the original kernel — windows cut by a dynamic
      lane slice at the exact sample offset, explicit pre-stimulus
      baseline subtraction before one contraction.
    - ``"aligned8"``: every dynamic lane slice 8-aligned (sublane
      boundary, ``pl.multiple_of``); the residual 0..7 shift is
      absorbed by an 8-variant operator bank + one-hot select (see
      :func:`_make_kernel_aligned`). Built round 3 as a fix
      hypothesis for the axon remote-compile crash; the round-4 chip
      bisect FALSIFIED it — the helper crashes on aligned dynamic
      lane slices too (tools/sweep_results/r4/pallas_bisect.json
      k4b). Kept for its interpret-mode parity value.
    - ``"bank128"``: the chip-proven formulation (round-4 probe
      tools/pallas_sublane_probe.py: every construct compiles through
      the remote helper). Windows are cut as dynamic SUBLANE slices
      over a rows-of-128 layout and the in-row shift (0..127) is
      absorbed by a 128-variant bank + reshape-free mask/fold select
      (see :func:`_make_kernel_bank`); numerics follow the block
      formulation's f32-safe two-term shape.
    """
    from . import pallas_support

    if interpret is None:
        interpret = pallas_support.default_interpret()
    if mode is None:
        # default follows the RESOLVED interpret flag (not the
        # platform) so an explicit interpret= override gets the
        # matching formulation: compiled Mosaic -> bank128 (the one
        # formulation that compiles through the axon remote helper),
        # interpreter -> exact (the parity anchor)
        mode = "exact" if interpret else "bank128"
    window = kernel_window(mode, pre, skip_samples, epoch_size)
    # Cached host planning (zero re-planning for a repeated layout),
    # and bucket both jit-cache keys so multi-recording runs reuse the
    # compiled kernel instead of recompiling per marker layout:
    # (a) tile count rounds up to a multiple of 8 (padded tiles point
    # at block 0 with src_rows -1 and are dropped on unsort);
    # (b) the raw sample axis rounds up to a multiple of 8 chunks.
    plan = cached_plan_pallas_tiles(
        positions, pre=pre, window=window, chunk=chunk, tile_b=tile_b
    )
    half = chunk // 2
    # every referenced half-chunk (hi and hi+1) must exist
    needed = (int(plan.half_idx.max(initial=0)) + 2) * half
    C, S = raw_i16.shape
    sample_bucket = 8 * chunk
    padded = ((max(S, needed) + sample_bucket - 1)
              // sample_bucket) * sample_bucket
    if padded != S:
        raw_i16 = np.pad(raw_i16, ((0, 0), (0, padded - S)))
    if mode in BANK_MODES:
        Wvm, fold, slab_rows = bank128_banks(
            wavelet_index, epoch_size, skip_samples, feature_size, pre
        )
        C = raw_i16.shape[0]
        blocks, shifts_rows, inv = bank_plan_arrays(plan, C)
        rows_out = bank_ingest_rows(
            jnp.asarray(
                raw_i16.reshape(C, -1, _BANK_BLK)
            ),
            jnp.asarray(plan.half_idx),
            jnp.asarray(blocks),
            jnp.asarray(shifts_rows),
            jnp.asarray(Wvm, bank_wvm_dtype(mode)),
            jnp.asarray(fold),
            tile_b=tile_b,
            chunk=chunk,
            feature_size=feature_size,
            slab_rows=slab_rows,
            interpret=bool(interpret),
            bank_bf16=mode == "bank128_bf16",
        )  # (n_tiles*tile_b*C, K), unscaled
        # scale/pack/normalize/unsort: the shared bank tail
        return bank_finish(
            rows_out, np.asarray(resolutions, np.float32), inv
        )
    if mode == "aligned8":
        Wv_np, Mv_np, colsum_np, _ = aligned8_banks(
            wavelet_index, epoch_size, skip_samples, feature_size, pre
        )
        # tile bases are half-chunk aligned (half % 8 == 0), so the
        # tile-relative offset and the absolute start agree mod 8
        offsets8 = plan.offsets & ~(_ALIGN - 1)
        shifts = plan.offsets & (_ALIGN - 1)
        tiled = _ingest_tiles_aligned(
            jnp.asarray(raw_i16),
            jnp.asarray(resolutions, jnp.float32),
            jnp.asarray(plan.half_idx),
            jnp.asarray(offsets8),
            jnp.asarray(shifts),
            jnp.asarray(Wv_np),
            jnp.asarray(Mv_np),
            jnp.asarray(colsum_np)[None, :],
            tile_b=tile_b,
            chunk=chunk,
            window8=window,
            feature_size=feature_size,
            interpret=bool(interpret),
        )
    else:
        E = jnp.asarray(
            device_ingest.ingest_matrix(
                wavelet_index, epoch_size, skip_samples, feature_size, pre,
                window_len=window, fold_baseline=False,
            )
        )
        tiled = _ingest_tiles(
            jnp.asarray(raw_i16),
            jnp.asarray(resolutions, jnp.float32),
            jnp.asarray(plan.half_idx),
            jnp.asarray(plan.offsets),
            E,
            tile_b=tile_b,
            chunk=chunk,
            window=window,
            feature_size=feature_size,
            interpret=bool(interpret),
            pre=pre,
        )
    # unsort: tiled row t*tile_b+e holds epoch src_rows[t, e]
    return tiled[jnp.asarray(plan_unsort_index(plan))]


def make_pallas_ingest_featurizer(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    chunk: int = 65536,
    tile_b: int = 32,
    interpret: bool | None = None,
    mode: str | None = None,
):
    """Callable (raw int16, resolutions, positions) -> features, the
    plug-in counterpart of ``make_device_ingest_featurizer`` for the
    Pallas path (host planning happens per call; the kernel is jitted
    and cached by shape). ``mode`` selects the kernel formulation —
    see :func:`ingest_features_pallas`."""
    if mode is not None:
        kernel_window(mode)  # validate at build time, not first featurize

    def featurize(raw_i16, resolutions, positions):
        return ingest_features_pallas(
            np.asarray(raw_i16),
            np.asarray(resolutions, np.float32),
            np.asarray(positions),
            wavelet_index=wavelet_index,
            epoch_size=epoch_size,
            skip_samples=skip_samples,
            feature_size=feature_size,
            pre=pre,
            chunk=chunk,
            tile_b=tile_b,
            interpret=interpret,
            mode=mode,
        )

    return featurize
