"""The 4-bit tier (ISSUE 18): the int4 feature rung below int8, and
int8/int4 quantization of the multiplexed tenant weight stack.

Two halves, one gate discipline:

**int4 features** — ``precision=int4`` quantizes FINISHED f32 feature
rows with the same per-(row, channel, subband)-group symmetric scales
the int8 rung uses (``decode_ingest.quantize_dequantize_int8``), one
rung looser: 4-bit symmetric levels (qmax = 7), two nibbles packed per
byte in the shipped representation. The in-graph round trip IS the
rung (downstream keeps its f32 contract while every value has passed
through 4 bits); :func:`pack_int4_rows` / :func:`unpack_int4_rows`
pin that the packed wire format reconstructs the round trip exactly.
Gated per run by :data:`INT4_GATE_TOL` (override
``EEG_TPU_INT4_GATE_TOL``) with per-run auto-disable
(``pipeline.int4_gate_disabled``) — the bf16/int8 policy verbatim.

**quantized weight stack** — ``weights_precision=int8|int4`` on the
multiplexed engine keeps the (d, 128) f32 host mirror as master (so
tenant add/swap/remove stays zero-recompile device_put) but makes the
RESIDENT matrix the packed int8/int4 payload plus per-lane scales,
dequantized inside the program (:func:`dequantize_weight_stack` — VPU
elementwise, feeding the existing single MXU dot). Per-lane scales
deliberately: a lane is one tenant's model, and a cross-tenant max
would couple one tenant's quantization grid to its neighbors' weight
magnitudes (a swap_model on lane 3 would move lane 7's margins).
Promotion rides the established warmup margin-parity gate
(:func:`weights_gate_tolerance`), 2 consecutive failures degrade back
to the f32 stack, and the resident-bytes win (4x/8x) is accounted on
serve stats and bench lines — never assumed.
"""

from __future__ import annotations

import functools
import math

import numpy as np

from . import decode_ingest

#: int4 feature gate: max abs deviation of the int4-quantized feature
#: rows vs the f32 reference on the SAME rows before the rung
#: auto-disables. The arithmetic envelope follows the quantizer:
#: symmetric per-(channel, subband) scales put the worst rounding
#: error at scale/2 = group_max/14, and L2-normalized rows keep
#: group_max <= 1, so the envelope is ~7.2e-2 — eighteen times the
#: int8 rung's (~4e-3), which is what dropping 4 bits costs. 1.5e-1
#: is ~2x that envelope (the bf16 gate's headroom factor, tighter
#: than int8's 5x: at 4 bits the gate is the load-bearing safety and
#: should trip on anything beyond plain rounding). Override for
#: experiments via EEG_TPU_INT4_GATE_TOL.
INT4_GATE_TOL = 1.5e-1

#: symmetric 4-bit quantization levels: q in [-7, 7], stored +8 as a
#: nibble in [1, 15] (0 never occurs — a cheap corruption tripwire).
INT4_QMAX = 7.0

#: the weight-stack precision grammar (single source for the
#: multiplexed engine, the bench, and tests). f32 is the PR 16
#: baseline: the host mirror device_put verbatim.
WEIGHTS_PRECISIONS = ("f32", "int8", "int4")

#: headroom factor on the weight-stack gate's arithmetic envelope
#: (|delta margin| <= ||f||_2 * ||delta w||_2 <= sqrt(d) * s_max / 2
#: for L2-normalized feature rows): the same order the feature gates
#: carry over their own envelopes.
WEIGHTS_GATE_HEADROOM = 4.0

#: pre-registered accelerator flip (docs/chip_playbook.md): the
#: quantized stack's conc-16 predictions/sec must hold >= this ratio
#: of the f32 multiplexed engine's on chip before weights_precision
#: defaults quantized on that platform. Below 1.0 deliberately: the
#: quantized stack's win is resident VMEM bytes (4x/8x — N tenants'
#: weights next to the megakernel instead of paged from HBM), so a
#: small throughput toll is a fair trade, but >5% is not.
WEIGHTS_QUANT_FLIP_RATIO = 0.95

#: sweep-artifact filename stems carrying a serve_multitenant_quant
#: chip run (staged by tools/collect_chip_runs.sh).
_QUANT_ARTIFACTS = ("serve_multitenant_quant*.json",)


def int4_gate_tolerance() -> float:
    """The documented int4 feature gate (:data:`INT4_GATE_TOL`), with
    the experiment override ``EEG_TPU_INT4_GATE_TOL`` — same
    logged-never-silent fallback policy as the bf16/int8 gates."""
    import logging
    import os

    raw = os.environ.get("EEG_TPU_INT4_GATE_TOL")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logging.getLogger(__name__).warning(
                "EEG_TPU_INT4_GATE_TOL=%r is not a float; using the "
                "default gate %g", raw, INT4_GATE_TOL,
            )
    return INT4_GATE_TOL


def quantize_dequantize_int4(rows, feature_size: int):
    """The int4 feature rung's core (traceable): symmetric
    per-(row, channel, subband) scales, round-to-nearest into 4-bit
    levels, immediate dequantization back to f32 — the int8 core
    (``decode_ingest.quantize_dequantize_int8``) with qmax = 7.

    Returns ``(dequantized rows (n, C*K) f32, scales
    (n_groups, n, C) f32)``. Scales are per ROW (batch-invariance:
    bit-identical whatever micro-batch a window rides in),
    deterministic rounding (cache contract), zero rows stay exactly
    zero. See the int8 docstring for why each invariant is
    load-bearing; all three transfer verbatim.
    """
    import jax.numpy as jnp

    n = rows.shape[0]
    K = int(feature_size)
    C = rows.shape[1] // K
    x = rows.reshape(n, C, K)
    outs = []
    scales = []
    for lo, hi in decode_ingest.subband_group_bounds(K):
        g = x[:, :, lo:hi]
        s = jnp.max(jnp.abs(g), axis=2) / INT4_QMAX  # (n, C)
        s = jnp.maximum(s, 1e-30)  # all-zero group: 0/s stays 0
        q = jnp.clip(
            jnp.round(g / s[..., None]), -INT4_QMAX, INT4_QMAX
        )
        outs.append(q.astype(jnp.int8).astype(jnp.float32)
                    * s[..., None])
        scales.append(s)
    return (
        jnp.concatenate(outs, axis=2).reshape(n, C * K),
        jnp.stack(scales),
    )


@functools.lru_cache(maxsize=None)
def _int4_path_program(feature_size: int):
    import jax

    @jax.jit
    def run(rows):
        dq, _ = quantize_dequantize_int4(rows, feature_size)
        return dq

    return run


def int4_feature_path(rows, feature_size: int):
    """Jitted quantize→dequantize pass over finished feature rows —
    the int4 rung the decode featurizer (and the serving engine's
    int4 program) applies after the f32 math."""
    return _int4_path_program(int(feature_size))(rows)


def pack_int4_rows(q) -> np.ndarray:
    """Pack integer 4-bit levels ``q (n, d) in [-7, 7]`` two nibbles
    per byte along the column axis (d even): byte j of a row carries
    column 2j in its low nibble and 2j+1 in its high nibble, each
    stored +8 (so the wire value is in [1, 15] and a zero byte is
    provably corruption, never data)."""
    q = np.asarray(q)
    if q.ndim != 2 or q.shape[1] % 2:
        raise ValueError(
            f"int4 packing needs an (n, even) matrix, got {q.shape}"
        )
    shifted = q.astype(np.int32) + 8
    if shifted.size and (shifted.min() < 1 or shifted.max() > 15):
        raise ValueError(
            f"int4 levels out of [-7, 7]: [{q.min()}, {q.max()}]"
        )
    return (shifted[:, 0::2] | (shifted[:, 1::2] << 4)).astype(
        np.uint8
    )


def unpack_int4_rows(packed) -> np.ndarray:
    """Inverse of :func:`pack_int4_rows`: ``(n, d//2) uint8`` back to
    ``(n, d) int32`` levels in [-7, 7]."""
    p = np.asarray(packed, np.uint8).astype(np.int32)
    lo = (p & 0xF) - 8
    hi = (p >> 4) - 8
    return np.stack([lo, hi], axis=2).reshape(p.shape[0], -1)


def quantize_int4_packed(rows, feature_size: int):
    """The shipped int4 representation of finished feature rows:
    ``(packed (n, C*K//2) uint8, scales (n_groups, n, C) f32)`` —
    host-side, numpy. :func:`dequantize_int4_packed` reconstructs the
    in-graph round trip (:func:`quantize_dequantize_int4`) exactly;
    tests pin the equivalence, so the traceable round trip and the
    wire format can never drift apart."""
    rows = np.asarray(rows, np.float32)
    n = rows.shape[0]
    K = int(feature_size)
    C = rows.shape[1] // K
    x = rows.reshape(n, C, K)
    qs = []
    scales = []
    for lo, hi in decode_ingest.subband_group_bounds(K):
        g = x[:, :, lo:hi]
        s = np.max(np.abs(g), axis=2) / INT4_QMAX
        s = np.maximum(s, 1e-30)
        qs.append(
            np.clip(np.round(g / s[..., None]), -INT4_QMAX, INT4_QMAX)
        )
        scales.append(s)
    q = np.concatenate(qs, axis=2).reshape(n, C * K).astype(np.int8)
    return pack_int4_rows(q), np.stack(scales).astype(np.float32)


def dequantize_int4_packed(
    packed, scales, feature_size: int
) -> np.ndarray:
    """Reconstruct f32 rows from the packed int4 representation —
    bitwise the in-graph round trip's output."""
    q = unpack_int4_rows(packed).astype(np.float32)
    n = q.shape[0]
    K = int(feature_size)
    C = q.shape[1] // K
    x = q.reshape(n, C, K)
    outs = []
    for i, (lo, hi) in enumerate(
        decode_ingest.subband_group_bounds(K)
    ):
        outs.append(
            x[:, :, lo:hi]
            * np.asarray(scales[i], np.float32)[..., None]
        )
    return np.concatenate(outs, axis=2).reshape(n, C * K)


def subband_lane_masks(
    n_channels: int, feature_size: int
) -> tuple:
    """The (channel, subband) groups of the channel-major ``(C*K,)``
    feature layout as disjoint 0/1 float32 lane masks — the
    full-lane-ops spelling of ``subband_group_bounds`` for code that
    cannot reshape or lane-slice (Mosaic kernels: lane-split reshapes
    and dynamic lane slices are the documented remote-compile crasher
    class)."""
    bounds = decode_ingest.subband_group_bounds(int(feature_size))
    d = int(n_channels) * int(feature_size)
    masks = []
    for c in range(int(n_channels)):
        base = c * int(feature_size)
        for lo, hi in bounds:
            m = np.zeros((d,), np.float32)
            m[base + lo:base + hi] = 1.0
            masks.append(m)
    return tuple(masks)


def masked_quantize_dequantize(feats, masks, qmax: float):
    """Grouped symmetric quantize→dequantize via disjoint lane masks —
    numerically identical to the reshape-based cores
    (``quantize_dequantize_int8`` / :func:`quantize_dequantize_int4`)
    but built from full-lane VPU ops only (abs, row-max, divide,
    round, clip, multiply, add): safe inside the mega Pallas kernel.

    Identity argument, group by group: ``max(|feats| * m, axis=1)``
    is the group's abs-max (masked-off lanes contribute 0, and an
    abs-max is >= 0), the scalar scale math is the same f32 ops in
    the same order, and each lane receives exactly one group's
    ``m * (q * s)`` plus zeros.
    """
    import jax.numpy as jnp

    out = jnp.zeros_like(feats)
    a = jnp.abs(feats)
    for m in masks:
        mv = jnp.asarray(m, feats.dtype)
        s = jnp.max(a * mv, axis=1, keepdims=True) / qmax
        s = jnp.maximum(s, 1e-30)
        q = jnp.clip(jnp.round(feats / s), -qmax, qmax)
        out = out + mv * (q * s)
    return out


def _weights_qmax(precision: str) -> float:
    if precision == "int8":
        return 127.0
    if precision == "int4":
        return INT4_QMAX
    raise ValueError(
        f"weights_precision {precision!r} has no quantized form; use "
        f"one of {WEIGHTS_PRECISIONS[1:]}"
    )


def quantize_weight_stack(w_host, precision: str):
    """Quantize the multiplexed engine's (d, 128) f32 host mirror into
    the resident payload: ``(packed, scales (128,) f32)`` — packed is
    ``(d, 128) int8`` for int8 or ``(d//2, 128) uint8`` for int4 (row
    2i in the low nibble, 2i+1 in the high, each stored +8).

    Scales are per LANE (symmetric, ``max|w[:, lane]| / qmax``): one
    lane is one tenant's model, and a cross-lane max would couple a
    tenant's quantization grid to its neighbors' magnitudes — a
    ``swap_model`` on one lane would move every other tenant's
    margins, breaking the snapshot-isolation contract. Host-side
    numpy: this runs inside ``_publish`` on the admin path, never in
    the program."""
    w = np.asarray(w_host, np.float32)
    qmax = _weights_qmax(precision)
    s = np.max(np.abs(w), axis=0) / qmax  # (lanes,)
    s = np.maximum(s, 1e-30).astype(np.float32)
    q = np.clip(np.rint(w / s[None, :]), -qmax, qmax)
    if precision == "int8":
        return q.astype(np.int8), s
    if w.shape[0] % 2:
        raise ValueError(
            f"int4 weight packing needs an even row count, got "
            f"{w.shape[0]}"
        )
    shifted = q.astype(np.int32) + 8
    packed = (shifted[0::2, :] | (shifted[1::2, :] << 4)).astype(
        np.uint8
    )
    return packed, s


def dequantize_weight_stack(packed, scales, precision: str, n_rows: int):
    """Traceable inverse of :func:`quantize_weight_stack` — the VPU
    dequant that runs INSIDE the serving program (elementwise ops on
    the resident payload, feeding the existing single MXU dot). For
    int4 the nibble split is uint8 bitwise + an interleaving stack,
    kept OUTSIDE any Pallas kernel body: sub-byte unpacking in Mosaic
    would need int8 blocks below the (32, 128) minimum tile or
    lane-split reshapes — the documented remote-compile crasher class
    — so the packed->f32 expansion is plain XLA and the kernel keeps
    its f32 contract."""
    import jax.numpy as jnp

    scales = jnp.asarray(scales, jnp.float32)
    if precision == "int8":
        return packed.astype(jnp.float32) * scales[None, :]
    if precision == "int4":
        lo = (packed & np.uint8(0xF)).astype(jnp.float32) - 8.0
        hi = (packed >> np.uint8(4)).astype(jnp.float32) - 8.0
        vals = jnp.stack([lo, hi], axis=1).reshape(
            int(n_rows), packed.shape[1]
        )
        return vals * scales[None, :]
    raise ValueError(
        f"weights_precision {precision!r} has no quantized form; use "
        f"one of {WEIGHTS_PRECISIONS[1:]}"
    )


def weights_gate_tolerance(precision: str, w_host) -> float:
    """The quantized weight stack's warmup margin-parity gate: the
    arithmetic envelope of the margin perturbation, with headroom.
    ``|delta margin| = |f . delta_w| <= ||f||_2 * ||delta_w||_2``,
    feature rows are L2-normalized (``||f||_2 <= 1``), and symmetric
    rounding bounds each weight's error by ``s_max / 2``, so
    ``||delta_w||_2 <= sqrt(d) * s_max / 2`` with ``s_max`` the
    largest per-lane scale in the CURRENT stack — the gate tightens
    automatically for small-magnitude models instead of waving a
    fixed constant at everything. ``EEG_TPU_WEIGHTS_GATE_TOL``
    overrides with an ABSOLUTE tolerance (0 forces the gate shut:
    the auto-disable drill)."""
    import logging
    import os

    raw = os.environ.get("EEG_TPU_WEIGHTS_GATE_TOL")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logging.getLogger(__name__).warning(
                "EEG_TPU_WEIGHTS_GATE_TOL=%r is not a float; using "
                "the derived envelope gate", raw,
            )
    w = np.asarray(w_host, np.float32)
    qmax = _weights_qmax(precision)
    s_max = (float(np.max(np.abs(w))) if w.size else 0.0) / qmax
    s_max = max(s_max, 1e-30)
    return WEIGHTS_GATE_HEADROOM * math.sqrt(w.shape[0]) * s_max / 2.0


def resident_weight_bytes(packed, scales) -> int:
    """What the quantized stack actually keeps resident: the packed
    matrix plus its per-lane scales (the f32 twin's number is the
    mirror's nbytes; both land on stats and bench lines so the 4x/8x
    claim is accounted, never assumed)."""
    return int(
        np.asarray(packed).nbytes + np.asarray(scales).nbytes
    )


def accelerator_decision(root: str | None = None) -> dict:
    """The quantized weight stack's accelerator decision, as DATA:
    harvest the best on-chip ``serve_multitenant_quant`` sweep (staged
    by tools/collect_chip_runs.sh) and judge its 16-tenant
    quantized-vs-f32-multiplexed throughput ratio against the
    pre-registered :data:`WEIGHTS_QUANT_FLIP_RATIO`. Returns
    ``{"quantize_stack", "quant_preds_per_s", "f32_preds_per_s",
    "ratio", "weights_precision", "source", "threshold_ratio",
    "reason"}`` — artifact lands, the residency default flips, zero
    code change."""
    import glob
    import json
    import os

    from . import serve_mega

    base = root or serve_mega._sweep_results_root()
    best = None
    best_src = None
    for pattern in _QUANT_ARTIFACTS:
        for path in glob.glob(os.path.join(base, "*", pattern)):
            try:
                if os.path.getsize(path) == 0:
                    continue
                with open(path) as f:
                    rec = json.loads(f.read().strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                continue
            if rec.get("platform") not in ("tpu", "axon"):
                continue
            block = (
                (rec.get("serve") or {}).get("multitenant_quant") or {}
            )
            if block.get("tenants") != 16:
                continue
            qps = (block.get("quant") or {}).get("preds_per_s")
            fps = (block.get("f32") or {}).get("preds_per_s")
            wp = block.get("weights_precision")
            if not (
                isinstance(qps, (int, float))
                and isinstance(fps, (int, float))
                and qps > 0 and fps > 0
            ):
                continue
            if best is None or qps / fps > best[0]:
                best, best_src = (qps / fps, qps, fps, wp), path
    decision = {
        "threshold_ratio": WEIGHTS_QUANT_FLIP_RATIO,
        "source": (
            os.path.relpath(best_src, os.path.dirname(base))
            if best_src
            else None
        ),
    }
    if best is None:
        decision.update(
            quantize_stack=False,
            reason=(
                "no on-chip serve_multitenant_quant artifact staged; "
                "the f32 stack stands until one lands"
            ),
        )
        return decision
    ratio, qps, fps, wp = best
    decision.update(
        quant_preds_per_s=qps,
        f32_preds_per_s=fps,
        weights_precision=wp,
        ratio=round(ratio, 4),
    )
    if ratio >= WEIGHTS_QUANT_FLIP_RATIO:
        decision.update(
            quantize_stack=True,
            reason=(
                f"serve_multitenant_quant measured {qps:.0f} preds/s "
                f"on chip at 16 tenants >= "
                f"{WEIGHTS_QUANT_FLIP_RATIO:g}x the f32 multiplexed "
                f"engine ({fps:.0f}); the quantized stack's "
                f"resident-bytes win is free — quantize"
            ),
        )
    else:
        decision.update(
            quantize_stack=False,
            reason=(
                f"serve_multitenant_quant measured {qps:.0f} preds/s "
                f"on chip at 16 tenants < "
                f"{WEIGHTS_QUANT_FLIP_RATIO:g}x the f32 multiplexed "
                f"engine ({fps:.0f}); the throughput toll outweighs "
                f"residency — the f32 stack stands"
            ),
        )
    return decision
