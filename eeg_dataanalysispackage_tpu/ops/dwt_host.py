"""Host (numpy, float64) DWT with bit-exact reference accumulation.

This is the *parity* implementation of the eegdsp fast wavelet
transform (see ``eegdsp_compat`` for the identified algorithm): every
inner product is a sequential left-to-right float64 fold, reproduced
vectorially with ``np.cumsum`` (cumsum's prefix chain is exactly the
Java accumulation order). The batched XLA implementation for TPUs
lives in ``ops/dwt.py``; this one is the ground truth it is tested
against, and is what ``fe=dwt-8`` (the reference-parity feature mode)
uses.
"""

from __future__ import annotations

import numpy as np

from . import eegdsp_compat


def _seq_dot(block: np.ndarray, f: np.ndarray) -> np.ndarray:
    """Sequential left-fold of sum(block * f) over the last axis."""
    return np.cumsum(block * f, axis=-1)[..., -1]


def fwt_periodic(signal: np.ndarray, h: np.ndarray, g: np.ndarray) -> np.ndarray:
    """Full in-place-layout FWT over the last axis.

    signal: (..., n) float64, n a power of two >= len(h).
    Returns (..., n): [a_K | d_K | d_{K-1} | ... | d_1] where K is the
    number of levels run (decompose while current length >= len(h)).
    """
    out = np.array(signal, dtype=np.float64, copy=True)
    n = out.shape[-1]
    L = len(h)
    while n >= L:
        half = n // 2
        idx = (2 * np.arange(half)[:, None] + np.arange(L)[None, :]) % n
        block = out[..., :n][..., idx]  # (..., half, L)
        out[..., :half] = _seq_dot(block, h)
        out[..., half:n] = _seq_dot(block, g)
        n = half
    return out


def dwt_coefficients(
    signal: np.ndarray, wavelet_index: int = 8, count: int = 16
) -> np.ndarray:
    """First ``count`` entries of the eegdsp coefficient layout —
    the reference's ``getDwtCoefficients()[0:FEATURE_SIZE]``."""
    h, g = eegdsp_compat.filter_pair(wavelet_index)
    return fwt_periodic(signal, h, g)[..., :count]


def l2_normalize_seq(features: np.ndarray) -> np.ndarray:
    """L2-normalize over the last axis with the reference's exact
    arithmetic: sequential sum of squares, sqrt, elementwise divide
    (SignalProcessing.java:38-52)."""
    sumsq = np.cumsum(features * features, axis=-1)[..., -1]
    return features / np.sqrt(sumsq)[..., None]
