"""Host (numpy, float64) DWT with bit-exact reference accumulation.

This is the *parity* implementation of the eegdsp fast wavelet
transform (see ``eegdsp_compat`` for the identified algorithm): every
inner product is a sequential left-to-right float64 fold, reproduced
vectorially with ``np.cumsum`` (cumsum's prefix chain is exactly the
Java accumulation order). The batched XLA implementation for TPUs
lives in ``ops/dwt.py``; this one is the ground truth it is tested
against, and is what ``fe=dwt-8`` (the reference-parity feature mode)
uses.
"""

from __future__ import annotations

import numpy as np

from . import eegdsp_compat


def _seq_dot(block: np.ndarray, f: np.ndarray) -> np.ndarray:
    """Sequential left-fold of sum(block * f) over the last axis."""
    return np.cumsum(block * f, axis=-1)[..., -1]


def fwt_subbands(
    signal: np.ndarray,
    h: np.ndarray,
    g: np.ndarray,
    max_levels: int | None = None,
):
    """The cascade itself: ``(approximation, [d_1, d_2, ..., d_K])``
    over the last axis — the ONE implementation of the eegdsp
    boundary/accumulation convention, shared by the full-layout
    transform below and the per-subband statistics family
    (``features/subband.py``), so the two can never drift.

    ``max_levels`` bounds the depth (None = decompose while the
    current length >= len(h), eegdsp's own stop rule).
    """
    a = np.array(signal, dtype=np.float64, copy=True)
    n = a.shape[-1]
    L = len(h)
    details = []
    while n >= L and (max_levels is None or len(details) < max_levels):
        half = n // 2
        idx = (2 * np.arange(half)[:, None] + np.arange(L)[None, :]) % n
        block = a[..., idx]  # (..., half, L)
        details.append(_seq_dot(block, g))
        a = _seq_dot(block, h)
        n = half
    return a, details


def fwt_periodic(signal: np.ndarray, h: np.ndarray, g: np.ndarray) -> np.ndarray:
    """Full FWT over the last axis in the eegdsp coefficient layout.

    signal: (..., n) float64 with n >= len(h). Returns
    (..., m): [a_K | d_K | d_{K-1} | ... | d_1] where K is the number
    of levels run (decompose while current length >= len(h)). For
    power-of-two n this matches eegdsp's in-place layout exactly and
    m == n; odd intermediate lengths (e.g. n=750 -> 375) keep
    floor(n/2) coefficients per level with indices taken mod n, the
    same convention as the conv formulation in ``ops/dwt.py``, and
    m < n.
    """
    a, details = fwt_subbands(signal, h, g)
    return np.concatenate([a] + details[::-1], axis=-1)


def dwt_coefficients(
    signal: np.ndarray, wavelet_index: int = 8, count: int = 16
) -> np.ndarray:
    """First ``count`` entries of the eegdsp coefficient layout —
    the reference's ``getDwtCoefficients()[0:FEATURE_SIZE]``."""
    h, g = eegdsp_compat.filter_pair(wavelet_index)
    return fwt_periodic(signal, h, g)[..., :count]


def l2_normalize_seq(features: np.ndarray) -> np.ndarray:
    """L2-normalize over the last axis with the reference's exact
    arithmetic: sequential sum of squares, sqrt, elementwise divide
    (SignalProcessing.java:38-52)."""
    sumsq = np.cumsum(features * features, axis=-1)[..., -1]
    return features / np.sqrt(sumsq)[..., None]
