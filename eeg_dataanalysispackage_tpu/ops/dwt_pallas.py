"""Pallas TPU kernel for the DWT feature-extraction hot path.

Fuses the whole per-epoch feature computation — analysis-window slice,
6-level db cascade (as the composed ``ops.dwt.cascade_matrix``
matmul), channel concat, L2 normalization — into ONE kernel: per grid
step a ``(TILE_B, C, T)`` epoch tile is DMA'd into VMEM, each
channel's analysis window is sliced *in VMEM* (no relayout copy),
contracted against the cascade matrix on the MXU at HIGHEST precision,
and row-normalized on the VPU before the single ``(TILE_B, C*K)``
result leaves for HBM.

Measured on v5e-1 (131072-epoch batches of 3x1000 f32): ~9.8M
epochs/s at tile_b=128 vs ~23-37M epochs/s for the XLA einsum
formulation (``ops.dwt.epoch_features``), both bit-comparable (max
diff 1.8e-7). The einsum path stays the default — XLA already fuses
this pattern to the HBM roofline — and the Pallas kernel is the
explicit-fusion counterpart for shapes/stages XLA cannot fuse (e.g.
appending quantization, scatter, or streaming halo logic to the
feature stage) and the template for long-signal kernels. VMEM budget:
the epoch tile is the dominant term (TILE_B*C*T*4 bytes x2 for double
buffering; TILE_B=128 at 3x1000 is ~3 MB of the ~16 MB/core budget —
tile_b=256 measurably overflows scoped VMEM once an upstream
elementwise producer is fused into the kernel's input DMA, so 128 is
the default).

Replaces: the reference's per-epoch eegdsp ``processSignal`` Spark map
(WaveletTransform.java:108-141, LogisticRegressionClassifier.java:55-61).

On CPU the kernel runs in interpreter mode (tests); on TPU it compiles
to Mosaic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from . import dwt as dwt_xla


def _make_kernel(n_channels: int, skip: int, size: int):
    def kernel(x_ref, w_ref, o_ref):
        ys = []
        for c in range(n_channels):
            xc = x_ref[:, c, skip : skip + size]
            ys.append(
                lax.dot_general(
                    xc,
                    w_ref[:],
                    (((1,), (0,)), ((), ())),
                    precision=lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32,
                )
            )
        y = jnp.concatenate(ys, axis=-1)
        norm = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
        o_ref[:] = y / jnp.maximum(norm, 1e-30)

    return kernel


def epoch_features_pallas(
    epochs: jnp.ndarray,
    wavelet_index: int = 8,
    skip_samples: int = 175,
    epoch_size: int = 512,
    feature_size: int = 16,
    tile_b: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Traceable (B, C, T) epochs -> (B, C*K) normalized features.

    ``interpret`` defaults to True off-TPU (CI / CPU meshes) and False
    on TPU, where the kernel compiles to Mosaic.
    """
    B, C, T = epochs.shape
    if skip_samples + epoch_size > T:
        raise ValueError(
            f"analysis window [{skip_samples}, {skip_samples + epoch_size}) "
            f"exceeds epoch length {T}"
        )
    if interpret is None:
        from . import pallas_support

        interpret = pallas_support.default_interpret()
    W = jnp.asarray(
        np.asarray(
            dwt_xla.cascade_matrix(wavelet_index, epoch_size, feature_size),
            dtype=np.float32,
        )
    )
    K = C * feature_size
    x = epochs.astype(jnp.float32)

    tile = min(tile_b, max(8, B))
    pad = (-B) % tile
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
    padded_b = B + pad

    out = pl.pallas_call(
        _make_kernel(C, skip_samples, epoch_size),
        grid=(padded_b // tile,),
        in_specs=[
            pl.BlockSpec((tile, C, T), lambda i: (i, 0, 0)),
            pl.BlockSpec((epoch_size, feature_size), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, K), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded_b, K), jnp.float32),
        interpret=interpret,
    )(x, W)
    return out[:B]


def make_batched_extractor_pallas(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    tile_b: int = 128,
    interpret: bool | None = None,
):
    """Jitted ``(B, C, T) -> (B, C*feature_size)`` Pallas extractor
    (the ``method='pallas'`` counterpart of
    ``ops.dwt.make_batched_extractor``)."""

    @jax.jit
    def extract(epochs: jnp.ndarray) -> jnp.ndarray:
        return epoch_features_pallas(
            jnp.asarray(epochs, jnp.float32),
            wavelet_index=wavelet_index,
            skip_samples=skip_samples,
            epoch_size=epoch_size,
            feature_size=feature_size,
            tile_b=tile_b,
            interpret=interpret,
        )

    return extract
