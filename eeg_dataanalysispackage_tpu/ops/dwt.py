"""Batched XLA implementation of the eegdsp FWT (TPU hot path).

The identified algorithm (see ``ops/eegdsp_compat.py``) is a cascade
of periodized stride-2 FIR filter banks. Here each level is expressed
as one ``lax.conv_general_dilated`` with a 2-output-channel kernel
(scaling + wavelet filter) over a circularly-extended signal, so the
whole 6-level cascade + channel concat + L2 normalization trace into a
single jitted XLA program — no per-epoch Python, no dynamic shapes.
``vmap``/sharding happen naturally over the batch dimension; on TPU
the convolutions lower onto the MXU as small matmuls.

Replaces: the reference's per-epoch Spark map of
``WaveletTransform.extractFeatures`` over RDD partitions
(LogisticRegressionClassifier.java:55-61,90).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import eegdsp_compat


def _fwt_levels(x: jnp.ndarray, h: jnp.ndarray, g: jnp.ndarray):
    """Run the cascade on (N, n); returns (a_final, [d_1, d_2, ...])."""
    L = h.shape[0]
    kernel = jnp.stack([h, g])[:, None, :]  # (out_ch=2, in_ch=1, L)
    details = []
    a = x
    n = x.shape[1]
    while n >= L:
        # circular extension: index (2i+j) mod n, max 2(n//2-1)+L-1
        ext = jnp.concatenate([a, a[:, : L - 2]], axis=1) if L > 2 else a
        # HIGHEST: on TPU, f32 convs otherwise run bf16 multiply passes
        # (~1e-3 relative error on these features — measured); the op
        # is tiny and HBM-bound, so full precision is free.
        out = lax.conv_general_dilated(
            ext[:, None, :],
            kernel,
            window_strides=(2,),
            padding="VALID",
            dimension_numbers=("NCH", "OIH", "NCH"),
            precision=lax.Precision.HIGHEST,
        )
        a = out[:, 0, : n // 2]
        details.append(out[:, 1, : n // 2])
        n //= 2
    return a, details


def fwt_coefficient_prefix(x: jnp.ndarray, h, g, count: int) -> jnp.ndarray:
    """First ``count`` entries of the eegdsp layout [aK|dK|...|d1]."""
    a, details = _fwt_levels(x, h, g)
    layout = [a] + details[::-1]
    return jnp.concatenate(layout, axis=1)[:, :count]


@lru_cache(maxsize=None)
def cascade_matrix(
    wavelet_index: int, n: int, count: int
) -> np.ndarray:
    """(n, count) float64 matrix M with coeffs[:count] = signal @ M.

    The FWT is linear, so the first-``count``-coefficient map composes
    into one dense matrix, computed exactly by running the host
    (bit-parity) implementation on the identity. On TPU this turns the
    whole cascade into a single MXU matmul that runs at the HBM
    bandwidth roofline — versus the level-by-level conv formulation,
    whose 1-feature convolutions lower to tiny ill-tiled ops (measured
    ~160x below roofline through the axon tunnel).
    """
    from . import dwt_host

    eye = np.eye(n, dtype=np.float64)
    h, g = eegdsp_compat.filter_pair(wavelet_index)
    return np.ascontiguousarray(dwt_host.fwt_periodic(eye, h, g)[:, :count])


def safe_l2_normalize(feats: jnp.ndarray) -> jnp.ndarray:
    """Row-wise L2 normalize with a zero-vector guard.

    (The host parity path reproduces Java's 0/0 -> NaN on an all-zero
    feature vector; the device paths guard instead — zero stays zero.)
    """
    norm = jnp.sqrt(jnp.sum(feats * feats, axis=-1, keepdims=True))
    return feats / jnp.maximum(norm, 1e-30)


def windowed_features(
    flat: jnp.ndarray,
    wavelet_index: int,
    count: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """(N, n) already-windowed signals -> (N, count) coefficients via
    the composed-cascade matmul (the shared device hot path)."""
    n = flat.shape[-1]
    kernel = jnp.asarray(
        cascade_matrix(wavelet_index, n, count), dtype=flat.dtype
    )
    return jnp.dot(flat, kernel, precision=precision)


def epoch_features(
    epochs: jnp.ndarray,
    wavelet_index: int = 8,
    skip_samples: int = 175,
    epoch_size: int = 512,
    feature_size: int = 16,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """Traceable (B, C, T) epochs -> (B, C*feature_size) features.

    The analysis-window slice is embedded into the cascade kernel
    (zero rows outside [skip, skip+size)), so slice + 6-level DWT is
    one einsum over the raw input layout — measured ~16x faster than
    slice-reshape-matmul on v5e (no relayout copy), which itself is
    ~16x faster than the level-by-level conv formulation.
    """
    B, C, T = epochs.shape
    kernel_np = cascade_matrix(wavelet_index, epoch_size, feature_size)
    full = np.zeros((T, feature_size))
    full[skip_samples : skip_samples + epoch_size] = kernel_np
    kernel = jnp.asarray(full, dtype=epochs.dtype)
    coeffs = jnp.einsum("bct,tk->bck", epochs, kernel, precision=precision)
    return safe_l2_normalize(coeffs.reshape(B, C * feature_size))


def make_compact_extractor(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    feature_size: int = 16,
    dtype=jnp.float32,
    donate_epochs: bool = False,
):
    """Jitted ``(B, C, epoch_size) -> (B, C*feature_size)`` extractor
    over COMPACT-RESIDENT epochs (the analysis window only, no dead
    columns).

    The full-width headline path (:func:`epoch_features`) embeds the
    [skip, skip+size) slice into the operator and reads all T=1000
    resident columns to consume 512 of them
    (WaveletTransform.java:127-130 — only the window is ever used).
    Storing epochs pre-sliced halves true HBM bytes/epoch (12000 ->
    6144 f32); this is the ``fe=dwt-8-tpu-compact`` backend and the
    library home of the bench's ``einsum_512`` variant, armed as the
    honest-bytes headline candidate (VERDICT r4 weakness 7 /
    docs/chip_playbook.md einsum_512 row).

    ``donate_epochs`` (opt-in) donates the epoch batch's device
    buffer to the call — single-use staged batches stop being
    double-resident in HBM; never enable it for a batch the caller
    feeds to the extractor (or anything else) again.
    """
    cascade_matrix(wavelet_index, epoch_size, feature_size)  # warm cache

    @partial(jax.jit, donate_argnums=(0,) if donate_epochs else ())
    def extract(epochs: jnp.ndarray) -> jnp.ndarray:
        return compact_epoch_features(
            jnp.asarray(epochs, dtype=dtype),
            wavelet_index,
            epoch_size,
            feature_size,
        )

    return extract


def compact_epoch_features(
    ep: jnp.ndarray,
    wavelet_index: int,
    epoch_size: int,
    feature_size: int,
) -> jnp.ndarray:
    """Traceable (B, C, epoch_size) pre-windowed epochs ->
    (B, C*feature_size) normalized features — the shared compact-
    residency body (the extractor above and
    parallel/train.make_compact_train_step both call this)."""
    B, C, n = ep.shape
    if n != epoch_size:
        # windowed_features sizes its cascade from the input, so a
        # mis-sliced batch would silently get a different-depth
        # transform; fail loudly instead
        raise ValueError(
            f"compact path built for epoch_size {epoch_size}; "
            f"got windowed batch of width {n}"
        )
    coeffs = windowed_features(ep, wavelet_index, feature_size)
    return safe_l2_normalize(coeffs.reshape(B, C * feature_size))


def make_batched_extractor(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    channels: Sequence[int] = (1, 2, 3),
    dtype=jnp.float32,
    method: str = "matmul",
    donate_epochs: bool = False,
):
    """Build a jitted ``(B, n_ch, n_samples) -> (B, F)`` extractor.

    The returned callable is the ``fe=dwt-8-tpu`` hot path: slice the
    per-channel analysis window, run the cascade, concat channels,
    L2-normalize each feature vector.

    method='matmul' (default): single composed-cascade matmul — the
    fast path, and in f32 *more* accurate than cascading f32 levels
    (one rounding instead of six).
    method='conv': the level-by-level filter-bank formulation (kept
    for cross-checking and for future Pallas work on long signals).

    ``donate_epochs`` (opt-in) donates the epoch batch's buffer to
    the extraction — correct only for single-use staged batches (see
    :func:`make_compact_extractor`).
    """
    if method not in ("matmul", "conv"):
        raise ValueError(f"unknown method {method!r}; use 'matmul' or 'conv'")
    h_np, g_np = eegdsp_compat.filter_pair(wavelet_index)
    ch_idx = np.array([c - 1 for c in channels])
    if method == "matmul":
        cascade_matrix(wavelet_index, epoch_size, feature_size)  # warm cache

    @partial(jax.jit, donate_argnums=(0,) if donate_epochs else ())
    def extract(epochs: jnp.ndarray) -> jnp.ndarray:
        ep = jnp.asarray(epochs, dtype=dtype)
        B = ep.shape[0]
        # channel gather only when the selection isn't the identity —
        # a no-op gather forces a full relayout copy of the batch
        if list(ch_idx) != list(range(ep.shape[1])):
            ep = ep[:, ch_idx, :]
        if method == "matmul":
            return epoch_features(
                ep, wavelet_index, skip_samples, epoch_size, feature_size
            )
        h = jnp.asarray(h_np, dtype=dtype)
        g = jnp.asarray(g_np, dtype=dtype)
        sl = ep[:, :, skip_samples : skip_samples + epoch_size]
        flat = sl.reshape(B * len(channels), epoch_size)
        coeffs = fwt_coefficient_prefix(flat, h, g, feature_size)
        feats = coeffs.reshape(B, len(channels) * feature_size)
        return safe_l2_normalize(feats)

    return extract


@partial(jax.jit, static_argnames=("wavelet_index", "count"))
def dwt_coefficients(x: jnp.ndarray, wavelet_index: int = 8, count: int = 16):
    """Jitted coefficient prefix for raw (N, n) signals (float32)."""
    h_np, g_np = eegdsp_compat.filter_pair(wavelet_index)
    h = jnp.asarray(h_np, dtype=x.dtype)
    g = jnp.asarray(g_np, dtype=x.dtype)
    return fwt_coefficient_prefix(x, h, g, count)
