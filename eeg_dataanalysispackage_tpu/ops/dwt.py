"""Batched XLA implementation of the eegdsp FWT (TPU hot path).

The identified algorithm (see ``ops/eegdsp_compat.py``) is a cascade
of periodized stride-2 FIR filter banks. Here each level is expressed
as one ``lax.conv_general_dilated`` with a 2-output-channel kernel
(scaling + wavelet filter) over a circularly-extended signal, so the
whole 6-level cascade + channel concat + L2 normalization trace into a
single jitted XLA program — no per-epoch Python, no dynamic shapes.
``vmap``/sharding happen naturally over the batch dimension; on TPU
the convolutions lower onto the MXU as small matmuls.

Replaces: the reference's per-epoch Spark map of
``WaveletTransform.extractFeatures`` over RDD partitions
(LogisticRegressionClassifier.java:55-61,90).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import eegdsp_compat


def _fwt_levels(x: jnp.ndarray, h: jnp.ndarray, g: jnp.ndarray):
    """Run the cascade on (N, n); returns (a_final, [d_1, d_2, ...])."""
    L = h.shape[0]
    kernel = jnp.stack([h, g])[:, None, :]  # (out_ch=2, in_ch=1, L)
    details = []
    a = x
    n = x.shape[1]
    while n >= L:
        # circular extension: index (2i+j) mod n, max 2(n//2-1)+L-1
        ext = jnp.concatenate([a, a[:, : L - 2]], axis=1) if L > 2 else a
        # HIGHEST: on TPU, f32 convs otherwise run bf16 multiply passes
        # (~1e-3 relative error on these features — measured); the op
        # is tiny and HBM-bound, so full precision is free.
        out = lax.conv_general_dilated(
            ext[:, None, :],
            kernel,
            window_strides=(2,),
            padding="VALID",
            dimension_numbers=("NCH", "OIH", "NCH"),
            precision=lax.Precision.HIGHEST,
        )
        a = out[:, 0, : n // 2]
        details.append(out[:, 1, : n // 2])
        n //= 2
    return a, details


def fwt_coefficient_prefix(x: jnp.ndarray, h, g, count: int) -> jnp.ndarray:
    """First ``count`` entries of the eegdsp layout [aK|dK|...|d1]."""
    a, details = _fwt_levels(x, h, g)
    layout = [a] + details[::-1]
    return jnp.concatenate(layout, axis=1)[:, :count]


def make_batched_extractor(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    channels: Sequence[int] = (1, 2, 3),
    dtype=jnp.float32,
):
    """Build a jitted ``(B, n_ch, n_samples) -> (B, F)`` extractor.

    The returned callable is the ``fe=dwt-8-tpu`` hot path: slice the
    per-channel analysis window, cascade the filter bank, concat
    channels, L2-normalize each feature vector.
    """
    h_np, g_np = eegdsp_compat.filter_pair(wavelet_index)
    ch_idx = np.array([c - 1 for c in channels])

    @jax.jit
    def extract(epochs: jnp.ndarray) -> jnp.ndarray:
        ep = jnp.asarray(epochs, dtype=dtype)
        B = ep.shape[0]
        h = jnp.asarray(h_np, dtype=dtype)
        g = jnp.asarray(g_np, dtype=dtype)
        sl = ep[:, ch_idx, skip_samples : skip_samples + epoch_size]
        flat = sl.reshape(B * len(channels), epoch_size)
        coeffs = fwt_coefficient_prefix(flat, h, g, feature_size)
        feats = coeffs.reshape(B, len(channels) * feature_size)
        norm = jnp.sqrt(jnp.sum(feats * feats, axis=1, keepdims=True))
        return feats / norm

    return extract


@partial(jax.jit, static_argnames=("wavelet_index", "count"))
def dwt_coefficients(x: jnp.ndarray, wavelet_index: int = 8, count: int = 16):
    """Jitted coefficient prefix for raw (N, n) signals (float32)."""
    h_np, g_np = eegdsp_compat.filter_pair(wavelet_index)
    h = jnp.asarray(h_np, dtype=x.dtype)
    g = jnp.asarray(g_np, dtype=x.dtype)
    return fwt_coefficient_prefix(x, h, g, count)
