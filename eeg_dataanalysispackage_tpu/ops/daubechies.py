"""Daubechies scaling-filter generation via spectral factorization.

The reference's DWT lives in the closed-source ``eegdsp`` jar
(WaveletTransform.java:108-136 calls it; index 8 in its 0..17 wavelet
registry = Daubechies-8). With no source and no network, the filter
taps are *computed* here to full double precision with mpmath instead
of being copied from a table:

  P(y) = sum_{k<N} C(N-1+k, k) y^k          (Daubechies polynomial)
  roots of P -> z-domain via y = (2 - z - 1/z)/4, keep |z| < 1
  m0(z) ~ ((1+z)/2)^N * prod (z - z_k)/(1 - z_k), normalized so
  sum(h) = sqrt(2)  (orthonormal convention).

Validated against the textbook db2 taps to 1e-16 in tests.
"""

from __future__ import annotations

from functools import lru_cache

import mpmath as mp
import numpy as np


@lru_cache(maxsize=None)
def daubechies_scaling(n_vanishing: int, precision: int = 80) -> np.ndarray:
    """Orthonormal Daubechies scaling filter with ``n_vanishing``
    vanishing moments (2*n_vanishing taps), sum = sqrt(2)."""
    N = int(n_vanishing)
    if N < 1:
        raise ValueError("n_vanishing must be >= 1")
    if N == 1:  # Haar
        h = np.array([1.0, 1.0]) / np.sqrt(2.0)
        return h
    with mp.workdps(precision):
        # Daubechies polynomial P(y), ascending powers
        coeffs = [mp.binomial(N - 1 + k, k) for k in range(N)]
        # polyroots wants descending order
        roots_y = mp.polyroots(list(reversed(coeffs)), maxsteps=200, extraprec=200)

        # Each y-root gives a quadratic in z: z^2 - (2 - 4y) z + 1 = 0.
        z_roots = []
        for y in roots_y:
            b = 2 - 4 * y
            disc = mp.sqrt(b * b - 4)
            z1 = (b + disc) / 2
            z2 = (b - disc) / 2
            z = z1 if abs(z1) < 1 else z2
            z_roots.append(z)

        # Filter polynomial: ((1+z)/2)^N times prod (z - z_k)/(1 - z_k)
        poly = [mp.mpf(1)]
        for _ in range(N):
            poly = _polymul(poly, [mp.mpf(1), mp.mpf(1)])  # (1 + z)
        for z in z_roots:
            poly = _polymul(poly, [-z, mp.mpf(1)])  # (z - z_k) ascending

        # real part (conjugate roots pair up; imag parts cancel)
        poly = [mp.re(c) for c in poly]
        s = sum(poly)
        sqrt2 = mp.sqrt(2)
        h = [c / s * sqrt2 for c in poly]
        return np.array([float(c) for c in h], dtype=np.float64)


def _polymul(a, b):
    out = [mp.mpf(0)] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        for j, bj in enumerate(b):
            out[i + j] += ai * bj
    return out
