"""Reverse-engineered eegdsp discrete-wavelet compatibility layer.

The reference's feature extractor delegates to the closed-source
``eegdsp`` jar (WaveletTransform.java:108-136). With no source
available, the exact algorithm was identified *numerically* from the
reference's golden checksum (``FeatureExtractionTest.java:106``,
sum(11x48 features) == -24.861844096031625) by searching the space of
filter families x boundary conventions x phases x decomposition depths
and then pinning the remaining 2-ulp gap via accumulation order. The
winning convention — bit-exact on the fixture — is:

- scaling filter: the 10-tap Daubechies filter in the classic
  *12-decimal-digit truncated* table (Daubechies, "Ten Lectures",
  Table 6.1, N=5), ascending textbook order h0..h9. The registry index
  the app calls ``8`` ("dwt-8") resolves to this filter, i.e. eegdsp's
  ``names[8]`` is the 10-tap "Daubechies10" — the reference test's
  comment "Daubechies 8 mother wavelet" is wrong about its own jar;
- wavelet filter: g[j] = -(-1)^j h[L-1-j];
- per level, on the current prefix of length n:
  a[i] = sum_j h[j] * x[(2i+j) mod n],
  d[i] = sum_j g[j] * x[(2i+j) mod n], written back as [a | d];
- decompose while n >= len(h): 512 -> 8 in six levels, leaving the
  layout [a6(8) | d6(8) | d5(16) | d4(32) | ...];
- ``getDwtCoefficients()[0:16]`` therefore yields a6 ++ d6, *not*
  "level-5 approximation coefficients" as the reference's comments
  claim;
- all inner products accumulate left-to-right in float64 (matched with
  sequential cumsum folds).

The registry mirrors eegdsp's 18-entry wavelet name table
(WaveletTransform.java:160-166 validates 0 <= NAME <= 17): index i
maps to the (i+2)-tap Daubechies filter; odd tap counts do not exist,
which matches the reference's own try/catch around wavelet loading
(WaveletTransform.java:114-119). Only index 8 is pinned by a golden
checksum; the other even indices use the same 12-digit truncation rule
applied to spectral-factorization values.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import numpy as np

from . import daubechies

# The golden-pinned 10-tap table (index 8). 12 decimal digits, exactly
# as classic print tables give them — using higher-precision values
# breaks bit parity with the reference (verified: full-precision taps
# land 3e-11 off the checksum; these land 0.0 off).
DAUB10_H = np.array(
    [
        0.160102397974,
        0.603829269797,
        0.724308528438,
        0.138428145901,
        -0.242294887066,
        -0.032244869585,
        0.077571493840,
        -0.006241490213,
        -0.012580751999,
        0.003335725285,
    ],
    dtype=np.float64,
)

NUM_WAVELETS = 18  # registry indices 0..17 (WaveletTransform.java:161)


def wavelet_name(index: int) -> str:
    return f"Daubechies{index + 2}"


@lru_cache(maxsize=None)
def scaling_filter(index: int) -> np.ndarray:
    """Scaling filter for registry ``index`` (0..17), textbook order.

    Raises ValueError for indices whose tap count is odd (no such
    Daubechies filter — the reference logs and fails for those too).
    """
    if not 0 <= index < NUM_WAVELETS:
        raise ValueError("Wavelet Name must be >= 0 and <= 17")
    taps = index + 2
    if taps % 2:
        raise ValueError(
            f"Exception loading wavelet {wavelet_name(index)}: "
            f"no Daubechies filter with an odd tap count ({taps})"
        )
    if index == 8:
        return DAUB10_H
    h = daubechies.daubechies_scaling(taps // 2)[::-1]
    # same 12-decimal truncation rule as the printed tables
    return np.round(h, 12)


def wavelet_filter(h: np.ndarray) -> np.ndarray:
    """g[j] = -(-1)^j h[L-1-j] (the identified eegdsp convention)."""
    L = len(h)
    signs = np.array([(-1.0) ** (k + 1) for k in range(L)])
    return signs * h[::-1]


def filter_pair(index: int) -> Tuple[np.ndarray, np.ndarray]:
    h = scaling_filter(index)
    return h, wavelet_filter(h)
