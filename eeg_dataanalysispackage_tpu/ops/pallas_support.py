"""Shared Pallas platform support checks."""

from __future__ import annotations

import os

import jax


def default_interpret() -> bool:
    """Should a Pallas kernel default to interpreter mode here?

    Compiled Mosaic runs on a native TPU backend, and on the axon
    platform (a real TPU behind a tunnel) only when its remote-compile
    hook is enabled (``PALLAS_AXON_REMOTE_COMPILE``). Everything else
    (CPU test meshes, plain CPU) interprets.
    """
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "axon":
        enabled = os.environ.get("PALLAS_AXON_REMOTE_COMPILE", "")
        return enabled.strip().lower() not in ("1", "true", "yes")
    return True


def default_ingest_mode() -> str:
    """Platform-aware default for the irregular Pallas ingest kernel.

    Compiled Mosaic (TPU, or axon with remote compile): ``bank128`` —
    the only formulation whose every construct compiles through the
    axon remote helper (round-4 chip bisect + probe: dynamic lane
    slices and lane-split reshapes crash it; the exact and aligned8
    kernels use one each). Interpreter platforms: ``exact`` — the
    subtract-first parity anchor the other modes are tested against.
    """
    return "exact" if default_interpret() else "bank128"
