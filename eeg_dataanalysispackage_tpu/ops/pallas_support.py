"""Shared Pallas platform support checks."""

from __future__ import annotations

import os

import jax


def default_interpret() -> bool:
    """Should a Pallas kernel default to interpreter mode here?

    Compiled Mosaic runs on a native TPU backend, and on the axon
    platform (a real TPU behind a tunnel) only when its remote-compile
    hook is enabled (``PALLAS_AXON_REMOTE_COMPILE``). Everything else
    (CPU test meshes, plain CPU) interprets.
    """
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "axon":
        enabled = os.environ.get("PALLAS_AXON_REMOTE_COMPILE", "")
        return enabled.strip().lower() not in ("1", "true", "yes")
    return True
