"""The serve-path megakernel: raw window bytes -> margin, one pass.

The serving engine's fused program (serve/engine.py) reuses the batch
path's ``make_device_ingest_featurizer`` — correct by construction,
but built for IRREGULAR marker layouts: it cuts windows with the XLA
element gather the roofline analysis measured at ~5 ns/ELEMENT on CPU
and far below roofline on chip (docs/performance.md). The serving
stream has none of that irregularity: the engine LAYS OUT the
micro-batch itself, so window ``i`` can live at a known static offset.
This module exploits exactly that — the whole serving hot path

    int16 decode -> window cut -> f32 pre-stimulus mean subtract ->
    Db cascade contraction -> 48-dim L2-normalized feature -> linear
    margin

runs as ONE kernel over the staged stream, and neither epochs nor
feature rows ever materialize in HBM: the program's only output is the
``(capacity,)`` margin vector (4 bytes/request out against ~5 KB of
int16 window bytes in).

Two lowerings share the contract (the interpret-mode/XLA twin pattern
``ops/ingest_pallas.py`` established):

- ``pallas``: a Pallas TPU kernel. The stream is laid out at a
  128-lane-padded window stride and viewed as rows-of-128 (the
  bank128 kernel's chip-proven layout), so each grid step's BlockSpec
  fetch is whole aligned rows — standard pipelined DMA, which Pallas
  DOUBLE-BUFFERS automatically: step i+1's window block streams into
  VMEM while step i computes. Window cuts are STATIC slices (the
  stream is regular by construction — no dynamic lane slice, the
  remote-compile crasher class), the cascade contraction is one MXU
  dot against the zero-padded window operator
  (``device_ingest.ingest_matrix(fold_baseline=False)`` — explicit
  subtract-first baseline, the f32-safety shape every kernel here
  uses), the L2 normalize runs on the VPU, and the margin is one more
  MXU dot against the weight vector padded to a 128-lane matrix.
  Interpret mode runs the same kernel on CPU for hermetic tier-1
  parity pins; on TPU it compiles to Mosaic.
- ``xla``: the compiled twin for hosts where Mosaic is unavailable —
  the SAME regular layout collapses the window cut to a free reshape
  (``(C, cap*Wp) -> (C, cap, Wp)``), i.e. the gather-free einsum
  family the chip table clocks at 45.1M eps vs the fused engine
  program's gather formulation. On CPU this twin is the mega rung's
  production lowering (and genuinely faster than the fused program:
  it never pays the scalar-load gather), so the rung, its warmup
  gate, and the parity pins all run in tier-1.

Accelerator default follows the PR 9 decision path: the engine's
``auto`` rung resolves through :func:`accelerator_decision`, which
harvests staged ``serve_mega`` sweep artifacts
(tools/collect_chip_runs.sh) and flips the accelerator default from
``fused`` to ``mega`` iff a measured-silicon line shows the mega rung
beating the fused twin at concurrency 16 by the pre-registered
margin — artifact lands, default flips, zero code change. CPU hosts
default to ``mega`` outright: the XLA twin's gather-free win is
measured locally by the serve_mega bench/smoke gate.

Numerics: subtract-first baseline, ``Precision.HIGHEST`` contractions
with f32 accumulation, the shared ``safe_l2_normalize`` — the same
ladder-rung class as every fused formulation (~1e-6 on margins; the
engine pins it at warmup against the fused program and refuses the
rung above :func:`mega_gate_tolerance`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import constants
from . import device_ingest
from . import dwt as dwt_xla

#: the engine-rung decision surface (single source for the engine,
#: the bench, and tests).
LOWERINGS = ("pallas", "xla")

#: windows per Pallas grid step; divides the 64-multiple capacity grid.
MEGA_TILE = 8

#: warmup parity gate: max abs deviation of mega margins vs the fused
#: program's margins on the same synthetic windows before the engine
#: refuses the rung. Margins are (unit-norm feature row) . (model
#: weights); the rungs' feature deviation sits in the established
#: ~1e-7..1e-6 ladder class (docs/performance.md), so 5e-5 is that
#: envelope with the weight-norm factor of a trained linear model —
#: three orders tighter than any decision threshold gap observed on
#: real margins. Override for experiments via EEG_TPU_MEGA_GATE_TOL.
MEGA_GATE_TOL = 5e-5

#: the pre-registered accelerator flip margin (the PR 9 decision-path
#: pattern): a staged chip artifact must show the mega rung's
#: concurrency-16 predictions/sec beating the fused twin's by >= this
#: ratio before the accelerator ``auto`` rung resolves to mega.
MEGA_FLIP_RATIO = 1.1

#: sweep-artifact filename stems that carry a serve_mega chip sweep.
_MEGA_ARTIFACTS = ("serve_mega*.json",)


def mega_gate_tolerance() -> float:
    """The documented mega warmup gate (``MEGA_GATE_TOL``), with the
    experiment override ``EEG_TPU_MEGA_GATE_TOL`` (logged, never
    silent, on an unparseable value — the decode-rung gate policy)."""
    import logging
    import os

    raw = os.environ.get("EEG_TPU_MEGA_GATE_TOL")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logging.getLogger(__name__).warning(
                "EEG_TPU_MEGA_GATE_TOL=%r is not a float; using the "
                "default gate %g", raw, MEGA_GATE_TOL,
            )
    return MEGA_GATE_TOL


def padded_stride(pre: int, post: int) -> int:
    """The serve stream's per-window stride: the live window (pre +
    post samples) rounded up to whole 128-lane rows, so every window
    starts on a lane-tile boundary and the Pallas block fetches are
    aligned whole rows. The pad columns are zeros the operator's zero
    rows never read."""
    win = int(pre) + int(post)
    return -(-win // 128) * 128


def default_lowering() -> str:
    """``pallas`` where Mosaic compiles (real TPU, or axon with the
    remote-compile hook), the ``xla`` twin everywhere else — resolved
    per call, never cached (the 'auto'-resolution staleness class
    device_ingest documents)."""
    from . import pallas_support

    return "xla" if pallas_support.default_interpret() else "pallas"


def _sweep_results_root() -> str:
    from . import decode_ingest

    return decode_ingest._sweep_results_root()


def accelerator_decision(root: str | None = None) -> dict:
    """The mega rung's accelerator decision path, as DATA (the PR 9
    pattern): harvest the best on-chip ``serve_mega`` sweep line and
    judge its concurrency-16 mega-vs-fused ratio against the
    pre-registered :data:`MEGA_FLIP_RATIO`. Returns ``{"rung",
    "mega_preds_per_s", "fused_preds_per_s", "ratio", "source",
    "threshold_ratio", "reason"}`` — the flip happens when (and only
    when) measured silicon says the megakernel earns it. With no chip
    artifact on disk, the decision is ``fused`` with that absence as
    the recorded reason."""
    import glob
    import json
    import os

    base = root or _sweep_results_root()
    best = None
    best_src = None
    for pattern in _MEGA_ARTIFACTS:
        for path in glob.glob(os.path.join(base, "*", pattern)):
            try:
                if os.path.getsize(path) == 0:
                    continue
                with open(path) as f:
                    rec = json.loads(f.read().strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                continue
            if rec.get("platform") not in ("tpu", "axon"):
                continue
            sweep = (
                (rec.get("serve") or {}).get("mega_vs_fused") or {}
            ).get("sweep") or []
            for level in sweep:
                if level.get("concurrency") != 16:
                    continue
                mega = (level.get("mega") or {}).get("preds_per_s")
                fused = (level.get("fused") or {}).get("preds_per_s")
                if not (
                    isinstance(mega, (int, float))
                    and isinstance(fused, (int, float))
                    and mega > 0 and fused > 0
                ):
                    continue
                if best is None or mega / fused > best[0]:
                    best, best_src = (mega / fused, mega, fused), path
    decision = {
        "threshold_ratio": MEGA_FLIP_RATIO,
        "source": (
            os.path.relpath(best_src, os.path.dirname(base))
            if best_src
            else None
        ),
    }
    if best is None:
        decision.update(
            rung="fused",
            mega_preds_per_s=None,
            fused_preds_per_s=None,
            ratio=None,
            reason=(
                "no on-chip serve_mega sweep in the staged artifacts; "
                "the fused engine program stands"
            ),
        )
        return decision
    ratio, mega, fused = best
    decision.update(
        mega_preds_per_s=mega,
        fused_preds_per_s=fused,
        ratio=round(ratio, 4),
    )
    if ratio >= MEGA_FLIP_RATIO:
        decision.update(
            rung="mega",
            reason=(
                f"serve_mega measured {mega:.0f} preds/s on chip at "
                f"concurrency 16 >= {MEGA_FLIP_RATIO:g}x the fused "
                f"twin ({fused:.0f}); the megakernel takes the "
                f"accelerator default"
            ),
        )
    else:
        decision.update(
            rung="fused",
            reason=(
                f"serve_mega measured {mega:.0f} preds/s on chip at "
                f"concurrency 16 < {MEGA_FLIP_RATIO:g}x the fused "
                f"twin ({fused:.0f}); fused stands"
            ),
        )
    return decision


@functools.lru_cache(maxsize=None)
def _cached_accelerator_rung() -> str:
    return accelerator_decision()["rung"]


def default_engine_rung() -> str:
    """What the serving engine's ``engine_rung="auto"`` resolves to:
    ``mega`` on CPU hosts (the XLA twin never pays the gather — the
    win this module exists for, and the warmup gate still guards the
    numerics), the recorded chip decision on accelerators."""
    if jax.devices()[0].platform == "cpu":
        return "mega"
    return _cached_accelerator_rung()


def _make_mega_kernel(n_channels: int, tile_b: int, stride: int,
                      pre: int, feature_size: int,
                      precision: str = "f32"):
    """The Pallas kernel body: one grid step = ``tile_b`` windows.

    ``a_ref`` is the step's stream block in the rows-of-128 layout
    (automatically double-buffered by the BlockSpec pipeline); every
    construct here is from the bank128 kernel's chip-proven set —
    lane-contiguous reshapes, STATIC lane slices (offsets are
    ``e * stride`` with ``stride % 128 == 0``), MXU dots with f32
    accumulation, VPU reductions.

    ``precision="int8"|"int4"`` quantizes the finished feature rows
    before the margin dot via the MASKED grouped quantizer
    (ops/quant.masked_quantize_dequantize): full-lane VPU ops only —
    the reshape-based cores' ``(n, C, K)`` regrouping is a lane-split
    reshape, the documented remote-compile crasher class — and
    numerically identical to them, so the kernel's margins parity-gate
    against the fused quantized program like the f32 kernel does
    against the fused f32 program."""
    from . import quant

    C = n_channels
    K = feature_size
    if precision in ("int8", "int4"):
        masks = quant.subband_lane_masks(C, K)
        qmax = 127.0 if precision == "int8" else quant.INT4_QMAX
    elif precision != "f32":
        raise ValueError(
            f"mega kernel precision {precision!r}; use f32, int8, or "
            f"int4 (bf16 has no mega twin — its cascade runs bf16 "
            f"operands, not quantized f32 rows)"
        )

    def kernel(a_ref, res_ref, e_ref, wm_ref, o_ref, xa_ref):
        # decode: int16 (or staged f32) block -> scaled f32, once
        x = (
            a_ref[:].astype(jnp.float32).reshape(C, tile_b * stride)
            * res_ref[:]
        )
        for e in range(tile_b):
            seg = x[:, e * stride:(e + 1) * stride]
            # explicit f32 pre-stimulus baseline (Baseline.java:29-57;
            # subtract-first — folding it into the operator cancels
            # catastrophically on real EEG DC offsets)
            base = jnp.mean(seg[:, :pre], axis=1, keepdims=True)
            xa_ref[e * C:(e + 1) * C, :] = seg - base
        y = lax.dot_general(
            xa_ref[:], e_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (tile_b*C, K)
        feats = dwt_xla.safe_l2_normalize(y.reshape(tile_b, C * K))
        if precision in ("int8", "int4"):
            feats = quant.masked_quantize_dequantize(
                feats, masks, qmax
            )
        # margin: one more MXU dot against the weights padded to a
        # 128-lane matrix (column 0 carries the model; features never
        # leave VMEM)
        o_ref[:] = lax.dot_general(
            feats, wm_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )

    return kernel


@functools.lru_cache(maxsize=None)
def _mega_program(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    n_channels: int,
    pre: int,
    post: int,
    capacity: int,
    lowering: str,
    interpret: bool,
    donate: bool,
    tile_b: int = MEGA_TILE,
    precision: str = "f32",
):
    """The jitted megakernel program, cached per geometry/capacity:
    ``(stream (C, capacity*Wp), resolutions (C,), weights (C*K,)) ->
    margins (capacity,) float32`` (pre-intercept, like the fused
    program's fused matvec). One compiled program serves every batch
    size 1..capacity — padded windows are zero, each window's compute
    is row-independent, so a window's margin is BIT-IDENTICAL whatever
    batch it rides in (pinned in tests/test_serve_mega.py).

    ``precision="int8"|"int4"`` quantizes the finished feature rows
    before the margin (the quantized-feature engines' mega twin —
    ISSUE 18 closes the PR 12 leftover that hard-pinned them to
    fused): the XLA twin applies the SAME canonical quantize cores the
    fused program uses, the pallas kernel the masked spelling of
    them."""
    if precision not in ("f32", "int8", "int4"):
        raise ValueError(
            f"mega precision {precision!r}; use f32, int8, or int4 "
            f"(bf16 has no mega twin — its cascade runs bf16 "
            f"operands, not quantized f32 rows)"
        )
    if capacity % tile_b:
        raise ValueError(
            f"mega capacity {capacity} must be a multiple of the "
            f"{tile_b}-window kernel tile (the engine's 64-multiple "
            f"bucketing satisfies it)"
        )
    if pre < 1:
        raise ValueError(
            "the megakernel's baseline subtract needs pre >= 1 "
            "(pre=0 geometries serve through the host-extractor mode)"
        )
    C = int(n_channels)
    K = int(feature_size)
    Wp = padded_stride(pre, post)
    live = pre + skip_samples + epoch_size
    if live > Wp:
        raise ValueError(
            f"window geometry (pre {pre} + skip {skip_samples} + "
            f"epoch {epoch_size} = {live}) exceeds the padded stride "
            f"{Wp} (= pre+post rounded to 128)"
        )
    E_np = device_ingest.ingest_matrix(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        window_len=Wp, fold_baseline=False,
    )
    donate_args = (0,) if donate else ()

    if lowering == "xla":
        # the compiled twin: the regular layout makes the window cut a
        # reshape, and only the columns the math consumes are ever
        # scaled (the _ingest_reshape idiom — pre head for the
        # baseline, live analysis window for the contraction)
        W_np = E_np[pre + skip_samples: pre + skip_samples + epoch_size]

        @functools.partial(jax.jit, donate_argnums=donate_args)
        def run(stream, resolutions, weights):
            W = jnp.asarray(W_np)
            rows = stream.reshape(C, capacity, Wp)
            scale = resolutions[:, None, None]
            pre_f = rows[:, :, :pre].astype(jnp.float32) * scale
            live_f = rows[
                :, :, pre + skip_samples: pre + skip_samples + epoch_size
            ].astype(jnp.float32) * scale
            base = jnp.mean(pre_f, axis=2, keepdims=True)
            z = (live_f - base).reshape(C * capacity, epoch_size)
            y = lax.dot_general(
                z, W, (((1,), (0,)), ((), ())),
                precision=lax.Precision.HIGHEST,
            )
            feats = jnp.transpose(
                y.reshape(C, capacity, K), (1, 0, 2)
            ).reshape(capacity, C * K)
            feats = dwt_xla.safe_l2_normalize(feats)
            # the quantized-feature rungs: the CANONICAL cores — the
            # exact traceables the fused serving program runs, so
            # feature rows (and thus margins, modulo the dot
            # formulations' documented drift) parity-gate cleanly
            if precision == "int8":
                from . import decode_ingest

                feats, _ = decode_ingest.quantize_dequantize_int8(
                    feats, K
                )
            elif precision == "int4":
                from . import quant

                feats, _ = quant.quantize_dequantize_int4(feats, K)
            return jnp.dot(
                feats, weights.astype(jnp.float32),
                precision=lax.Precision.HIGHEST,
            )

        return run

    if lowering != "pallas":
        raise ValueError(
            f"unknown mega lowering {lowering!r}; use one of {LOWERINGS}"
        )

    rpw = Wp // 128
    kernel = _make_mega_kernel(C, tile_b, Wp, pre, K, precision)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(capacity // tile_b,),
        in_specs=[
            pl.BlockSpec(
                (C, tile_b * rpw, 128), lambda i: (0, i, 0)
            ),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((Wp, K), lambda i: (0, 0)),
            pl.BlockSpec((C * K, 128), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, 128), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((tile_b * C, Wp), jnp.float32),
        ],
    )

    @functools.partial(jax.jit, donate_argnums=donate_args)
    def run(stream, resolutions, weights):
        wm = jnp.zeros((C * K, 128), jnp.float32).at[:, 0].set(
            weights.astype(jnp.float32)
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((capacity, 128), jnp.float32),
            interpret=interpret,
        )(
            stream.reshape(C, capacity * rpw, 128),
            resolutions.astype(jnp.float32)[:, None],
            jnp.asarray(E_np),
            wm,
        )
        return out[:, 0]

    return run


@functools.lru_cache(maxsize=None)
def _mega_multi_program(
    wavelet_index: int,
    epoch_size: int,
    skip_samples: int,
    feature_size: int,
    n_channels: int,
    pre: int,
    post: int,
    capacity: int,
    lowering: str,
    interpret: bool,
    donate: bool,
    tile_b: int = MEGA_TILE,
    weights_precision: str = "f32",
):
    """The tenant-stacked megakernel: ``(stream, resolutions,
    weight_matrix (C*K, 128), tenant_lanes (capacity,) int32) ->
    margins (capacity,)``, one compiled program for every tenant mix
    (serve/multiplex.py).

    ``weights_precision="int8"|"int4"`` swaps the weight-matrix
    argument for ``(packed, scales)`` — the quantized stack's
    RESIDENT payload (ops/quant.py) — and reconstructs the (C*K, 128)
    f32 matrix inside the program with elementwise VPU ops feeding
    the SAME single MXU dot (pallas) / HIGHEST matmul (xla twin). The
    dequant stays OUTSIDE the kernel body deliberately: sub-byte
    nibble unpacking in Mosaic needs int8 blocks under the (32, 128)
    minimum tile or lane-split reshapes — the remote-compile crasher
    class — while as plain XLA it fuses into the program for free and
    the kernel keeps its chip-proven f32 contract.

    The solo kernel ALREADY computes the full ``(tile_b, 128)`` margin
    matrix against a 128-lane weight matrix and discards 127 columns;
    the multi-tenant pallas lowering simply passes the filled tenant
    stack as that matrix and gathers each row's tenant column OUTSIDE
    the kernel (no in-kernel dynamic lane slice — the remote-compile
    crasher class). Column position is reduction-invariant in the MXU
    dot (and measured so on the XLA interpret path), so a tenant's
    margin matches the solo kernel's column 0 bit-for-bit. The XLA
    twin mirrors the fused multi program's discipline instead: 128
    unrolled HIGHEST matvecs — each byte-identical to the solo twin's
    margin dot — then the per-row column pick (a plain matmul column
    drifts ~3e-5 from the matvec; measured, not assumed). Both
    lowerings sit behind the engine's warmup margin-parity gate
    exactly like the solo program."""
    if capacity % tile_b:
        raise ValueError(
            f"mega capacity {capacity} must be a multiple of the "
            f"{tile_b}-window kernel tile (the engine's 64-multiple "
            f"bucketing satisfies it)"
        )
    if pre < 1:
        raise ValueError(
            "the megakernel's baseline subtract needs pre >= 1 "
            "(pre=0 geometries serve through the host-extractor mode)"
        )
    C = int(n_channels)
    K = int(feature_size)
    Wp = padded_stride(pre, post)
    live = pre + skip_samples + epoch_size
    if live > Wp:
        raise ValueError(
            f"window geometry (pre {pre} + skip {skip_samples} + "
            f"epoch {epoch_size} = {live}) exceeds the padded stride "
            f"{Wp} (= pre+post rounded to 128)"
        )
    E_np = device_ingest.ingest_matrix(
        wavelet_index, epoch_size, skip_samples, feature_size, pre,
        window_len=Wp, fold_baseline=False,
    )
    donate_args = (0,) if donate else ()

    if weights_precision not in ("f32", "int8", "int4"):
        raise ValueError(
            f"mega weights_precision {weights_precision!r}; use one "
            f"of ('f32', 'int8', 'int4')"
        )

    def wrap_quantized(inner):
        """Adapt a ``(stream, res, weight_matrix, lanes)`` body to the
        quantized-stack signature ``(stream, res, packed, scales,
        lanes)``: the resident payload expands to f32 inside the
        program (ops/quant.dequantize_weight_stack — elementwise, VPU
        on Mosaic platforms) and the margin math is untouched."""
        if weights_precision == "f32":
            return inner
        from . import quant

        def run(stream, resolutions, packed, scales, tenant_lanes):
            wm = quant.dequantize_weight_stack(
                packed, scales, weights_precision, C * K
            )
            return inner(stream, resolutions, wm, tenant_lanes)

        return run

    if lowering == "xla":
        W_np = E_np[pre + skip_samples: pre + skip_samples + epoch_size]

        def body(stream, resolutions, weight_matrix, tenant_lanes):
            W = jnp.asarray(W_np)
            rows = stream.reshape(C, capacity, Wp)
            scale = resolutions[:, None, None]
            pre_f = rows[:, :, :pre].astype(jnp.float32) * scale
            live_f = rows[
                :, :, pre + skip_samples: pre + skip_samples + epoch_size
            ].astype(jnp.float32) * scale
            base = jnp.mean(pre_f, axis=2, keepdims=True)
            z = (live_f - base).reshape(C * capacity, epoch_size)
            y = lax.dot_general(
                z, W, (((1,), (0,)), ((), ())),
                precision=lax.Precision.HIGHEST,
            )
            feats = jnp.transpose(
                y.reshape(C, capacity, K), (1, 0, 2)
            ).reshape(capacity, C * K)
            feats = dwt_xla.safe_l2_normalize(feats)
            # one (capacity, 128) HIGHEST-precision matmul, then a
            # row-indexed gather. Under Precision.HIGHEST a matmul
            # column is bitwise the solo twin's matvec on XLA:CPU
            # (measured; NOT true at default precision, which is why
            # the fused multi program unrolls per-column matvecs
            # instead — each formulation copies its solo twin's
            # primitive exactly)
            columns = jnp.dot(
                feats, weight_matrix.astype(jnp.float32),
                precision=lax.Precision.HIGHEST,
            )
            return jnp.take_along_axis(
                columns, tenant_lanes[:, None], axis=1
            )[:, 0]

        return jax.jit(
            wrap_quantized(body), donate_argnums=donate_args
        )

    if lowering != "pallas":
        raise ValueError(
            f"unknown mega lowering {lowering!r}; use one of {LOWERINGS}"
        )

    rpw = Wp // 128
    kernel = _make_mega_kernel(C, tile_b, Wp, pre, K)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(capacity // tile_b,),
        in_specs=[
            pl.BlockSpec(
                (C, tile_b * rpw, 128), lambda i: (0, i, 0)
            ),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((Wp, K), lambda i: (0, 0)),
            pl.BlockSpec((C * K, 128), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, 128), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((tile_b * C, Wp), jnp.float32),
        ],
    )

    def body(stream, resolutions, weight_matrix, tenant_lanes):
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((capacity, 128), jnp.float32),
            interpret=interpret,
        )(
            stream.reshape(C, capacity * rpw, 128),
            resolutions.astype(jnp.float32)[:, None],
            jnp.asarray(E_np),
            weight_matrix.astype(jnp.float32),
        )
        return jnp.take_along_axis(
            out, tenant_lanes[:, None], axis=1
        )[:, 0]

    return jax.jit(wrap_quantized(body), donate_argnums=donate_args)


def make_serve_mega_multi_program(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    n_channels: int = constants.USED_CHANNELS,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    capacity: int = 64,
    lowering: str | None = None,
    interpret: bool | None = None,
    donate: bool | None = None,
    weights_precision: str = "f32",
):
    """Build (or fetch cached) the tenant-stacked megakernel program
    for one serving geometry — the multi-tenant twin of
    :func:`make_serve_mega_program`, same resolution rules.
    ``weights_precision="int8"|"int4"`` builds the packed-stack
    lowering: ``(stream, resolutions, packed, scales, tenant_lanes)``
    with the dequant inside the program."""
    from . import pallas_support

    if lowering is None:
        lowering = default_lowering()
    if interpret is None:
        interpret = pallas_support.default_interpret()
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return _mega_multi_program(
        int(wavelet_index), int(epoch_size), int(skip_samples),
        int(feature_size), int(n_channels), int(pre), int(post),
        int(capacity), str(lowering), bool(interpret), bool(donate),
        weights_precision=str(weights_precision),
    )


def make_serve_mega_program(
    wavelet_index: int = 8,
    epoch_size: int = 512,
    skip_samples: int = 175,
    feature_size: int = 16,
    n_channels: int = constants.USED_CHANNELS,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    capacity: int = 64,
    lowering: str | None = None,
    interpret: bool | None = None,
    donate: bool | None = None,
    precision: str = "f32",
):
    """Build (or fetch cached) the megakernel program for one serving
    geometry. ``lowering`` None resolves per platform
    (:func:`default_lowering`); ``interpret`` None follows
    ``pallas_support.default_interpret`` (tests force
    ``lowering="pallas", interpret=True`` for hermetic kernel parity);
    ``donate`` None donates the staged stream on accelerator backends
    only (the engine's established donation policy — XLA:CPU cannot
    alias it and would warn per call). ``precision="int8"|"int4"``
    builds the quantized-feature twin (the finished rows pass through
    that rung's quantizer before the margin)."""
    from . import pallas_support

    if lowering is None:
        lowering = default_lowering()
    if interpret is None:
        interpret = pallas_support.default_interpret()
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return _mega_program(
        int(wavelet_index), int(epoch_size), int(skip_samples),
        int(feature_size), int(n_channels), int(pre), int(post),
        int(capacity), str(lowering), bool(interpret), bool(donate),
        precision=str(precision),
    )


def stage_mega_stream(
    windows, n_channels: int, window_len: int, stride: int,
    capacity: int, dtype=None,
) -> np.ndarray:
    """Lay a micro-batch out at the padded stride: window ``i``'s raw
    samples at columns ``[i*stride, i*stride + window_len)``, pad
    columns and unused capacity rows zero. The megakernel's host-side
    staging counterpart of the engine's fused-stream packing."""
    if dtype is None:
        dtype = np.asarray(windows[0]).dtype
    stream = np.zeros((n_channels, capacity * stride), dtype=dtype)
    for i, w in enumerate(windows):
        w = np.asarray(w)
        if w.shape != (n_channels, window_len):
            raise ValueError(
                f"window {i} has shape {w.shape}, expected "
                f"({n_channels}, {window_len})"
            )
        stream[:, i * stride:i * stride + window_len] = w
    return stream
