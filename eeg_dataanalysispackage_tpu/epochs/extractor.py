"""Stimulus-locked epoch extraction with bit-exact reference semantics.

Reproduces the epoching engine of
``OffLineDataProvider.processEEGFiles``
(OffLineDataProvider.java:147-268) as a vectorized host computation:

1. window gather: samples ``[pos-100, pos+750)`` around each marker
   position (out-of-range windows skipped, matching the
   ArrayIndexOutOfBoundsException catch at :262-264);
2. float32 round-trip: the reference narrows double->float
   (``DataProviderUtils.toFloatArray``) before baseline correction;
3. baseline correction in float32 with *sequential* accumulation of the
   first 100 samples (``Baseline.correct(float[],int)`` accumulates a
   float — Baseline.java:29-42). np.cumsum is a sequential left fold,
   so the vectorized form is bit-identical to the Java loop;
4. the trailing 750 samples are widened back to float64
   (``EpochHolder.setFZ/CZ/PZ`` — EpochHolder.java:75-91);
5. the order-dependent target/non-target balance scan
   (OffLineDataProvider.java:248-260) — inherently sequential, kept as
   a tiny host loop over booleans.

Everything downstream (DWT, classifiers) consumes the resulting
``(n_epochs, n_channels, 750)`` float64 array.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np

from ..io.brainvision import Marker
from ..utils import constants


@dataclasses.dataclass
class EpochBatch:
    """Extracted epochs + labels for one or more recordings."""

    epochs: np.ndarray  # (n, channels, POSTSTIMULUS) float64
    targets: np.ndarray  # (n,) float64 of {0.0, 1.0}
    stimulus_indices: np.ndarray  # (n,) int

    def __len__(self) -> int:
        return self.epochs.shape[0]

    @staticmethod
    def empty(n_channels: int = constants.USED_CHANNELS,
              post: int = constants.POSTSTIMULUS_SAMPLES) -> "EpochBatch":
        return EpochBatch(
            epochs=np.zeros((0, n_channels, post), dtype=np.float64),
            targets=np.zeros((0,), dtype=np.float64),
            stimulus_indices=np.zeros((0,), dtype=int),
        )

    @staticmethod
    def concatenate(batches: Sequence["EpochBatch"]) -> "EpochBatch":
        if not batches:
            return EpochBatch.empty()
        return EpochBatch(
            epochs=np.concatenate([b.epochs for b in batches], axis=0),
            targets=np.concatenate([b.targets for b in batches], axis=0),
            stimulus_indices=np.concatenate(
                [b.stimulus_indices for b in batches], axis=0
            ),
        )


def valid_window_starts(
    positions: np.ndarray, pre: int, n_samples: int
) -> np.ndarray:
    """Boolean validity of ``[pos-pre, pos+post)`` windows.

    Java's Arrays.copyOfRange(arr, from, to) throws only when
    from < 0 or from > arr.length; a ``to`` beyond the end ZERO-PADS.
    So windows starting in-range but running past the end are kept,
    zero-padded — only windows starting before 0 or after the end are
    dropped (the reference's swallowed AIOOBE,
    OffLineDataProvider.java:262-264). Shared by the host gather and
    the device-ingest planner so retention can never desynchronize.
    """
    positions = np.asarray(positions, dtype=np.int64)
    return (positions - pre >= 0) & (positions - pre <= n_samples)


def gather_windows(
    channels: np.ndarray,
    positions: np.ndarray,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
) -> Tuple[np.ndarray, np.ndarray]:
    """Gather ``[pos-pre, pos+post)`` windows from full channels.

    channels: (n_channels, n_samples) float64.
    Returns (windows, valid): windows is
    (n_valid, n_channels, pre+post) float64; ``valid`` is a boolean
    mask over the input positions (False = skipped out-of-range, the
    reference's swallowed ArrayIndexOutOfBoundsException).
    """
    n_samples = channels.shape[1]
    positions = np.asarray(positions, dtype=np.int64)
    valid = valid_window_starts(positions, pre, n_samples)
    starts = positions[valid] - pre
    padded = np.pad(channels, ((0, 0), (0, pre + post)))
    idx = starts[:, None] + np.arange(pre + post)[None, :]
    windows = padded[:, idx]  # (n_channels, n_valid, pre+post)
    return np.ascontiguousarray(windows.transpose(1, 0, 2)), valid


def baseline_correct_f32(windows: np.ndarray, pre: int) -> np.ndarray:
    """float32 baseline correction, bit-identical to Baseline.java.

    windows: (..., pre+post) float64. The double values are narrowed to
    float32, the first ``pre`` samples are summed *sequentially* in
    float32 (np.cumsum == the Java left-to-right fold), divided by
    ``pre`` in float32, and subtracted elementwise in float32.
    Returns float32 array of the same shape.
    """
    w32 = windows.astype(np.float32)
    seq_sum = np.cumsum(w32[..., :pre], axis=-1, dtype=np.float32)[..., -1]
    baseline = (seq_sum / np.float32(pre)).astype(np.float32)
    return w32 - baseline[..., None]


class BalanceState:
    """The reference's sequential class-balance filter.

    A target epoch is kept only while ``n_targets <= n_nontargets``; a
    non-target only while ``n_targets >= n_nontargets``
    (OffLineDataProvider.java:248-260). Order-dependent by design —
    a host scan over one boolean per epoch, not device work. The
    counters are instance fields spanning all files of an info.txt run
    (OffLineDataProvider.java:58-59), so balancing is global over the
    whole run, not per file.
    """

    def __init__(self) -> None:
        self.n_targets = 0
        self.n_nontargets = 0

    def scan(self, is_target: np.ndarray) -> np.ndarray:
        from ..io import native

        counters = np.array([self.n_targets, self.n_nontargets], dtype=np.int64)
        keep_native = native.balance_scan(np.asarray(is_target, bool), counters)
        if keep_native is not None:
            self.n_targets = int(counters[0])
            self.n_nontargets = int(counters[1])
            return keep_native
        keep = np.zeros(len(is_target), dtype=bool)
        for i, t in enumerate(is_target):
            if t and self.n_targets <= self.n_nontargets:
                keep[i] = True
                self.n_targets += 1
            elif not t and self.n_targets >= self.n_nontargets:
                keep[i] = True
                self.n_nontargets += 1
        return keep


def extract_epochs(
    channels: np.ndarray,
    markers: Sequence[Marker],
    guessed_number: int,
    pre: int = constants.PRESTIMULUS_SAMPLES,
    post: int = constants.POSTSTIMULUS_SAMPLES,
    balance: BalanceState | None = None,
) -> EpochBatch:
    """channels (n_channels, n_samples) + markers -> balanced epochs.

    Follows the reference per-marker loop (OffLineDataProvider.java:200-265):
    every marker is considered (including non-Stimulus ones, whose
    empty digit-string yields stimulus index -1 and whose position is
    usually out of range), the window is float32 baseline-corrected,
    the label is 1.0 iff stimulus_index + 1 == guessed_number, and the
    global balance scan decides retention.
    """
    from ..io import native

    positions = np.array([m.position for m in markers], dtype=np.int64)
    stim_idx = np.array([m.stimulus_index() for m in markers], dtype=int)

    native_out = native.gather_baseline(
        np.asarray(channels, dtype=np.float64), positions, pre, post
    )
    if native_out is not None:
        epochs, valid = native_out
    else:
        windows, valid = gather_windows(channels, positions, pre, post)
        corrected = baseline_correct_f32(windows, pre)
        # widen to float64 and drop the pre-stimulus prefix (EpochHolder)
        epochs = corrected[..., pre:].astype(np.float64)
    stim_idx = stim_idx[valid]

    is_target = (stim_idx + 1) == guessed_number
    balance = balance or BalanceState()
    keep = balance.scan(is_target)

    return EpochBatch(
        epochs=np.ascontiguousarray(epochs[keep]),
        targets=is_target[keep].astype(np.float64),
        stimulus_indices=stim_idx[keep],
    )
