"""Continuous-EEG sliding-window epocher (the seizure workload's front end).

The marker-locked extractor (``epochs/extractor.py``) answers "what
happened around each stimulus"; epilepsy recordings have no stimuli —
the papers this reproduction tracks (cost-sensitive wavelet mining,
arXiv:2109.13818; DWT seizure prediction, arXiv:2102.01647) slide a
fixed window over the *continuous* signal and label each window from
clinician-annotated seizure **intervals**. This module is that
epocher, producing the same :class:`~..epochs.extractor.EpochBatch`
contract as the marker path so everything downstream — feature
extraction, the feature cache, classifiers, statistics, serving —
works unchanged.

Interval annotation convention (BrainVision-native, no format
extensions): a seizure interval is a pair of ordinary ``.vmrk``
markers of type ``Seizure`` whose description is ``on`` / ``off``::

    Mk12=Seizure,on,84000,1,0
    Mk13=Seizure,off,91500,1,0

Onsets without a matching ``off`` run to the end of the recording
(an annotation cut short by the recording stopping — kept, not
dropped). Non-``Seizure`` markers are ignored, so a continuous
recording may carry stimulus markers too.

Labeling: window ``[s, s+window)`` is positive iff the fraction of
its samples inside any seizure interval is ``>= label_overlap``
(default 0.5 — the window is "mostly seizure"). There is no balance
scan and no baseline correction: class imbalance is the workload's
defining property (the cost-sensitive training knobs exist for it),
and a continuous window has no prestimulus segment to correct
against.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from ..io.brainvision import Marker
from . import extractor

#: the .vmrk marker type that carries interval annotations
SEIZURE_MARKER_KIND = "Seizure"


@dataclasses.dataclass(frozen=True)
class SlidingConfig:
    """One sliding-window epoching configuration.

    ``window``/``stride`` are in samples; ``label_overlap`` is the
    in-interval sample fraction at which a window labels positive.
    """

    window: int = 512
    stride: int = 256
    label_overlap: float = 0.5

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {self.stride}")
        if not (0.0 < self.label_overlap <= 1.0):
            raise ValueError(
                f"label_overlap must be in (0, 1], got {self.label_overlap}"
            )


def seizure_intervals(
    markers: Sequence[Marker], n_samples: int
) -> List[Tuple[int, int]]:
    """Ordered ``[start, end)`` sample intervals from Seizure markers.

    Markers pair in position order: each ``on`` opens an interval, the
    next ``off`` closes it. A dangling ``on`` closes at ``n_samples``;
    an ``off`` with no open interval is ignored with the same
    tolerance the reference shows malformed markers. Intervals are
    clamped to ``[0, n_samples)``.
    """
    events = sorted(
        (
            (m.position, m.stimulus.strip().lower())
            for m in markers
            if m.kind == SEIZURE_MARKER_KIND
        ),
        key=lambda e: e[0],
    )
    out: List[Tuple[int, int]] = []
    open_start = None
    for pos, what in events:
        if what == "on":
            if open_start is None:
                open_start = pos
        elif what == "off" and open_start is not None:
            if pos > open_start:
                out.append(
                    (max(0, open_start), min(int(pos), int(n_samples)))
                )
            open_start = None
    if open_start is not None and open_start < n_samples:
        out.append((max(0, int(open_start)), int(n_samples)))
    return [iv for iv in out if iv[1] > iv[0]]


def window_starts(n_samples: int, window: int, stride: int) -> np.ndarray:
    """Start samples of every FULL window: 0, stride, ... while
    ``start + window <= n_samples`` (a trailing partial window is
    dropped — its feature statistics would not be comparable)."""
    if n_samples < window:
        return np.zeros((0,), dtype=np.int64)
    return np.arange(0, n_samples - window + 1, stride, dtype=np.int64)


def overlap_fractions(
    starts: np.ndarray, window: int, intervals: Sequence[Tuple[int, int]]
) -> np.ndarray:
    """Per-window fraction of samples inside any interval.

    Intervals from :func:`seizure_intervals` are non-overlapping (the
    on/off pairing closes each before the next opens), so per-interval
    overlaps sum without double counting.
    """
    starts = np.asarray(starts, dtype=np.int64)
    covered = np.zeros(starts.shape, dtype=np.float64)
    for lo, hi in intervals:
        overlap = np.minimum(starts + window, hi) - np.maximum(starts, lo)
        covered += np.maximum(overlap, 0)
    return covered / float(window)


def extract_sliding_epochs(
    channels: np.ndarray,
    markers: Sequence[Marker],
    config: SlidingConfig,
) -> extractor.EpochBatch:
    """Continuous channels + interval annotations -> labeled windows.

    ``channels`` is the scaled ``(n_channels, n_samples)`` float64
    matrix (``Recording.read_channels``). Returns an ``EpochBatch``
    whose ``epochs`` are the raw ``(n, n_channels, window)`` slices
    (float64, no baseline correction), ``targets`` the 0/1 interval-
    overlap labels, and ``stimulus_indices`` the window *start
    samples* — the online serving path re-derives the same windows
    from them, which is what keeps batch and served statistics
    identical.
    """
    channels = np.asarray(channels, dtype=np.float64)
    n_samples = channels.shape[1]
    starts = window_starts(n_samples, config.window, config.stride)
    if len(starts) == 0:
        return extractor.EpochBatch(
            epochs=np.zeros(
                (0, channels.shape[0], config.window), dtype=np.float64
            ),
            targets=np.zeros((0,), dtype=np.float64),
            stimulus_indices=np.zeros((0,), dtype=int),
        )
    intervals = seizure_intervals(markers, n_samples)
    fractions = overlap_fractions(starts, config.window, intervals)
    targets = (fractions >= config.label_overlap).astype(np.float64)
    # one strided gather for every window: (n, C, window)
    idx = starts[:, None] + np.arange(config.window)[None, :]
    epochs = np.ascontiguousarray(
        channels[:, idx].transpose(1, 0, 2)
    )
    return extractor.EpochBatch(
        epochs=epochs,
        targets=targets,
        stimulus_indices=starts.astype(int),
    )
