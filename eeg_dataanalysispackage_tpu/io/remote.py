"""Remote filesystems: HTTP(S) and GCS-style object stores.

The reference's entire I/O story runs over a remote filesystem — a
hard-coded HDFS endpoint (``Utils/Const.java:38-42``) dialed by every
data path (``OffLineDataProvider.java:90``,
``HadoopLoadingTest.java:56-119``). The TPU-native equivalent is an
object-store client speaking HTTP: ranged reads (the object-store
analogue of HDFS block reads), bounded retries with exponential
backoff, per-request timeouts, and mid-body resume — the semantics the
Hadoop ``FileSystem``/``DFSInputStream`` stack provides for the
reference.

Everything is stdlib (``http.client``) — no SDK dependency — and the
endpoint is injectable, so hermetic tests drive the full retry/resume
machinery against a local mock server (tests/test_remote_fs.py) and
production points the same code at a real bucket gateway.

URI routing lives here too: :func:`filesystem_for` maps
``http(s)://`` / ``gs://`` / ``file://`` / plain paths onto the right
``io.sources.FileSystem`` implementation, which is how
``info_file=https://...`` works end-to-end through the provider and
pipeline (see ``io/provider.py``).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import random
import time
import urllib.parse
from typing import Optional, Tuple

from ..obs import chaos, events
from . import circuit
from . import deadline as deadline_mod

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry contract for one logical read/write.

    ``max_attempts`` counts tries of each individual request (a chunk
    fetch, a HEAD, a PUT); ``backoff_s`` doubles after every failure up
    to ``max_backoff_s``. ``timeout_s`` is the per-request socket
    timeout — a hung endpoint costs at most
    ``max_attempts * timeout_s + total backoff`` per request, never an
    unbounded stall.

    ``jitter="full"`` opts into full-jitter backoff (uniform over
    ``[0, deterministic wait]``): many workers retrying the same
    recovering endpoint spread out instead of synchronizing their
    backoff waves into periodic thundering herds. The default stays
    deterministic so tests and chaos runs replay exactly.

    The budget is additionally **deadline-aware**: when the calling
    thread carries an ambient :class:`io.deadline.Deadline` (a serving
    request's budget, installed via ``deadline_scope``), the retry
    ladder stops — raising with the attempt history — as soon as the
    remaining budget cannot cover the next backoff sleep. Callers
    without a deadline scope get the classic fixed-attempts behavior.
    """

    max_attempts: int = 4
    timeout_s: float = 20.0
    backoff_s: float = 0.25
    max_backoff_s: float = 4.0
    jitter: str = "none"  # "none" | "full"

    def __post_init__(self):
        if self.jitter not in ("none", "full"):
            raise ValueError(
                f"jitter must be 'none' or 'full', got {self.jitter!r}"
            )

    def sleep_for(self, attempt: int) -> float:
        wait = min(self.backoff_s * (2.0**attempt), self.max_backoff_s)
        if self.jitter == "full":
            return random.uniform(0.0, wait)
        return wait


class RemoteIOError(IOError):
    """A remote request failed after exhausting its retry budget."""


#: statuses worth retrying: transient server/gateway conditions.
_RETRYABLE_STATUSES = (429, 500, 502, 503, 504)

#: redirect statuses followed by the WebHDFS namenode->datanode hops.
_REDIRECT_STATUSES = (301, 302, 303, 307, 308)


class HttpFileSystem:
    """``io.sources.FileSystem`` over HTTP(S) with object-store semantics.

    Reads stream in ``chunk_size`` ranged GETs; each chunk retries
    independently and a connection dying mid-body resumes from the
    bytes already received (``Range: bytes=<got>-``) instead of
    restarting the object. Servers that ignore ``Range`` (status 200)
    are detected on the first chunk and read in one body. 404/410 map
    to ``FileNotFoundError`` so the provider's skip-on-missing behavior
    (``OffLineDataProvider.java:154-161``) works unchanged over remote
    inputs.
    """

    def __init__(
        self,
        base_url: str = "",
        retry: Optional[RetryPolicy] = None,
        chunk_size: int = 4 * 1024 * 1024,
        headers: Optional[dict] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.retry = retry or RetryPolicy()
        self.chunk_size = int(chunk_size)
        self.headers = dict(headers or {})
        # one keep-alive connection per (scheme, netloc), reused across
        # the chunked read loop; dropped on any error or server close.
        # Instances are not thread-safe — use one per worker thread.
        self._conns: dict = {}

    # -- url/connection plumbing ---------------------------------------

    def _split(self, path: str) -> Tuple[str, str, str]:
        """path -> (scheme, netloc, request path)."""
        url = path if "://" in path else f"{self.base_url}/{path.lstrip('/')}"
        parts = urllib.parse.urlsplit(url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"HttpFileSystem cannot handle {url!r}")
        req_path = parts.path or "/"
        if parts.query:
            req_path += "?" + parts.query
        return parts.scheme, parts.netloc, req_path

    def _connect(self, scheme: str, netloc: str) -> http.client.HTTPConnection:
        conn = self._conns.get((scheme, netloc))
        if conn is not None:
            return conn
        cls = (
            http.client.HTTPSConnection
            if scheme == "https"
            else http.client.HTTPConnection
        )
        conn = cls(netloc, timeout=self.retry.timeout_s)
        self._conns[(scheme, netloc)] = conn
        return conn

    def _drop(self, scheme: str, netloc: str) -> None:
        conn = self._conns.pop((scheme, netloc), None)
        if conn is not None:
            conn.close()

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        extra_headers: Optional[dict] = None,
    ):
        """One request with the retry budget; returns (status, headers,
        body bytes or b'' for HEAD). Retries connection errors,
        timeouts, and transient statuses; mid-body drops on GET are
        handled by the caller (it owns resume state).

        The per-endpoint circuit breaker (io/circuit.py) wraps the
        whole budget: when consecutive calls have exhausted their
        retries, ``allow()`` fails fast with the aggregated evidence
        instead of stalling through one more full backoff ladder.

        Deadline awareness (io/deadline.py): when the calling thread
        carries an ambient deadline — a serving request's budget
        threaded down through ``deadline_scope`` — the retry ladder
        stops early the moment the remaining budget cannot cover the
        next backoff sleep, raising with the full attempt history
        instead of sleeping past a deadline the caller already missed.
        A deadline-aborted ladder still records a breaker failure: the
        attempts that did run all failed, and a dead endpoint must not
        stay invisible to the circuit just because its callers are in
        a hurry.
        """
        scheme, netloc, req_path = self._split(path)
        breaker = circuit.breaker_for(f"{scheme}://{netloc}")
        dl = deadline_mod.active_deadline()
        if dl is not None and dl.expired:
            # checked BEFORE breaker.allow(): a spent budget must not
            # claim (and then leak) the breaker's one half-open probe
            # slot — this caller was never going to probe anything
            raise RemoteIOError(
                f"{method} {scheme}://{netloc}{req_path} not attempted: "
                f"deadline budget ({dl.budget_s:.3f}s) already spent"
            )
        breaker.allow()
        last_err: Exception | None = None
        attempt_history: list = []
        for attempt in range(self.retry.max_attempts):
            conn = self._connect(scheme, netloc)
            try:
                # chaos injection: one request attempt dropped — lands
                # in this loop's own retry contract like a real
                # transient (timeout / connection reset / 5xx)
                chaos.maybe_fire("remote.request", RemoteIOError)
                headers = {**self.headers, **(extra_headers or {})}
                conn.request(method, req_path, body=body, headers=headers)
                resp = conn.getresponse()
                status = resp.status
                if status in _RETRYABLE_STATUSES:
                    resp.read()
                    raise RemoteIOError(f"HTTP {status} from {netloc}{req_path}")
                data = b"" if method == "HEAD" else resp.read()
                resp_headers = {k.lower(): v for k, v in resp.getheaders()}
                if resp.will_close:
                    self._drop(scheme, netloc)
                breaker.record_success()
                return status, resp_headers, data
            except (OSError, http.client.HTTPException, RemoteIOError) as e:
                last_err = e
                attempt_history.append(
                    f"attempt {attempt + 1}: {type(e).__name__}: {e}"
                )
                self._drop(scheme, netloc)
                logger.warning(
                    "%s %s attempt %d/%d failed: %s",
                    method,
                    req_path,
                    attempt + 1,
                    self.retry.max_attempts,
                    e,
                )
                # telemetry: each retry attempt is a span event, so a
                # crash/run report shows the retry ladder per request
                events.event(
                    "remote.retry",
                    method=method,
                    path=req_path,
                    attempt=attempt + 1,
                    max_attempts=self.retry.max_attempts,
                    error=f"{type(e).__name__}: {e}",
                )
                if attempt + 1 < self.retry.max_attempts:
                    wait = self.retry.sleep_for(attempt)
                    if dl is not None and not dl.can_cover(wait):
                        # the caller's budget cannot cover the next
                        # backoff: stop the ladder NOW with the whole
                        # attempt history, instead of sleeping past a
                        # deadline the caller has already missed
                        aborted = RemoteIOError(
                            f"{method} {scheme}://{netloc}{req_path} "
                            f"aborted after {attempt + 1}/"
                            f"{self.retry.max_attempts} attempts: "
                            f"deadline budget ({dl.remaining():.3f}s "
                            f"remaining) cannot cover the {wait:.3f}s "
                            f"backoff; attempts: {attempt_history}"
                        )
                        events.event(
                            "remote.deadline_abort",
                            method=method,
                            path=req_path,
                            attempts=attempt + 1,
                            remaining_s=round(dl.remaining(), 4),
                            next_backoff_s=round(wait, 4),
                        )
                        breaker.record_failure(aborted)
                        raise aborted
                    time.sleep(wait)
        exhausted = RemoteIOError(
            f"{method} {scheme}://{netloc}{req_path} failed after "
            f"{self.retry.max_attempts} attempts: {last_err}"
        )
        breaker.record_failure(exhausted)
        raise exhausted

    # -- FileSystem protocol -------------------------------------------

    def exists(self, path: str) -> bool:
        status, _, _ = self._request("HEAD", path)
        if status in (404, 410):
            return False
        if status == 405:  # HEAD not allowed: probe with a 1-byte range
            status, _, _ = self._request(
                "GET", path, extra_headers={"Range": "bytes=0-0"}
            )
            # 416 = object exists but is empty (range unsatisfiable)
            return status in (200, 206, 416)
        return 200 <= status < 300

    def read_bytes(self, path: str) -> bytes:
        got = bytearray()
        total: Optional[int] = None
        while total is None or len(got) < total:
            start = len(got)
            end = start + self.chunk_size - 1
            status, headers, data = self._request(
                "GET", path, extra_headers={"Range": f"bytes={start}-{end}"}
            )
            if status in (404, 410):
                raise FileNotFoundError(path)
            if status == 200:
                # server ignored Range: the body is the whole object
                if start:
                    raise RemoteIOError(
                        f"{path}: server stopped honoring Range mid-read"
                    )
                return data
            if status == 416:
                # any range is unsatisfiable at this offset: empty
                # object (start 0) or EOF under an unknown total
                # ("Content-Range: bytes 0-N/*", RFC 7233)
                break
            if status != 206:
                raise RemoteIOError(f"GET {path}: unexpected HTTP {status}")
            if total is None:
                total = _total_from_content_range(
                    headers.get("content-range", "")
                )
            got.extend(data)
            if not data:
                raise RemoteIOError(f"GET {path}: empty 206 body at {start}")
            if total is None and len(data) < self.chunk_size:
                break  # short chunk under an unknown total: EOF
        return bytes(got)

    def read_range(self, path: str, start: int, length: int) -> bytes:
        """Read ``length`` bytes at ``start`` (object-store block read)."""
        status, _, data = self._request(
            "GET",
            path,
            extra_headers={"Range": f"bytes={start}-{start + length - 1}"},
        )
        if status in (404, 410):
            raise FileNotFoundError(path)
        if status == 200:
            return data[start : start + length]
        if status != 206:
            raise RemoteIOError(f"GET {path}: unexpected HTTP {status}")
        return data

    def read_text(self, path: str) -> str:
        return self.read_bytes(path).decode("utf-8", errors="replace")

    def write_bytes(self, path: str, data: bytes) -> None:
        status, _, _ = self._request("PUT", path, body=data)
        if not 200 <= status < 300:
            raise RemoteIOError(f"PUT {path}: HTTP {status}")


class GcsFileSystem(HttpFileSystem):
    """``gs://bucket/object`` over the GCS XML API.

    Maps bucket/object names onto ``{endpoint}/{bucket}/{object}``
    (the storage.googleapis.com path style). ``endpoint`` is
    injectable for hermetic tests and private gateways; ``token`` adds
    a bearer header for non-public buckets. All transfer semantics
    (ranged chunked reads, retry, resume) come from the HTTP layer.
    """

    def __init__(
        self,
        endpoint: str = "https://storage.googleapis.com",
        token: Optional[str] = None,
        **kwargs,
    ):
        headers = dict(kwargs.pop("headers", {}))
        if token:
            headers["Authorization"] = f"Bearer {token}"
        super().__init__(base_url=endpoint, headers=headers, **kwargs)

    def _split(self, path: str) -> Tuple[str, str, str]:
        if path.startswith("gs://"):
            path = path[len("gs://") :]
        return super()._split(path)


class WebHdfsFileSystem(HttpFileSystem):
    """``hdfs://host:port/path`` over the WebHDFS REST API.

    The reference's storage is literally HDFS — ``Const.java:38-39``
    hard-codes ``hdfs://localhost:8020`` and every data path dials it
    (``OffLineDataProvider.java:90``). This adapter speaks the WebHDFS
    REST protocol (the HTTP face of the same namenode), so
    ``info_file=hdfs://...`` works end-to-end with zero Hadoop client
    dependency:

    - ``GETFILESTATUS`` answers ``exists`` and supplies the object
      length that drives the chunked read loop,
    - ``OPEN`` with ``offset``/``length`` params is the ranged read
      (WebHDFS's native form of the HTTP ``Range`` header),
    - ``CREATE`` is the namenode/datanode two-step: a body-less PUT
      that 307-redirects to the datanode which takes the bytes.

    Redirects are first-class (the namenode redirects OPEN/CREATE to a
    datanode); gateways that answer directly (HttpFS-style, no
    redirect) are handled too. Retry/backoff/timeout semantics are
    inherited per request from :class:`HttpFileSystem`, and a chunk
    body that dies mid-transfer is retried by the same machinery.

    ``endpoint`` overrides the URI authority — real clusters serve
    WebHDFS on the HTTP port (9870), not the RPC port carried in
    ``hdfs://`` URIs (8020); without an override the authority is used
    verbatim, which also lets hermetic tests serve a namenode on
    127.0.0.1. ``user`` adds ``user.name=`` pseudo-authentication.
    Both default from ``WEBHDFS_ENDPOINT`` / ``WEBHDFS_USER`` env
    vars so scheme-routed instances (``filesystem_for`` from
    ``info_file=hdfs://...`` — no kwargs path) can still reach a
    gateway whose HTTP authority differs from the URI's RPC one.
    """

    def __init__(
        self,
        endpoint: Optional[str] = None,
        user: Optional[str] = None,
        api_prefix: str = "/webhdfs/v1",
        **kwargs,
    ):
        super().__init__(base_url="", **kwargs)
        endpoint = endpoint or os.environ.get("WEBHDFS_ENDPOINT")
        self.endpoint = endpoint.rstrip("/") if endpoint else None
        self.user = user or os.environ.get("WEBHDFS_USER")
        self.api_prefix = api_prefix

    # -- URL construction ----------------------------------------------

    def _rest_url(self, path: str, op: str, **params) -> str:
        """hdfs path -> full http REST URL for one operation."""
        if path.startswith("hdfs://"):
            rest = path[len("hdfs://") :]
            authority, _, hpath = rest.partition("/")
            hpath = "/" + hpath
            if not authority and self.endpoint is None:
                # hdfs:///path (Hadoop default-FS form) has no
                # authority to dial — fail fast rather than letting
                # http.client resolve an empty netloc to localhost:80
                raise ValueError(
                    f"{path!r} has no authority; set endpoint= or "
                    f"WEBHDFS_ENDPOINT for default-FS hdfs:/// URIs"
                )
            base = self.endpoint or f"http://{authority}"
        else:
            if self.endpoint is None:
                raise ValueError(
                    f"WebHdfsFileSystem needs an hdfs:// URI or an "
                    f"endpoint=, got {path!r}"
                )
            base = self.endpoint
            hpath = path if path.startswith("/") else "/" + path
        query = {"op": op, **params}
        if self.user:
            query["user.name"] = self.user
        return (
            f"{base}{self.api_prefix}"
            f"{urllib.parse.quote(hpath)}?{urllib.parse.urlencode(query)}"
        )

    def _follow(self, method: str, url: str, body: Optional[bytes] = None):
        """A request plus namenode->datanode redirect hops (each hop
        gets the full retry budget). Relative Location headers (RFC
        7231, emitted by some proxies) resolve against the current
        hop's URL."""
        for _ in range(4):
            status, headers, data = self._request(method, url, body=body)
            if status in _REDIRECT_STATUSES and "location" in headers:
                url = urllib.parse.urljoin(url, headers["location"])
                continue
            return status, headers, data
        raise RemoteIOError(f"{method} {url}: too many redirects")

    # -- FileSystem protocol -------------------------------------------

    def _file_status(self, path: str) -> Optional[dict]:
        status, _, data = self._follow(
            "GET", self._rest_url(path, "GETFILESTATUS")
        )
        if status in (404, 410):
            return None
        if status != 200:
            raise RemoteIOError(f"GETFILESTATUS {path}: HTTP {status}")
        try:
            return json.loads(data)["FileStatus"]
        except (ValueError, KeyError, TypeError) as e:
            # a 200 from something that isn't WebHDFS (captive portal,
            # misrouted gateway) stays inside the module's IOError
            # contract instead of leaking JSONDecodeError/KeyError
            raise RemoteIOError(
                f"GETFILESTATUS {path}: unparseable response "
                f"({data[:80]!r})"
            ) from e

    def exists(self, path: str) -> bool:
        return self._file_status(path) is not None

    def list_dir(self, path: str) -> list:
        """Child entry names of a directory (WebHDFS ``LISTSTATUS``).
        The capability MLlib model-*directory* reads need — object
        stores without listing (plain http, gs ranged-read adapter)
        don't implement this method, which is how callers detect
        support."""
        status, _, data = self._follow(
            "GET", self._rest_url(path, "LISTSTATUS")
        )
        if status in (404, 410):
            raise FileNotFoundError(path)
        if status != 200:
            raise RemoteIOError(f"LISTSTATUS {path}: HTTP {status}")
        try:
            entries = json.loads(data)["FileStatuses"]["FileStatus"]
            return [e["pathSuffix"] for e in entries]
        except (ValueError, KeyError, TypeError) as e:
            raise RemoteIOError(
                f"LISTSTATUS {path}: unparseable response "
                f"({data[:80]!r})"
            ) from e

    def delete_dir(self, path: str) -> None:
        """Recursive delete (WebHDFS ``DELETE`` op). Missing targets
        are fine — the caller wants the path gone, not an error."""
        status, _, _ = self._request(
            "DELETE", self._rest_url(path, "DELETE", recursive="true")
        )
        if status not in (200, 404, 410):
            raise RemoteIOError(f"DELETE {path}: HTTP {status}")

    def read_range(self, path: str, start: int, length: int) -> bytes:
        url = self._rest_url(path, "OPEN", offset=start, length=length)
        status, _, data = self._follow("GET", url)
        if status in (404, 410):
            raise FileNotFoundError(path)
        if status != 200:
            raise RemoteIOError(f"OPEN {path} @{start}: HTTP {status}")
        return data

    def read_bytes(self, path: str) -> bytes:
        st = self._file_status(path)
        if st is None:
            raise FileNotFoundError(path)
        if st.get("type") == "DIRECTORY":
            # LocalFileSystem raises IsADirectoryError for the same
            # mistake; a DIRECTORY status has length 0 and would
            # otherwise silently read as b""
            raise IsADirectoryError(path)
        try:
            total = int(st["length"])
        except (KeyError, ValueError, TypeError) as e:
            raise RemoteIOError(
                f"GETFILESTATUS {path}: malformed FileStatus ({st!r})"
            ) from e
        got = bytearray()
        while len(got) < total:
            n = min(self.chunk_size, total - len(got))
            chunk = self.read_range(path, len(got), n)
            if not chunk:
                raise RemoteIOError(
                    f"OPEN {path}: empty body at offset {len(got)}/{total}"
                )
            got.extend(chunk)
        return bytes(got)

    def write_bytes(self, path: str, data: bytes) -> None:
        url = self._rest_url(path, "CREATE", overwrite="true")
        # Step 1: body-less PUT to the namenode; it answers 307 with
        # the datanode location that takes the bytes (the WebHDFS
        # CREATE contract). HttpFS-style gateways skip the redirect
        # and take the body directly on a second PUT to the same URL.
        status, headers, _ = self._request("PUT", url)
        if status in _REDIRECT_STATUSES and "location" in headers:
            # _follow handles further hops (HA proxy -> namenode ->
            # datanode chains) and relative Locations
            status2, _, _ = self._follow(
                "PUT", urllib.parse.urljoin(url, headers["location"]), body=data
            )
            if not 200 <= status2 < 300:
                raise RemoteIOError(f"CREATE {path} (data): HTTP {status2}")
        elif 200 <= status < 300:
            status2, _, _ = self._request(
                "PUT",
                self._rest_url(path, "CREATE", overwrite="true", data="true"),
                body=data,
                extra_headers={"Content-Type": "application/octet-stream"},
            )
            if not 200 <= status2 < 300:
                # the gateway 2xx-accepted the body-less step-1 CREATE
                # (overwrite=true rides step 1 because the real
                # namenode protocol consumes it there), so the target
                # may already be truncated — say so rather than leave
                # a later empty read() as the only clue
                raise RemoteIOError(
                    f"CREATE {path} (direct): HTTP {status2}; target "
                    f"may be left truncated by the accepted step-1 "
                    f"CREATE"
                )
        else:
            raise RemoteIOError(f"CREATE {path}: HTTP {status}")


def _hadoop_connect(host: str, port: int, user: Optional[str]):
    """Open a pyarrow libhdfs connection (module-level seam so tests
    can fake the native layer without a Hadoop install)."""
    try:
        from pyarrow import fs as pafs
    except ImportError as e:
        raise RemoteIOError(
            "HDFS_DRIVER=native needs pyarrow; unset it to use the "
            "zero-dependency WebHDFS driver"
        ) from e
    try:
        return pafs.HadoopFileSystem(host, port=port, user=user)
    except (OSError, RuntimeError) as e:
        raise RemoteIOError(
            f"native HDFS connect to {host}:{port} failed ({e}); the "
            f"libhdfs runtime (libhdfs.so + CLASSPATH from a Hadoop "
            f"install) must be present — or unset HDFS_DRIVER to use "
            f"WebHDFS"
        ) from e


class NativeHdfsFileSystem:
    """``hdfs://host:port/path`` over the native Hadoop RPC protocol.

    The reference dials this exact wire protocol: ``Const.java:38-42``
    hard-codes ``hdfs://localhost:8020`` (the RPC port) and
    ``OffLineDataProvider.java:90`` opens files through the Java
    DFSClient. :class:`WebHdfsFileSystem` covers the same namenode via
    its HTTP face, but clusters with WebHDFS disabled are unreachable
    that way (VERDICT r4 missing item 2) — this adapter reaches them
    through pyarrow's libhdfs binding, which speaks the real
    protobuf/SASL RPC protocol via the vendored Hadoop native client.

    Selected per process with ``HDFS_DRIVER=native`` (the default
    stays WebHDFS: zero native dependencies). Needs ``libhdfs.so``
    and a Hadoop classpath at runtime; a missing runtime raises a
    :class:`RemoteIOError` naming the fix instead of an opaque
    loader error. ``hdfs:///path`` (default-FS form) dials the
    ``fs.defaultFS`` from the node's own Hadoop config, exactly like
    the Java client. Connections are cached per authority.
    """

    def __init__(self, user: Optional[str] = None):
        self.user = user or os.environ.get("HDFS_USER")
        self._conns: dict = {}

    @staticmethod
    def _split(path: str) -> tuple:
        if not path.startswith("hdfs://"):
            raise ValueError(
                f"NativeHdfsFileSystem needs an hdfs:// URI, got {path!r}"
            )
        rest = path[len("hdfs://") :]
        authority, _, hpath = rest.partition("/")
        return authority, "/" + hpath

    def _fs(self, authority: str):
        if authority not in self._conns:
            if authority:
                host, _, port = authority.partition(":")
                port_n = int(port) if port else 8020
            else:
                # hdfs:/// -> libhdfs "default": fs.defaultFS from the
                # local Hadoop configuration
                host, port_n = "default", 0
            self._conns[authority] = _hadoop_connect(
                host, port_n, self.user
            )
        return self._conns[authority]

    # -- FileSystem protocol -------------------------------------------

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs

        authority, hpath = self._split(path)
        info = self._fs(authority).get_file_info([hpath])[0]
        return info.type != pafs.FileType.NotFound

    def read_bytes(self, path: str) -> bytes:
        from pyarrow import fs as pafs

        authority, hpath = self._split(path)
        fs = self._fs(authority)
        info = fs.get_file_info([hpath])[0]
        if info.type == pafs.FileType.NotFound:
            raise FileNotFoundError(path)
        if info.type == pafs.FileType.Directory:
            raise IsADirectoryError(path)
        with fs.open_input_stream(hpath) as f:
            return f.read()

    def read_text(self, path: str) -> str:
        return self.read_bytes(path).decode("utf-8", errors="replace")

    def list_dir(self, path: str) -> list:
        """Child entry names (same contract as
        ``WebHdfsFileSystem.list_dir``)."""
        from pyarrow import fs as pafs

        authority, hpath = self._split(path)
        infos = self._fs(authority).get_file_info(
            pafs.FileSelector(hpath, recursive=False)
        )
        return [os.path.basename(i.path) for i in infos]

    def delete_dir(self, path: str) -> None:
        """Recursive delete; missing targets are fine (same contract
        as ``WebHdfsFileSystem.delete_dir``)."""
        authority, hpath = self._split(path)
        try:
            self._fs(authority).delete_dir(hpath)
        except FileNotFoundError:
            pass

    def write_bytes(self, path: str, data: bytes) -> None:
        authority, hpath = self._split(path)
        with self._fs(authority).open_output_stream(hpath) as f:
            f.write(data)


def _total_from_content_range(value: str) -> Optional[int]:
    # "bytes 0-1048575/31719424" -> 31719424
    if "/" in value:
        tail = value.rsplit("/", 1)[1]
        if tail.isdigit():
            return int(tail)
    return None


def filesystem_for(path: str, **kwargs):
    """URI scheme -> FileSystem instance (the Const.java endpoint
    selection, made pluggable).

    ``http(s)://`` -> :class:`HttpFileSystem`; ``gs://`` ->
    :class:`GcsFileSystem`; ``hdfs://`` -> :class:`WebHdfsFileSystem`
    (the reference's actual scheme — Const.java:38-39), or
    :class:`NativeHdfsFileSystem` (real Hadoop RPC, for clusters with
    WebHDFS disabled) when ``HDFS_DRIVER=native``; ``file://`` and
    plain paths -> local POSIX. The returned filesystem accepts
    the original URI form in every call, so callers can thread one
    (fs, path) pair everywhere.
    """
    from . import sources

    if path.startswith(("http://", "https://")):
        return HttpFileSystem(**kwargs)
    if path.startswith("gs://"):
        return GcsFileSystem(**kwargs)
    if path.startswith("hdfs://"):
        driver = os.environ.get("HDFS_DRIVER", "webhdfs").strip().lower()
        if driver == "native":
            return NativeHdfsFileSystem(
                **{k: v for k, v in kwargs.items() if k == "user"}
            )
        if driver != "webhdfs":
            raise ValueError(
                f"HDFS_DRIVER must be 'webhdfs' or 'native', "
                f"got {driver!r}"
            )
        return WebHdfsFileSystem(**kwargs)
    return sources.LocalFileSystem()
