"""DL4J model-zip ARCHITECTURE import (the open half of the NN format).

The reference saves its neural nets with DL4J's ``ModelSerializer``
(NeuralNetworkClassifier.java:171-176): a zip whose
``coefficients.bin`` wraps ND4J's closed native array serialization
(weights NOT importable — documented out of scope,
io/mllib_format.py docstring) but whose ``configuration.json`` is
plain Jackson JSON of the ``MultiLayerConfiguration`` the classifier
built from its ``config_*`` keys (NeuralNetworkClassifier.java:
96-130, 258-320). This module inverts that mapping: it reads the
JSON (from the zip or a bare file) and reconstructs the ``config_*``
dictionary, so a reference deployment's NN *architecture* ports in
one call and retrains on this framework::

    cfg = import_dl4j_architecture("model.zip")
    clf = registry.create("nn"); clf.set_config(cfg); clf.fit(X, y)

Parsing is deliberately tolerant across DL4J 0.x serialization
variants (the reference pins 0.8.0, pom.xml:105-108, but field
encodings shifted between 0.x releases): layer type from the
one-key wrapper object (``{"dense": {...}}``) or an ``@class`` tag;
activation from an ``activationFn`` ``@class`` (0.7+) or a bare
``activationFunction`` string (pre-0.7); enum-ish values normalized
case-insensitively. Anything that does not look like a
MultiLayerConfiguration raises with a pointer to what was found.
"""

from __future__ import annotations

import json
import re
import zipfile
from typing import Dict, Optional

#: JSON spellings -> the reference's config_layer*_layer_type values
#: (NeuralNetworkClassifier.java:269-312)
_LAYER_TYPES = {
    "output": "output",
    "outputlayer": "output",
    "dense": "dense",
    "denselayer": "dense",
    "autoencoder": "auto_encoder",
    "rbm": "rbm",
    "graveslstm": "graves_lstm",
}

_ACTIVATIONS = {
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "relu": "relu",
    "tanh": "tanh",
    "identity": "identity",
    "softplus": "softplus",
    "elu": "elu",
}

_LOSSES = {
    "mse": "mse",
    "mcxent": "xent",
    "xent": "xent",
    "binaryxent": "xent",
    "squaredloss": "squared_loss",
    "l2": "squared_loss",
    "negativeloglikelihood": "negativeloglikelihood",
}

_UPDATERS = {
    "sgd": "sgd",
    "adam": "adam",
    "nesterovs": "nesterovs",
    "adagrad": "adagrad",
    "rmsprop": "rmsprop",
}

_OPT_ALGOS = {
    "stochasticgradientdescent": "stochastic_gradient_descent",
    "linegradientdescent": "line_gradient_descent",
    "conjugategradient": "conjugate_gradient",
    "lbfgs": "lbfgs",
}

_WEIGHT_INITS = {
    "xavier": "xavier",
    "zero": "zero",
    "sigmoid": "sigmoid",
    "sigmoiduniform": "sigmoid",
    "uniform": "uniform",
    "relu": "relu",
}


def _squash(name: str) -> str:
    """'ActivationReLU' / 'GRAVES_LSTM' / 'relu' -> comparable key."""
    return re.sub(r"[^a-z0-9]", "", name.lower())


def _enum(value, table: Dict[str, str], kind: str) -> Optional[str]:
    """Normalize a JSON enum-ish value through a spelling table.
    Accepts raw strings, ``{"@class": "...impl.ActivationSigmoid"}``
    wrappers, and DL4J class-name prefixes (``Activation``/``Loss``/
    ``WeightInit``)."""
    if value is None:
        return None
    if isinstance(value, dict):
        value = value.get("@class", "")
        value = value.rsplit(".", 1)[-1]
    s = _squash(str(value))
    for prefix in ("activation", "loss", "weightinit", "updater"):
        if s.startswith(prefix) and s[len(prefix):] in table:
            s = s[len(prefix):]
            break
    if s in table:
        return table[s]
    raise ValueError(f"unrecognized DL4J {kind}: {value!r}")


def read_configuration_json(path: str) -> dict:
    """The ``configuration.json`` document from a ModelSerializer zip
    (any entry name containing 'configuration'), or from a bare JSON
    file."""
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            names = [
                n for n in z.namelist() if "configuration" in n.lower()
            ]
            if not names:
                raise ValueError(
                    f"{path} is a zip without a configuration.json "
                    f"entry (found: {z.namelist()[:6]}) — not a DL4J "
                    f"ModelSerializer archive"
                )
            return json.loads(z.read(names[0]).decode("utf-8"))
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _layer_of(conf: dict) -> tuple:
    """(layer_type, layer_fields) from one entry of ``confs``.

    0.x encodings: ``conf["layer"]`` is either a one-key wrapper
    ``{"dense": {...}}`` or a flat dict with an ``@class`` tag."""
    layer = conf.get("layer")
    if not isinstance(layer, dict) or not layer:
        raise ValueError(
            f"conf entry has no layer object (keys: {sorted(conf)})"
        )
    if "@class" in layer:
        cls = layer["@class"].rsplit(".", 1)[-1]
        key = _squash(cls)
        fields = layer
    elif len(layer) == 1:
        (key, fields), = layer.items()
        key = _squash(key)
        if not isinstance(fields, dict):
            raise ValueError(f"layer wrapper {key!r} holds no fields")
    else:
        # some 0.x builds inline the fields next to a "type" tag
        key = _squash(str(layer.get("type", "")))
        fields = layer
    if key not in _LAYER_TYPES:
        raise ValueError(
            f"unrecognized DL4J layer type {key!r} (supported: "
            f"{sorted(set(_LAYER_TYPES.values()))})"
        )
    return _LAYER_TYPES[key], fields


def _field(fields: dict, *names, default=None):
    for n in names:
        if n in fields and fields[n] is not None:
            return fields[n]
    return default


def import_dl4j_architecture(path: str) -> Dict[str, str]:
    """DL4J zip / configuration.json -> the reference's ``config_*``
    dictionary (NeuralNetworkClassifier's full key surface), ready
    for ``NeuralNetworkClassifier.set_config``. Weights are NOT
    imported (closed ND4J serialization) — retrain after porting."""
    doc = read_configuration_json(path)
    confs = doc.get("confs")
    if not isinstance(confs, list) or not confs:
        raise ValueError(
            f"not a MultiLayerConfiguration: no 'confs' list "
            f"(top-level keys: {sorted(doc)[:8]})"
        )

    cfg: Dict[str, str] = {}
    first = confs[0]
    # globals live on the per-layer NeuralNetConfiguration clones;
    # the first conf is authoritative (the builder applied them
    # uniformly — NeuralNetworkClassifier.java:96-120)
    seed = _field(first, "seed")
    if seed is not None:
        cfg["config_seed"] = str(int(seed))
    iters = _field(first, "numIterations", "iterationCount", "iterations")
    if iters is not None:
        cfg["config_num_iterations"] = str(int(iters))
    algo = _field(first, "optimizationAlgo", "optimizationAlgorithm")
    if algo is not None:
        cfg["config_optimization_algo"] = _enum(
            algo, _OPT_ALGOS, "optimization algo"
        )
    for flag in ("pretrain", "backprop"):
        if isinstance(doc.get(flag), bool):
            cfg[f"config_{flag}"] = "true" if doc[flag] else "false"

    loss = None
    for i, conf in enumerate(confs, start=1):
        ltype, fields = _layer_of(conf)
        cfg[f"config_layer{i}_layer_type"] = ltype
        n_out = _field(fields, "nout", "nOut")
        if n_out is None:
            raise ValueError(f"layer {i} ({ltype}) has no nOut")
        cfg[f"config_layer{i}_n_out"] = str(int(n_out))
        cfg[f"config_layer{i}_drop_out"] = str(
            float(_field(fields, "dropOut", "dropout", default=0.0))
        )
        act = _field(fields, "activationFn", "activationFunction",
                     "activation")
        cfg[f"config_layer{i}_activation_function"] = (
            _enum(act, _ACTIVATIONS, "activation")
            if act is not None
            else "sigmoid"
        )
        lf = _field(fields, "lossFn", "lossFunction", "loss")
        if lf is not None:
            loss = _enum(lf, _LOSSES, "loss")
        # training globals: 0.7+ clones them onto each LAYER; pre-0.7
        # keeps them on the conf object — read both homes (layer
        # first), first occurrence wins
        upd = _field(fields, "updater") or _field(conf, "updater")
        if upd is not None and "config_updater" not in cfg:
            cfg["config_updater"] = _enum(upd, _UPDATERS, "updater")
        lr = _field(fields, "learningRate")
        if lr is None:
            lr = _field(conf, "learningRate")
        if lr is not None and "config_learning_rate" not in cfg:
            cfg["config_learning_rate"] = str(float(lr))
        mom = _field(fields, "momentum")
        if mom is None:
            mom = _field(conf, "momentum")
        if mom is not None and "config_momentum" not in cfg:
            cfg["config_momentum"] = str(float(mom))
        wi = _field(fields, "weightInit") or _field(conf, "weightInit")
        if wi is not None and "config_weight_init" not in cfg:
            cfg["config_weight_init"] = _enum(
                wi, _WEIGHT_INITS, "weight init"
            )
    if loss is not None:
        cfg["config_loss_function"] = loss
    return cfg
