"""I/O layer shared knobs.

``EEG_TPU_PREFETCH_DEPTH`` is one knob for both sides of the input
pipeline — the provider's host-parse look-ahead (io/provider) and the
staged-batch buffer default (io/staging.prefetch). This module is its
single source, so the two consumers cannot desynchronize.
"""

from __future__ import annotations

import os

ENV_PREFETCH_DEPTH = "EEG_TPU_PREFETCH_DEPTH"
_DEFAULT_PREFETCH_DEPTH = 2


def env_int(name: str, default: int) -> int:
    """Positive-int env knob; unset/garbage resolves ``default``."""
    try:
        return max(1, int(os.environ.get(name, "")))
    except ValueError:
        return default


def default_prefetch_depth() -> int:
    """``EEG_TPU_PREFETCH_DEPTH``, else 2 (classic double buffering)."""
    return env_int(ENV_PREFETCH_DEPTH, _DEFAULT_PREFETCH_DEPTH)
