"""Model persistence bytes, routed through the pluggable filesystem.

The reference's classifiers save/load models on the cluster
filesystem (``model.save(sc, path)`` onto HDFS —
LogisticRegressionClassifier.java:144-152, ModelSerializer at
NeuralNetworkClassifier.java:171-187). Here every classifier
serializes to bytes and hands them to this module, so
``save_clf``/``load_clf`` work identically for local paths
(``file://`` tolerated) and remote URIs (``http(s)://``, ``gs://`` —
io/remote.py, with its retry/backoff semantics).

This module only moves bytes; reference-parity quirks that belong to
specific classifiers (the npz models delete a directory at the raw
save target first — LogisticRegressionClassifier.java:144-147) stay
at those call sites via :func:`delete_local_dir_target`.
"""

from __future__ import annotations

import os

from . import sources


def _fs_for(path: str):
    """Single source of scheme dispatch: io/remote.filesystem_for
    (so a scheme added there is automatically supported here)."""
    from . import remote

    return remote.filesystem_for(path)


def _is_local(path: str) -> bool:
    return isinstance(_fs_for(path), sources.LocalFileSystem)


def delete_local_dir_target(path: str) -> None:
    """Reference parity for the MLlib-style savers: delete an
    existing *directory* at the raw (un-suffixed) save target
    (LogisticRegressionClassifier.java:144-147). No-op for remote
    URIs and non-directories."""
    if not _is_local(path):
        return
    local = sources.LocalFileSystem._strip(path)
    if os.path.isdir(local):
        import shutil

        shutil.rmtree(local)


def write_model_bytes(path: str, data: bytes) -> None:
    """Write serialized model bytes to a local path or remote URI.

    Local writes create parent directories; they never delete
    existing entries (a directory at the target errors loudly —
    see :func:`delete_local_dir_target` for the savers that want the
    reference's delete-first quirk).
    """
    fs = _fs_for(path)
    if isinstance(fs, sources.LocalFileSystem):
        local = fs._strip(path)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
    fs.write_bytes(path, data)


def read_model_bytes(path: str) -> bytes:
    """Read serialized model bytes from a local path or remote URI.

    Raises ``FileNotFoundError`` for missing objects on either side
    (the remote layer maps 404 onto it already)."""
    return _fs_for(path).read_bytes(path)
