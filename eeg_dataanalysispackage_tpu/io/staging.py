"""Host->device staging: double-buffered prefetch of epoch batches.

The reference materializes its whole dataset as RDDs up front
(``sc.parallelize(epochs)``, LogisticRegressionClassifier.java:87-88)
and Spark's laziness hides the staging cost inside each job. The
TPU-native input pipeline instead overlaps host work (file parsing,
epoching, padding) with device compute explicitly: a background thread
pulls host batches from an iterator, stages each onto the device(s)
with ``jax.device_put`` — an async dispatch, so the copy itself
overlaps the consumer's current step — and hands them over through a
small bounded buffer (SURVEY.md section 7 stage 6: "double-buffered
device_put prefetch").

Typical use::

    batches = staging.minibatches(epochs, targets, batch_size=1024)
    for ep, lb, mask in staging.prefetch(batches, mesh=mesh):
        state, loss = train_step(state, ep, lb, mask)
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Iterable, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np

from . import ENV_PREFETCH_DEPTH  # noqa: F401  (re-export: the knob's name)
from . import default_prefetch_depth
from ..obs import chaos, domain as run_domain, events
from ..parallel import mesh as pmesh

logger = logging.getLogger(__name__)

_END = object()

#: default in-flight staged-batch bound when the caller does not pass
#: ``buffer_size`` explicitly — the shared ``EEG_TPU_PREFETCH_DEPTH``
#: knob (io/__init__), same source as io/provider's host-parse
#: look-ahead.
default_buffer_size = default_prefetch_depth


class _Poison:
    """A producer exception in transit to the consumer."""

    def __init__(self, error: BaseException):
        self.error = error


def minibatches(
    *arrays: np.ndarray,
    batch_size: int,
    drop_remainder: bool = False,
) -> Iterator[Tuple[np.ndarray, ...]]:
    """Slice aligned host arrays into leading-axis minibatches."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")
    n = arrays[0].shape[0]
    for a in arrays[1:]:
        if a.shape[0] != n:
            raise ValueError(
                f"misaligned batch arrays: {a.shape[0]} vs {n} rows"
            )
    for start in range(0, n, batch_size):
        if drop_remainder and start + batch_size > n:
            return
        yield tuple(a[start : start + batch_size] for a in arrays)


#: how often the consumer's blocking get wakes to check producer
#: liveness — the watchdog that keeps a dead producer from hanging a
#: consumer forever (a producer that dies WITHOUT delivering its
#: poison sentinel, e.g. killed by a failure inside its own failure
#: path, would otherwise leave the consumer blocked on an empty queue)
_WATCHDOG_POLL_S = 1.0


class ProducerDiedError(RuntimeError):
    """The prefetch producer thread died without delivering its
    end-of-stream or poison sentinel; the consumer fails fast instead
    of blocking on the queue forever."""


def prefetch(
    batches: Iterable[Sequence[np.ndarray]],
    mesh=None,
    buffer_size: Optional[int] = None,
    with_mask: bool = True,
    watchdog_poll_s: float = _WATCHDOG_POLL_S,
    stage_fn=None,
) -> Iterator[Tuple[jax.Array, ...]]:
    """Stage host batches onto device(s) ahead of consumption.

    Each yielded element is the input tuple staged with
    ``jax.device_put`` — committed to the default device when ``mesh``
    is None, or padded + sharded over the mesh's data axis (with a
    trailing validity mask appended when ``with_mask``, the
    ``mesh.shard_batch_with_mask`` convention) otherwise.

    ``stage_fn`` replaces the built-in device_put staging entirely:
    the producer thread calls ``stage_fn(item)`` per source item and
    yields its result — the seam the double-buffered ingest/compute
    overlap rides (the fn stages AND dispatches recording K+1's
    decode+featurize program while the consumer runs recording K's
    step). Everything else — the bounded buffer, poison/stop
    semantics, the consumer watchdog, and the ``staging.producer``
    chaos point — applies to a ``stage_fn`` producer unchanged, which
    is exactly why overlap is built on this function instead of a
    second thread loop.

    ``buffer_size`` bounds how many staged batches may be in flight;
    None resolves ``EEG_TPU_PREFETCH_DEPTH`` (default 2 = classic
    double buffering). Exceptions raised by the source iterator or by
    staging surface at the consumer, not in the thread.
    """
    if buffer_size is None:
        buffer_size = default_buffer_size()
    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")

    def stage(batch: Sequence[np.ndarray]) -> Tuple[jax.Array, ...]:
        if stage_fn is not None:
            return stage_fn(batch)
        if mesh is None:
            return tuple(jax.device_put(np.asarray(a)) for a in batch)
        if with_mask:
            return pmesh.shard_batch_with_mask(mesh, *batch)
        return tuple(
            pmesh.shard_batch(np.asarray(a), mesh)[0] for a in batch
        )

    buf: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    # the producer's failure slot: set BEFORE attempting delivery, so
    # a fault can never vanish silently — if the poisoned sentinel
    # never reaches the consumer (it stopped first / the queue stayed
    # full), the finally block below still sees and logs it
    failure: dict = {"error": None, "delivered": False, "logged": False}

    def _put_stop_aware(item) -> bool:
        """Poll the put so an abandoned consumer never wedges the
        producer thread; returns False when stop cut the delivery."""
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # the spawner's per-plan fault domain, adopted by the producer
    # thread: the staging.producer chaos point and every span/metric
    # the producer records stay inside the RIGHT plan when a
    # multi-tenant executor runs several plans at once
    domain = run_domain.capture()

    def producer() -> None:
        staged_n = 0
        # telemetry: the producer thread's lifetime is one span
        # (parented on the run root — its own thread); batch count
        # lands as an attribute, and the error event is emitted
        # INSIDE the span so the flight recorder attributes the
        # failure to staging.producer, not the run root
        with run_domain.adopt(domain), events.span(
            "staging.producer"
        ) as _span_rec:
            try:
                for batch in batches:
                    if stop.is_set():
                        return
                    # chaos injection: one staged batch fails (a
                    # poisoned device_put / host parse) — must surface
                    # at the consumer, never drop silently
                    chaos.maybe_fire("staging.producer")
                    staged = stage(batch)
                    staged_n += 1
                    if _span_rec is not None:
                        _span_rec["attrs"]["batches"] = staged_n
                    # re-check after the (possibly long) staging call
                    if not _put_stop_aware(staged):
                        return
            except BaseException as e:  # re-raised at the consumer
                # "delivered" is set by the CONSUMER on receipt — a
                # poison that entered the queue but was never read
                # (the consumer closed first) still counts as
                # undelivered and gets logged on join
                failure["error"] = e
                events.event(
                    "staging.producer_error",
                    error=f"{type(e).__name__}: {e}",
                    batches_staged=staged_n,
                )
                if not _put_stop_aware(_Poison(e)):
                    # delivery aborted (consumer already stopped) —
                    # log HERE too: a producer stranded past the
                    # consumer's join budget fails after the consumer-
                    # side check ran, and its error must not evaporate
                    failure["logged"] = True
                    logger.warning(
                        "prefetch producer failed after the consumer "
                        "stopped (%s: %s); error was never delivered",
                        type(e).__name__, e,
                    )
                return
            _put_stop_aware(_END)

    thread = threading.Thread(
        target=producer, name="eeg-tpu-prefetch", daemon=True
    )
    thread.start()
    try:
        while True:
            # timed get + producer-liveness check: the consumer-side
            # watchdog. A producer that dies without delivering _END
            # or a _Poison (its own failure path failed) must surface
            # as an error at the consumer, never as an infinite block.
            try:
                item = buf.get(timeout=watchdog_poll_s)
            except queue.Empty:
                if thread.is_alive():
                    continue  # producer is just slow (staging a batch)
                try:
                    # close the race where the producer delivered its
                    # final item between our timeout and the liveness
                    # check, then exited
                    item = buf.get_nowait()
                except queue.Empty:
                    events.event(
                        "staging.producer_dead",
                        thread=thread.name,
                    )
                    logger.error(
                        "prefetch producer thread %s died without "
                        "delivering end-of-stream; failing the "
                        "consumer fast", thread.name,
                    )
                    raise ProducerDiedError(
                        "staging producer thread died without "
                        "delivering end-of-stream or an error; the "
                        "batch source may have failed outside the "
                        "producer's own failure handling"
                    )
            if item is _END:
                return
            if isinstance(item, _Poison):
                failure["delivered"] = True
                raise item.error
            yield item
    finally:
        # consumer stopped (exhaustion, error, or early close): tell
        # the producer to quit at its next check rather than staging
        # the rest of the source
        stop.set()
        thread.join(timeout=5.0)
        if thread.is_alive():
            # a wedged device_put (or similar) stranded the daemon
            # thread past the join budget — say so instead of leaking
            # it invisibly
            logger.warning(
                "prefetch producer thread %s still alive after 5s "
                "join; abandoning it (daemon)", thread.name
            )
        err = failure["error"]
        if err is not None and not failure["delivered"] and not failure["logged"]:
            # the poisoned sentinel entered the queue but the consumer
            # exited without reading it — the failure must not
            # evaporate (the producer logs its own put-aborted case)
            logger.warning(
                "prefetch producer failed after the consumer stopped "
                "(%s: %s); error was never delivered",
                type(err).__name__, err,
            )


def prefetch_epochs(
    epochs: np.ndarray,
    targets: np.ndarray,
    batch_size: int,
    mesh=None,
    buffer_size: Optional[int] = None,
) -> Iterator[Tuple[jax.Array, ...]]:
    """Convenience: ``minibatches`` + ``prefetch`` over an epoch set,
    the staged-input form consumed by ``parallel.train.make_train_step``
    and ``checkpoint.run_resumable``."""
    return prefetch(
        minibatches(
            np.asarray(epochs, np.float32),
            np.asarray(targets, np.float32),
            batch_size=batch_size,
        ),
        mesh=mesh,
        buffer_size=buffer_size,
    )
