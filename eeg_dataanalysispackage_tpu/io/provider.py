"""Offline data provider: info.txt / .eeg inputs -> balanced epoch batch.

TPU-first re-design of ``DataTransformation/OffLineDataProvider.java``:
instead of a stateful loader mutating epoch lists per marker, files are
parsed on the host into dense ``(n, 3, 750)`` arrays ready for device
staging. Multi-file runs parse their triplets in a bounded thread pool
with an order-preserving merge (``_iter_recordings``), overlapping the
next files' host parse with the current file's epoching / device
work — bit-identical output at any pool size. Input-contract parity:

- args ``[<info.txt path>]`` or ``[<.eeg path>, <guessed number>]``
  (OffLineDataProvider.java:111-141);
- info.txt entries are resolved against the info.txt's directory
  (``filePrefix`` — :129);
- duplicate info.txt entries collapse, first-seen order, last guess
  wins (LinkedHashMap semantics — :53, :308);
- files whose .vhdr/.vmrk/.eeg sibling is missing are skipped with a
  log, not fatal (:154-161);
- channels named fz/cz/pz (case-insensitive) are selected (:172-183);
- the balance counters span all files of a run (:58-59).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import logging
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import brainvision, sources
from . import ENV_PREFETCH_DEPTH, default_prefetch_depth, env_int  # noqa: F401
from ..epochs import extractor
from ..utils import constants

logger = logging.getLogger(__name__)

#: parse-pool size for multi-file runs (``EEG_TPU_INGEST_WORKERS``
#: overrides; a pipeline query overrides per run via ``ingest_workers=``).
#: The decoded look-ahead beyond the in-flight parses is the shared
#: ``EEG_TPU_PREFETCH_DEPTH`` knob (io/__init__ — one source for the
#: provider look-ahead and io/staging's staged-batch buffer).
ENV_INGEST_WORKERS = "EEG_TPU_INGEST_WORKERS"
_DEFAULT_INGEST_WORKERS = 4


def default_ingest_workers() -> int:
    """Parse-pool size when the caller does not pin one: the env
    override, else min(4, cpu count) — file parsing is I/O plus numpy
    demux, both of which release the GIL, but past a few workers the
    ordered merge is the bottleneck, not parsing."""
    if os.environ.get(ENV_INGEST_WORKERS):
        return env_int(ENV_INGEST_WORKERS, _DEFAULT_INGEST_WORKERS)
    return min(_DEFAULT_INGEST_WORKERS, os.cpu_count() or 1)

#: the backend degradation ladder for fused device ingest, fastest
#: first: decode (slice-scan window cut on CPU / VMEM bank kernel on
#: accelerators — ops/decode_ingest.py) -> Pallas kernel -> block
#: (alignment-classed matmul) -> XLA element gather -> host epochs +
#: registry extractor. Each rung produces the same features
#: (tolerance-level numerics), so stepping down trades speed for
#: survival, never correctness.
FUSED_DEGRADATION_LADDER = ("decode", "pallas", "block", "xla", "host")

#: env opt-in for double-buffered ingest/compute overlap: the fused
#: featurization of recording K+1 runs on a staging producer thread
#: (io/staging.prefetch with a featurize ``stage_fn``) while the
#: consumer collects recording K — bit-identical epoch order and
#: statistics, overlap-on vs off (pinned). The ``overlap=`` query
#: parameter overrides per run.
ENV_OVERLAP = "EEG_TPU_OVERLAP"


def default_overlap() -> bool:
    """``EEG_TPU_OVERLAP=1`` turns the overlapped fused-ingest path on
    process-wide (a per-run ``overlap=`` query wins either way)."""
    return os.environ.get(ENV_OVERLAP) == "1"


def degradation_ladder(backend: str):
    """Backends to try, in order, starting from ``backend``.

    ``decode`` -> ``["decode", "pallas", "block", "xla", "host"]``;
    ``xla`` -> ``["xla", "host"]``. The terminal ``"host"`` rung is
    not a ``load_features_device`` backend — it signals the caller
    (pipeline/builder.py) to fall back to host epoch loading plus the
    registry feature extractor.
    """
    if backend not in FUSED_DEGRADATION_LADDER[:-1]:
        raise ValueError(f"unknown device-ingest backend {backend!r}")
    return list(
        FUSED_DEGRADATION_LADDER[FUSED_DEGRADATION_LADDER.index(backend):]
    )


# -- precision-gate memo -------------------------------------------------
# The gate decision is pure (content bytes x geometry x resolved
# tolerance -> record), but the double-featurize behind it costs two
# extra compiled programs + a featurize pass — measured as the bulk of
# pipeline_e2e_bf16's deficit vs the f32 cold run (BENCH_pr8: 685 vs
# 949 eps). Memoizing per content digest hoists that cost off every
# re-gating of the same session in one process (warm re-runs, the
# multi-tenant executor's N plans over one recording set). Bounded
# LRU; thread-safe (the executor gates from worker threads).
_GATE_MEMO: "collections.OrderedDict" = collections.OrderedDict()
_GATE_MEMO_CAP = 32
_GATE_MEMO_LOCK = threading.Lock()


def _gate_memo_get(key):
    with _GATE_MEMO_LOCK:
        record = _GATE_MEMO.get(key)
        if record is not None:
            _GATE_MEMO.move_to_end(key)
        return record


def _gate_memo_put(key, record) -> None:
    with _GATE_MEMO_LOCK:
        _GATE_MEMO[key] = dict(record)
        _GATE_MEMO.move_to_end(key)
        while len(_GATE_MEMO) > _GATE_MEMO_CAP:
            _GATE_MEMO.popitem(last=False)


def reset_gate_memo() -> None:
    """Drop the memoized gate decisions (test isolation)."""
    with _GATE_MEMO_LOCK:
        _GATE_MEMO.clear()


def fused_extractor_id(wavelet_index: int, precision: str = "f32") -> Tuple:
    """The fused path's static extractor id/config tuple (feature-
    cache key component), derived from
    :meth:`OfflineDataProvider.load_features_device`'s own parameter
    defaults — so the key can never drift from the geometry the
    computation actually runs with.

    ``precision`` folds the numeric class into the key: the f32 tuple
    is byte-unchanged from PR 3 (warm caches survive this PR), while
    the bf16 path keys its own entries — a bf16 feature matrix can
    never serve an f32-class request or vice versa (the
    WaveletTransform.cache_id precision-class rule from PR 7, applied
    to the fused family)."""
    import inspect

    defaults = {
        k: p.default
        for k, p in inspect.signature(
            OfflineDataProvider.load_features_device
        ).parameters.items()
        if p.default is not inspect.Parameter.empty
    }
    base = (
        "dwt-fused",
        int(wavelet_index),
        defaults["epoch_size"],
        defaults["skip_samples"],
        defaults["feature_size"],
    )
    if precision == "f32":
        return base
    return base + (str(precision),)


@dataclasses.dataclass
class PreparedRun:
    """One read pass's products: the feature-cache key AND the parsed
    recordings behind it.

    Before this existed, a cold cache-enabled run paid a double read:
    ``feature_cache_key`` streamed every triplet's bytes for the
    content digest, then ``load_features_device`` re-read the same
    files to parse them (documented in PR3's review round). The
    provider now digests the bytes it parses — one physical read per
    file — at the cost of holding the run's parsed recordings in
    memory between the key lookup and the (miss-path) featurization.
    On a cache HIT the parse work is wasted, but cheap: the sample
    blob becomes a zero-copy ``np.frombuffer`` view (no scaling —
    that happens at featurization, which a hit skips), so the
    hit-path overhead over a pure digest pass is the vhdr/vmrk text
    parse only. For multi-GB remote sessions where the byte residency
    is unwanted, ``cache=false`` restores pure streaming.
    """

    key: str
    recordings: List[Tuple[str, int, "brainvision.Recording"]]
    #: the ordered (rel_path, guessed, content digest) triples behind
    #: ``key`` — kept so callers with SEVERAL extractor configs per
    #: run (the seizure path's fe_sweep=) derive each config's cache
    #: key from the same single read pass instead of re-digesting
    digests: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list
    )


class OfflineDataProvider:
    """Loads BrainVision recordings and extracts balanced P300 epochs."""

    def __init__(
        self,
        args: Sequence[str],
        filesystem: Optional[sources.FileSystem] = None,
        channel_names: Sequence[str] = constants.CHANNEL_NAMES,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
        workers: Optional[int] = None,
        prefetch_depth: Optional[int] = None,
    ):
        args = [a for a in args if a is not None]
        if len(args) == 0 or len(args) > 6:
            raise ValueError(
                "Please enter the input in one of these formats: "
                "1. <location of info.txt file> "
                "2. <location of a .eeg file> <guessed number> *<optional values>"
            )
        self._args = list(args)
        if filesystem is None:
            # URI-scheme routing (Const.java's fixed HDFS endpoint,
            # made pluggable): info_file=https://... or gs://... runs
            # the whole provider over the remote object store.
            from . import remote

            filesystem = remote.filesystem_for(self._args[0])
        self._fs = filesystem
        self._channel_names = [c.lower() for c in channel_names]
        self._pre = pre
        self._post = post
        self._workers = (
            max(1, int(workers)) if workers is not None
            else default_ingest_workers()
        )
        self._prefetch_depth = (
            max(1, int(prefetch_depth)) if prefetch_depth is not None
            else default_prefetch_depth()
        )
        self._batch: Optional[extractor.EpochBatch] = None
        # Resolved channel indices persist across files of a run: the
        # reference's FZIndex/CZIndex/PZIndex are instance fields, so a
        # file missing a channel silently reuses the index resolved for
        # the previous file (OffLineDataProvider.java:49-51,172-183);
        # the int-field default 0 applies only before the first hit.
        self._last_indices: Dict[str, int] = {c: 0 for c in self._channel_names}

    # -- input handling -------------------------------------------------

    def _resolve_files(self) -> tuple[str, Dict[str, int]]:
        """Returns (prefix, ordered {path: guessed number})."""
        loc = self._args[0]
        if loc.endswith(constants.EEG_EXTENSION):
            if len(self._args) < 2:
                raise ValueError(
                    "A .eeg input requires a guessed number: "
                    "<location of a .eeg file> <guessed number>"
                )
            return "", {loc: int(self._args[1])}
        if loc.endswith(".txt"):
            prefix = loc[: loc.rfind("/")] + "/" if "/" in loc else ""
            return prefix, sources.parse_info_txt(self._fs.read_text(loc))
        raise ValueError(
            "Please enter the input in one of these formats: "
            "1. <location of info.txt file> "
            "2. <location of a .eeg file> <guessed number> *<optional values>"
        )

    # -- loading --------------------------------------------------------

    def _resolved_workers(self, n_files: int) -> int:
        """Parse-pool size for this run. Deterministic chaos replay
        (``faults=``) counts injection-point invocations in call
        order, which a parallel parse would scramble — an installed
        fault plan therefore forces the sequential path, keeping the
        chaos-parity contract bit-stable."""
        from ..obs import chaos

        if chaos.active_plan() is not None:
            return 1
        return min(self._workers, n_files)

    def _read_recording(
        self, eeg_path: str, digest: bool = False
    ) -> Tuple[brainvision.Recording, Optional[str]]:
        """Read ONE BrainVision triplet — one physical read per file —
        and parse it; with ``digest``, the content digest (sha256 over
        vhdr+vmrk+eeg bytes, the :meth:`content_digests` scheme) is
        computed from those same bytes, which is what keeps a cold
        cache-enabled run from reading every file twice. Reads land in
        ``obs.metrics`` (``ingest.file_reads``) so the exactly-once
        contract is observable."""
        from .. import obs

        base = os.path.splitext(eeg_path)[0]
        triplet = (base + ".vhdr", base + ".vmrk", eeg_path)
        for p in triplet:
            if not self._fs.exists(p):
                raise FileNotFoundError(f"No related file found: {p}")
        blobs = [self._fs.read_bytes(p) for p in triplet]
        obs.metrics.count("ingest.file_reads", len(blobs))
        fingerprint = None
        if digest:
            h = hashlib.sha256()
            for blob in blobs:
                h.update(blob)
            fingerprint = h.hexdigest()
        return brainvision.load_recording_bytes(*blobs), fingerprint

    def _iter_recordings(
        self, prefix: str, files: Dict[str, int], with_digests: bool = False
    ) -> Iterator[Tuple[str, int, brainvision.Recording, Optional[str]]]:
        """Yield ``(rel_path, guessed, recording, digest)`` in
        ``files`` order (``digest`` is None unless ``with_digests``).

        Parsing runs in a bounded thread pool (``workers`` in flight,
        ``prefetch_depth`` decoded results queued ahead), but results
        are merged back in submission order, so epoch order, the
        cross-file balance counters, the stale-channel-index reuse,
        and the seed-1 shuffle downstream are all bit-identical to the
        sequential loop. Files whose sibling is missing are skipped
        with the same log line as before; any other parse error
        surfaces at the file's in-order position. The consumer stepping
        the generator overlaps the *next* files' host parse with its
        own epoching/featurizing/device work.
        """
        from ..obs import events

        items = list(files.items())
        workers = self._resolved_workers(len(items))
        if workers <= 1:
            for rel_path, guessed in items:
                try:
                    # telemetry: one span per recording parse (no-op
                    # without an active recorder)
                    with events.span("ingest.parse", file=rel_path):
                        rec, fingerprint = self._read_recording(
                            prefix + rel_path, digest=with_digests
                        )
                except FileNotFoundError as e:
                    logger.warning("Did not load %s: %s", rel_path, e)
                    continue
                yield rel_path, guessed, rec, fingerprint
            return

        from .. import obs

        obs.metrics.gauge("ingest.parallel_workers", workers)

        # pool threads adopt the consumer's per-plan fault domain so
        # their reads/spans/metrics (and any remote.request chaos
        # firing inside a pooled fetch) attribute to the right plan
        # under the multi-tenant executor
        from ..obs import domain as run_domain

        domain = run_domain.capture()

        def _parse_one(path: str, rel: str):
            # runs on a pool thread: the span's parent falls back to
            # the recorder's run root (per-thread stacks keep the
            # consumer's span nesting uncorrupted)
            with run_domain.adopt(domain), events.span(
                "ingest.parse", file=rel, pooled=True
            ):
                return self._read_recording(path, digest=with_digests)

        depth = workers + self._prefetch_depth
        pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="eeg-tpu-ingest"
        )
        pending: "collections.deque" = collections.deque()
        idx = 0
        try:
            while idx < len(items) or pending:
                while idx < len(items) and len(pending) < depth:
                    rel_path, guessed = items[idx]
                    pending.append(
                        (
                            rel_path,
                            guessed,
                            pool.submit(
                                _parse_one, prefix + rel_path, rel_path
                            ),
                        )
                    )
                    idx += 1
                rel_path, guessed, fut = pending.popleft()
                try:
                    rec, fingerprint = fut.result()
                except FileNotFoundError as e:
                    logger.warning("Did not load %s: %s", rel_path, e)
                    continue
                obs.metrics.count("ingest.files_parsed")
                yield rel_path, guessed, rec, fingerprint
        finally:
            # consumer stopped early or a parse failed: cancel queued
            # work and let in-flight parses finish on their own
            # instead of blocking the exit on them
            pool.shutdown(wait=False, cancel_futures=True)

    def load(self) -> extractor.EpochBatch:
        """Parse inputs and extract epochs from every resolvable file."""
        prefix, files = self._resolve_files()
        balance = extractor.BalanceState()
        batches: List[extractor.EpochBatch] = []
        for _rel_path, guessed, rec, _ in self._iter_recordings(
            prefix, files
        ):
            batches.append(self._process_recording(rec, guessed, balance))
        self._batch = extractor.EpochBatch.concatenate(batches)
        return self._batch

    def prepare_fused_run(self, extractor_id: Tuple) -> PreparedRun:
        """One read pass producing BOTH the feature-cache key and the
        parsed recordings: every triplet's bytes are read once,
        digested for the content key, and parsed in the same worker
        (``_read_recording``). The caller looks the key up first; on a
        miss it hands ``recordings`` back to
        :meth:`load_features_device`, which featurizes from memory
        instead of re-reading — the PR3-review double-read, closed.
        Missing-sibling files are skipped exactly as :meth:`load`
        skips them, so the key still fingerprints the run that would
        actually happen."""
        prefix, files = self._resolve_files()
        recordings: List[Tuple[str, int, brainvision.Recording]] = []
        digests: List[Tuple[str, int, str]] = []
        for rel_path, guessed, rec, fingerprint in self._iter_recordings(
            prefix, files, with_digests=True
        ):
            recordings.append((rel_path, guessed, rec))
            digests.append((rel_path, guessed, fingerprint))
        from . import feature_cache

        key = feature_cache.run_key(
            digests, self._channel_names, self._pre, self._post,
            extractor_id,
        )
        return PreparedRun(key=key, recordings=recordings, digests=digests)

    # the one-read-pass seam is extractor-agnostic (the id tuple is
    # opaque to the digest); the seizure path reuses it with its own
    # full extractor-config tuple, so the workloads share the
    # exactly-once read contract
    prepare_run = prepare_fused_run

    def run_key_for(self, prepared: PreparedRun, extractor_id: Tuple) -> str:
        """A further extractor config's cache key over an existing
        :class:`PreparedRun`'s digests — no re-read, no re-digest
        (the fe_sweep= path keys one entry per feature config)."""
        from . import feature_cache

        return feature_cache.run_key(
            prepared.digests, self._channel_names, self._pre, self._post,
            extractor_id,
        )

    def content_digests(self) -> List[Tuple[str, int, str]]:
        """Ordered ``(rel_path, guessed, content digest)`` for every
        recording this run would load.

        The digest covers the raw bytes of the whole BrainVision
        triplet (.vhdr, .vmrk, .eeg), so any content change — new
        samples, edited markers, a different channel table — yields a
        new digest. Files whose sibling is missing are omitted, exactly
        as :meth:`load` skips them, so the list fingerprints the run
        that would actually happen. This is the provider half of the
        feature-cache key (io/feature_cache.run_key).
        """
        prefix, files = self._resolve_files()
        out: List[Tuple[str, int, str]] = []
        for rel_path, guessed in files.items():
            eeg_path = prefix + rel_path
            base = os.path.splitext(eeg_path)[0]
            triplet = (base + ".vhdr", base + ".vmrk", eeg_path)
            if not all(self._fs.exists(p) for p in triplet):
                continue
            # sha256, not blake2b: hardware SHA extensions make it
            # ~1.7x faster on the multi-MB .eeg streams this walks,
            # and digest speed is the warm-cache run's floor
            h = hashlib.sha256()
            for p in triplet:
                h.update(self._fs.read_bytes(p))
            out.append((rel_path, guessed, h.hexdigest()))
        return out

    # Reference-compatible alias (OffLineDataProvider.loadData).
    load_data = load

    def load_sliding(self, config) -> extractor.EpochBatch:
        """Continuous sliding-window epoching (the seizure workload):
        every resolvable recording is cut into
        ``(n, n_channels, window)`` windows labeled from its
        ``Seizure`` interval annotations (epochs/sliding.py), through
        the same bounded parse pool + order-preserving merge as
        :meth:`load`. The manifest's guessed numbers are ignored —
        labels come from the annotations, not a stimulus match — and
        there is no balance scan: class imbalance is the workload.
        ``config`` is an ``epochs.sliding.SlidingConfig``."""
        prefix, files = self._resolve_files()
        batches: List[extractor.EpochBatch] = []
        for _rel, _guessed, rec, _ in self._iter_recordings(prefix, files):
            batches.append(self.sliding_batch_for(rec, config))
        self._batch = extractor.EpochBatch.concatenate(batches)
        return self._batch

    def sliding_batch_for(self, rec, config) -> extractor.EpochBatch:
        """One recording's sliding-window batch (scaled float64
        channels -> epochs/sliding.py); public so the serving layer
        derives byte-identical windows from the same seam."""
        from ..epochs import sliding

        channels = rec.read_channels(self._channel_indices(rec))
        return sliding.extract_sliding_epochs(channels, rec.markers, config)

    def iter_recordings(self) -> Iterator[Tuple[str, int, "brainvision.Recording"]]:
        """Public ordered recording stream: ``(rel_path, guessed,
        recording)`` per resolvable file, parsed through the same
        bounded pool + order-preserving merge as :meth:`load` — the
        seam the serving layer (serve/pipeline.py) uses to turn a
        session into per-epoch requests without re-implementing input
        handling."""
        prefix, files = self._resolve_files()
        for rel, guessed, rec, _ in self._iter_recordings(prefix, files):
            yield rel, guessed, rec

    @property
    def pre(self) -> int:
        """Prestimulus window samples (epoch geometry)."""
        return self._pre

    @property
    def post(self) -> int:
        """Poststimulus window samples (epoch geometry)."""
        return self._post

    @property
    def n_channels(self) -> int:
        """Selected channel count (the feature row's channel axis)."""
        return len(self._channel_names)

    def channel_indices_for(self, rec: "brainvision.Recording"):
        """Resolved channel indices for one recording, including the
        reference's stale-index reuse quirk (:meth:`_channel_indices`);
        public for the serving layer."""
        return self._channel_indices(rec)

    def load_features_device(
        self,
        wavelet_index: int = 8,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        backend: str = "xla",
        recordings: Optional[
            Sequence[Tuple[str, int, brainvision.Recording]]
        ] = None,
        precision: str = "f32",
        overlap: Optional[bool] = None,
        mesh=None,
        mesh_axis: Optional[str] = None,
        pod=None,
    ):
        """TPU fast path: info.txt run -> DWT features without host epochs.

        Per recording, raw int16 channels stage to the device and one
        fused program produces the L2-normalized feature rows; the
        host handles only marker metadata and the cross-file balance
        state. Returns (features (n, C*feature_size) float32,
        targets (n,) float64).

        ``backend``: "decode" (ops/decode_ingest.py — windows cut by
        dynamic slices in a tiled scan on CPU, by the VMEM bank128
        kernel on accelerators; no XLA gather anywhere), "xla"
        (ops/device_ingest.py — gather + einsum), "block"
        (ops/device_ingest.make_classed_block_ingest_featurizer
        — tile-row gathers with windows batched by alignment class, so
        each class contracts as one matmul; the host gather plan is
        memoized in ops/plan_cache, and re-ingesting an unchanged
        recording re-plans nothing), or "pallas"
        (ops/ingest_pallas.py — the fully fused VMEM-chunked kernel;
        interpret mode off-TPU).

        ``precision="bf16"`` computes the cascade matmul in bfloat16
        with f32 accumulation — supported on the decode rung only, and
        meant to run behind the per-run accuracy gate
        (:meth:`bf16_gate_check` / pipeline/builder.py).

        ``overlap`` (None -> ``EEG_TPU_OVERLAP``) runs each
        recording's staging + fused-program dispatch on a background
        staging thread (io/staging.prefetch) so recording K+1's
        decode+featurize overlaps the consumer's handling of
        recording K — order-preserving, so features/targets are
        bit-identical to the serial path (pinned).

        ``mesh`` (a ``jax.sharding.Mesh`` with >= 2 devices on its
        ingest axis) shards each recording's epoch batch over the
        device mesh through ``parallel/sharded_ingest.py``: the raw
        int16 stream stages time-sharded (one contiguous block per
        device, padded to the shard grid — validity judged against
        the true length), each device cuts + featurizes the windows
        starting in its block (ring-halo for boundary straddlers),
        and the staged stream buffer is donated per shard on
        accelerator backends. Recordings the sharded path cannot
        express (non-INT16 sources, any per-recording failure) fall
        back to the requested ``backend``'s featurizer with a logged
        ``ingest.sharded_fallback`` count — the features are
        rung-tolerance-identical either way (the ladder contract). A
        single-device mesh is ignored here (the unsharded rung IS the
        degenerate case, byte-identical by construction).
        ``mesh_axis`` overrides the ingest axis (default: ``time``
        when the mesh has one, else its last axis).

        ``pod`` (a ``parallel.pod.PodRuntime`` with >= 2 processes)
        routes the whole run through the pod-partitioned ingest: the
        global metadata pass plans every recording identically on
        every process, this process reads + featurizes only its
        contiguous recording block (same rung program, globally
        planned positions/mask), and one DCN all-gather assembles the
        global ``(features, targets)`` — bit-identical rows to the
        unpartitioned run. ``mesh`` sharding, ``overlap``, and
        ``recordings`` reuse do not apply on that path (a pod run
        bypasses the feature cache, so there is no PreparedRun).

        Numerics follow the float32 device path (tolerance-level vs
        the bit-exact host path) — use :meth:`load` + a host-backend
        WaveletTransform when bit parity with the Java reference is
        required.
        """
        from .. import obs
        from ..epochs.extractor import BalanceState
        from ..obs import chaos, events
        from ..ops import device_ingest

        if backend not in ("decode", "xla", "block", "pallas"):
            raise ValueError(f"unknown device-ingest backend {backend!r}")
        if precision != "f32" and backend != "decode":
            raise ValueError(
                f"precision={precision!r} is a decode-rung feature; "
                f"backend {backend!r} computes f32"
            )
        # telemetry: record which fused rung this attempt runs — the
        # builder's ladder may call several times before one lands
        events.event(
            "ingest.fused_attempt",
            backend=backend,
            wavelet_index=int(wavelet_index),
        )
        # chaos injection: one fused-backend attempt fails (a Pallas
        # lowering error, an OOM) — the pipeline's degradation ladder
        # catches it and steps down a backend
        chaos.maybe_fire("ingest.fused")
        if recordings is None:
            prefix, files = self._resolve_files()
            source = (
                (rel, guessed, rec)
                for rel, guessed, rec, _ in self._iter_recordings(
                    prefix, files
                )
            )
        else:
            # a PreparedRun (prepare_fused_run) already read + parsed
            # this run's files for the cache key: featurize from
            # memory — no second read, and a degradation-ladder retry
            # on another backend re-reads nothing either
            source = iter(recordings)
        balance = BalanceState()
        sharded_extract = None
        sharded_axis = None
        if mesh is not None:
            from ..parallel import mesh as pmesh, sharded_ingest

            sharded_axis = mesh_axis or (
                pmesh.TIME_AXIS
                if pmesh.TIME_AXIS in mesh.axis_names
                else mesh.axis_names[-1]
            )
            if int(mesh.shape[sharded_axis]) >= 2:
                import jax

                # one extractor per run (the per-recording loop below
                # reuses it; shard capacities bucket like every rung)
                sharded_extract = sharded_ingest.make_sharded_ingest(
                    mesh,
                    wavelet_index=wavelet_index,
                    epoch_size=epoch_size,
                    skip_samples=skip_samples,
                    feature_size=feature_size,
                    pre=self._pre,
                    axis=sharded_axis,
                    # dead after the on-device scale; CPU cannot alias
                    # and would warn per call (the decode-rung policy)
                    donate_stream=jax.default_backend() != "cpu",
                )
        program = self._build_fused_featurizer(
            backend, wavelet_index, epoch_size, skip_samples,
            feature_size, precision,
        )
        pallas_featurizer = program if backend == "pallas" else None
        featurizer = None if backend == "pallas" else program

        if pod is not None and int(pod.num_processes) > 1:
            # pod-partitioned ingest (parallel/pod.py): this process
            # reads + featurizes only its contiguous recording block
            # with the SAME per-recording rung program as above,
            # driven by the globally planned positions/mask, and the
            # one DCN collective assembles the global matrix. The
            # local ladder semantics are the caller's, unchanged — a
            # rung failure here degrades exactly like a single-host
            # failure of the same rung.
            from ..parallel import pod as pod_mod

            return pod_mod.pod_features(
                pod,
                self,
                self._planned_entry_featurizer(program, backend),
                n_feat=len(self._channel_names) * feature_size,
            )

        def featurize_sharded(item):
            """One recording through the mesh-sharded ingest: pad the
            int16 stream to the shard grid, plan shard assignment
            (validity on the TRUE length), stage time-sharded, and
            run the halo'd per-shard featurizer. Returns the same
            (rows, mask, targets) triple as the pallas path (rows
            already kept-only). Raises for recordings the sharded
            path cannot express — the caller falls back to the
            requested rung per recording."""
            from ..parallel import sharded_ingest

            _rel_path, guessed, rec = item
            if rec.header.binary_format != "INT_16":
                # float32-source recordings would truncate through the
                # int16 staging seam; checked BEFORE stage_raw so the
                # fallback rung's own staging is the only full-stream
                # copy this recording pays
                raise ValueError(
                    "sharded ingest stages raw int16 streams; this "
                    f"recording is {rec.header.binary_format}"
                )
            raw, res, n_true = device_ingest.stage_raw(
                rec, self._channel_indices(rec)
            )
            if raw.dtype != np.int16:  # stage_raw's own fallback fired
                raise ValueError(
                    "sharded ingest stages raw int16 streams; this "
                    "recording decoded to float32"
                )
            n_shards = int(mesh.shape[sharded_axis])
            block = sharded_ingest.shard_block_for(
                raw.shape[1], n_shards
            )
            total = n_shards * block
            if total > raw.shape[1]:
                raw = np.pad(raw, ((0, 0), (0, total - raw.shape[1])))
            plan = sharded_ingest.plan_sharded_ingest(
                rec.markers,
                guessed,
                total,
                n_shards,
                block,
                pre=self._pre,
                balance=balance,
                valid_n_samples=n_true,
            )
            # staged through the multi-host entry point: on every
            # single-process (and host-local) mesh this is exactly the
            # old device_put, and a fully-addressable pod submesh
            # takes the same fast path (distributed.stage_local) — so
            # the ring-halo seam's staging is multi-host-ready without
            # a second code path
            staged = sharded_ingest.stage_recording_local_int16(
                raw, mesh, sharded_axis
            )
            rows = sharded_extract(staged, res, plan)
            # counted AFTER the extract lands: a failed attempt falls
            # back to the rung featurizer, which bills its own
            # h2d_bytes — counting up front would double-bill the
            # recording and record a sharded ingest that never happened
            obs.metrics.count(
                "ingest.h2d_bytes",
                int(raw.nbytes) + int(res.nbytes)
                + int(plan.local_positions.nbytes)
                + int(plan.mask.nbytes),
            )
            obs.metrics.count("ingest.sharded_recordings")
            return rows, None, plan.targets

        def featurize_one(item):
            """One recording's staging + plan + fused dispatch ->
            (device features, mask-or-None, targets). Shared verbatim
            by the serial loop and the overlap producer, so the two
            paths cannot drift; runs single-threaded in either case
            (the balance scan and the stale-channel-index reuse are
            order-dependent state)."""
            if sharded_extract is not None:
                # the balance scan is order-dependent run state; a
                # sharded attempt that fails after scanning must not
                # let the fallback rung double-count this recording
                saved = (balance.n_targets, balance.n_nontargets)
                try:
                    return featurize_sharded(item)
                except Exception as e:
                    balance.n_targets, balance.n_nontargets = saved
                    logger.warning(
                        "sharded ingest fell back to the %s rung for "
                        "%s (%s: %s)", backend, item[0],
                        type(e).__name__, e,
                    )
                    obs.metrics.count("ingest.sharded_fallback")
            _rel_path, guessed, rec = item
            raw, res, n_samples = device_ingest.stage_raw(
                rec, self._channel_indices(rec)
            )
            plan = device_ingest.plan_ingest(
                rec.markers,
                guessed,
                n_samples,
                pre=self._pre,
                post=self._post,
                balance=balance,
            )
            # host->device transfer accounting (bench attribution):
            # the staged stream + plan metadata bytes this recording
            # ships, whatever the rung
            obs.metrics.count(
                "ingest.h2d_bytes",
                int(raw.nbytes) + int(res.nbytes)
                + int(plan.positions.nbytes) + int(plan.mask.nbytes),
            )
            # async dispatch: keep the device array; the next file's
            # host parse/stage overlaps this file's device compute
            if backend == "pallas":
                kept = plan.positions[plan.mask]
                return pallas_featurizer(raw, res, kept), None, plan.targets
            return (
                featurizer(raw, res, plan.positions, plan.mask),
                plan.mask,
                plan.targets,
            )

        feats: List[np.ndarray] = []
        targets: List[np.ndarray] = []
        use_overlap = default_overlap() if overlap is None else bool(overlap)
        if use_overlap:
            # double-buffered ingest/compute overlap: the staging
            # producer thread runs recording K+1's featurize_one
            # (stage + plan + program dispatch) while this consumer
            # handles recording K. staging.prefetch's bounded buffer,
            # poison/stop semantics, consumer watchdog, and the
            # staging.producer chaos point all apply unchanged; the
            # queue is FIFO, so epoch order is bit-identical to the
            # serial loop at any prefetch depth (pinned).
            from . import staging

            obs.metrics.count("ingest.overlap_runs")
            for out, mask, tgt in staging.prefetch(
                source, stage_fn=featurize_one
            ):
                feats.append((out, mask))
                targets.append(tgt)
        else:
            # the ordered parallel parse: while this loop runs one
            # file's staging + fused program dispatch, the pool is
            # already parsing the next files' triplets on the host
            for item in source:
                out, mask, tgt = featurize_one(item)
                feats.append((out, mask))
                targets.append(tgt)
        n_feat = len(self._channel_names) * feature_size
        if not feats:
            return (
                np.zeros((0, n_feat), dtype=np.float32),
                np.zeros((0,), dtype=np.float64),
            )
        return (
            np.concatenate(
                [
                    np.asarray(out) if mask is None else np.asarray(out)[mask]
                    for out, mask in feats
                ]
            ),
            np.concatenate(targets),
        )

    def _build_fused_featurizer(
        self,
        backend: str,
        wavelet_index: int,
        epoch_size: int,
        skip_samples: int,
        feature_size: int,
        precision: str,
    ):
        """The per-rung fused program, one construction shared by
        :meth:`load_features_device` and the pod path's
        :meth:`planned_featurizer` so the two can never drift.
        Returns the callable; the pallas form takes kept positions,
        every other form ``(raw, res, positions, mask)``."""
        from ..ops import device_ingest

        if backend == "pallas":
            import os

            from ..ops import ingest_pallas

            return ingest_pallas.make_pallas_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                pre=self._pre,
                # None -> the library's platform default (bank128 on
                # compiled Mosaic, exact on interpreter platforms);
                # EEG_PALLAS_MODE overrides
                mode=os.environ.get("EEG_PALLAS_MODE") or None,
            )
        if backend == "decode":
            from ..ops import decode_ingest

            return decode_ingest.make_decode_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                pre=self._pre,
                precision=precision,
            )
        if backend == "block":
            # the host-planned alignment-classed form: positions here
            # are always concrete IngestPlan metadata, so the plan
            # cache applies and the 128-variant bank's MACs don't
            return device_ingest.make_classed_block_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                pre=self._pre,
            )
        return device_ingest.make_device_ingest_featurizer(
            wavelet_index=wavelet_index,
            epoch_size=epoch_size,
            skip_samples=skip_samples,
            feature_size=feature_size,
            channels=tuple(range(1, len(self._channel_names) + 1)),
            pre=self._pre,
            post=self._post,
        )

    def _planned_entry_featurizer(self, program, backend: str):
        """Closure featurizing ONE pod-plan entry (parallel/pod.py
        ``PodRecording``) through an already-built rung ``program``:
        read the owned waveform, stage it, and run the globally
        planned positions/mask. Returns the recording's kept feature
        rows."""

        def featurize_entry(entry):
            from .. import obs
            from ..ops import device_ingest
            from . import brainvision as bv

            blob = self._fs.read_bytes(entry.eeg_path)
            obs.metrics.count("ingest.file_reads", 1)
            rec = bv._recording_from_blob(
                entry.header, entry.markers, blob
            )
            raw, res, n_samples = device_ingest.stage_raw(
                rec, entry.channel_indices
            )
            if n_samples != entry.n_samples:
                # the metadata pass sized this recording from its byte
                # count; a disagreement means the file changed between
                # the global plan and this read — the plan (and the
                # balance state behind every later recording) is stale
                raise ValueError(
                    f"{entry.rel_path}: {n_samples} samples on read "
                    f"vs {entry.n_samples} at plan time; recording "
                    f"changed mid-run"
                )
            iplan = entry.plan
            obs.metrics.count(
                "ingest.h2d_bytes",
                int(raw.nbytes) + int(res.nbytes)
                + int(iplan.positions.nbytes) + int(iplan.mask.nbytes),
            )
            if backend == "pallas":
                return np.asarray(
                    program(raw, res, iplan.positions[iplan.mask])
                )
            return np.asarray(
                program(raw, res, iplan.positions, iplan.mask)
            )[iplan.mask]

        return featurize_entry

    def planned_featurizer(
        self,
        backend: str = "decode",
        wavelet_index: int = 8,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        precision: str = "f32",
    ):
        """Public pod-path seam: an entry-featurizing closure over a
        freshly built rung program (tests drive the partitioned
        ingest through this without a live multi-process runtime)."""
        return self._planned_entry_featurizer(
            self._build_fused_featurizer(
                backend, wavelet_index, epoch_size, skip_samples,
                feature_size, precision,
            ),
            backend,
        )

    def precision_gate_check(
        self,
        recordings: Sequence[Tuple[str, int, "brainvision.Recording"]],
        wavelet_index: int = 8,
        precision: str = "bf16",
        max_rows: int = 64,
        content_key: Optional[str] = None,
    ) -> dict:
        """The per-run precision accuracy gate (bf16 and int8 share
        it): the first recording's first ``max_rows`` kept markers are
        featurized through the decode rung in BOTH the requested
        precision and f32, and the rows compared against that rung's
        documented tolerance (ops/decode_ingest.feature_precision_
        gate). Returns the gate record (max_abs_dev / tolerance / ok /
        rows_checked, plus ``gate_seconds`` — the double-featurize
        cost, so reports can separate gate overhead from steady-state
        throughput — and ``cached``) the builder embeds in
        run_report.json. The reference pass runs on a 64-capacity
        plan, so its extra f32 program is the smallest compile the
        rung has.

        ``content_key`` (the first recording's content digest) hoists
        the double-featurize off the hot path where it re-runs: the
        decision is pure — a function of the bytes, the geometry, and
        the resolved tolerance — so a process re-gating the same
        content (warm re-runs, multi-tenant plans over one session)
        replays the memoized record with ``cached=True`` and
        ``gate_seconds=0.0`` instead of paying the two programs again.
        """
        import time as _time

        from ..ops import decode_ingest, device_ingest

        tol = decode_ingest.precision_gate_tolerance(precision)
        memo_key = None
        if content_key is not None:
            memo_key = (
                str(content_key), int(wavelet_index), str(precision),
                int(max_rows), float(tol), self._pre, self._post,
                tuple(self._channel_names),
                # the decode formulation is resolved per call and never
                # cached elsewhere (the 'auto'-resolution staleness
                # class) — a formulation flip between runs must re-gate,
                # not replay the other formulation's deviation
                decode_ingest.default_formulation(),
            )
            cached = _gate_memo_get(memo_key)
            if cached is not None:
                record = dict(cached)
                record["cached"] = True
                record["gate_seconds"] = 0.0
                return record
        t0 = _time.perf_counter()
        if not recordings:
            gate = decode_ingest.feature_precision_gate(
                np.zeros((0, 1), np.float32),
                np.zeros((0, 1), np.float32),
                precision=precision,
            )
        else:
            _rel, guessed, rec = recordings[0]
            raw, res, n_samples = device_ingest.stage_raw(
                rec, self._channel_indices(rec)
            )
            # fresh BalanceState: the gate compares feature VALUES for
            # identical windows — retention differences against the
            # real run are irrelevant, and the real run's balance
            # state must not be perturbed
            plan = device_ingest.plan_ingest(
                rec.markers, guessed, n_samples,
                pre=self._pre, post=self._post,
            )
            cap = min(max_rows, plan.capacity)
            positions, mask = plan.positions[:cap], plan.mask[:cap]
            kwargs = dict(
                wavelet_index=wavelet_index, pre=self._pre
            )
            f32_rows = decode_ingest.make_decode_ingest_featurizer(
                precision="f32", **kwargs
            )(raw, res, positions, mask)
            rung_rows = decode_ingest.make_decode_ingest_featurizer(
                precision=precision, **kwargs
            )(raw, res, positions, mask)
            real = np.asarray(mask, dtype=bool)
            gate = decode_ingest.feature_precision_gate(
                np.asarray(rung_rows)[real],
                np.asarray(f32_rows)[real],
                precision=precision,
            )
        gate["gate_seconds"] = round(_time.perf_counter() - t0, 6)
        gate["cached"] = False
        if memo_key is not None:
            _gate_memo_put(memo_key, gate)
        return gate

    def bf16_gate_check(
        self,
        recordings: Sequence[Tuple[str, int, "brainvision.Recording"]],
        wavelet_index: int = 8,
        max_rows: int = 64,
    ) -> dict:
        """The bf16 spelling of :meth:`precision_gate_check` (the PR 8
        surface, kept for its callers and pins)."""
        return self.precision_gate_check(
            recordings, wavelet_index=wavelet_index,
            precision="bf16", max_rows=max_rows,
        )

    def feature_cache_key(self, extractor: Tuple) -> str:
        """Content key for this run's feature matrix: the ordered
        triplet digests plus the provider's channel set and epoch
        window, plus the static ``extractor`` id/config tuple
        (io/feature_cache.run_key)."""
        from . import feature_cache

        return feature_cache.run_key(
            self.content_digests(),
            self._channel_names,
            self._pre,
            self._post,
            extractor,
        )

    def _channel_indices(self, rec: brainvision.Recording) -> List[int]:
        return self._channel_indices_for_header(rec.header)

    def _channel_indices_for_header(self, header) -> List[int]:
        """Channel resolution from the header alone (the pod metadata
        pass resolves every recording's indices without reading its
        waveform), including the reference's stale-index reuse quirk —
        which is exactly why this must advance in global load order."""
        indices = []
        for name in self._channel_names:
            idx = header.channel_index(name)
            if idx is None:
                idx = self._last_indices[name]
                logger.warning(
                    "Channel %s not found; reusing stale index %d", name, idx
                )
            self._last_indices[name] = idx
            indices.append(idx)
        return indices

    def _process_recording(
        self,
        rec: brainvision.Recording,
        guessed: int,
        balance: extractor.BalanceState,
    ) -> extractor.EpochBatch:
        channels = rec.read_channels(self._channel_indices(rec))
        return extractor.extract_epochs(
            channels,
            rec.markers,
            guessed,
            pre=self._pre,
            post=self._post,
            balance=balance,
        )

    # -- reference-parity accessors ------------------------------------

    @property
    def batch(self) -> extractor.EpochBatch:
        if self._batch is None:
            self.load()
        assert self._batch is not None
        return self._batch

    def get_data(self) -> List[np.ndarray]:
        """List of (3, 750) float64 epochs (reference ``getData``)."""
        return [e for e in self.batch.epochs]

    def get_data_labels(self) -> List[float]:
        """List of 0.0/1.0 labels (reference ``getDataLabels``)."""
        return [float(t) for t in self.batch.targets]
