"""Offline data provider: info.txt / .eeg inputs -> balanced epoch batch.

TPU-first re-design of ``DataTransformation/OffLineDataProvider.java``:
instead of a stateful loader mutating epoch lists per marker, files are
parsed on the host into dense ``(n, 3, 750)`` arrays ready for device
staging. Input-contract parity:

- args ``[<info.txt path>]`` or ``[<.eeg path>, <guessed number>]``
  (OffLineDataProvider.java:111-141);
- info.txt entries are resolved against the info.txt's directory
  (``filePrefix`` — :129);
- duplicate info.txt entries collapse, first-seen order, last guess
  wins (LinkedHashMap semantics — :53, :308);
- files whose .vhdr/.vmrk/.eeg sibling is missing are skipped with a
  log, not fatal (:154-161);
- channels named fz/cz/pz (case-insensitive) are selected (:172-183);
- the balance counters span all files of a run (:58-59).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import brainvision, sources
from ..epochs import extractor
from ..utils import constants

logger = logging.getLogger(__name__)

#: the backend degradation ladder for fused device ingest, fastest
#: first: Pallas kernel -> block (alignment-classed matmul) -> XLA
#: element gather -> host epochs + registry extractor. Each rung
#: produces the same features (tolerance-level numerics), so stepping
#: down trades speed for survival, never correctness.
FUSED_DEGRADATION_LADDER = ("pallas", "block", "xla", "host")


def degradation_ladder(backend: str):
    """Backends to try, in order, starting from ``backend``.

    ``pallas`` -> ``["pallas", "block", "xla", "host"]``; ``xla`` ->
    ``["xla", "host"]``. The terminal ``"host"`` rung is not a
    ``load_features_device`` backend — it signals the caller
    (pipeline/builder.py) to fall back to host epoch loading plus the
    registry feature extractor.
    """
    if backend not in FUSED_DEGRADATION_LADDER[:-1]:
        raise ValueError(f"unknown device-ingest backend {backend!r}")
    return list(
        FUSED_DEGRADATION_LADDER[FUSED_DEGRADATION_LADDER.index(backend):]
    )


class OfflineDataProvider:
    """Loads BrainVision recordings and extracts balanced P300 epochs."""

    def __init__(
        self,
        args: Sequence[str],
        filesystem: Optional[sources.FileSystem] = None,
        channel_names: Sequence[str] = constants.CHANNEL_NAMES,
        pre: int = constants.PRESTIMULUS_SAMPLES,
        post: int = constants.POSTSTIMULUS_SAMPLES,
    ):
        args = [a for a in args if a is not None]
        if len(args) == 0 or len(args) > 6:
            raise ValueError(
                "Please enter the input in one of these formats: "
                "1. <location of info.txt file> "
                "2. <location of a .eeg file> <guessed number> *<optional values>"
            )
        self._args = list(args)
        if filesystem is None:
            # URI-scheme routing (Const.java's fixed HDFS endpoint,
            # made pluggable): info_file=https://... or gs://... runs
            # the whole provider over the remote object store.
            from . import remote

            filesystem = remote.filesystem_for(self._args[0])
        self._fs = filesystem
        self._channel_names = [c.lower() for c in channel_names]
        self._pre = pre
        self._post = post
        self._batch: Optional[extractor.EpochBatch] = None
        # Resolved channel indices persist across files of a run: the
        # reference's FZIndex/CZIndex/PZIndex are instance fields, so a
        # file missing a channel silently reuses the index resolved for
        # the previous file (OffLineDataProvider.java:49-51,172-183);
        # the int-field default 0 applies only before the first hit.
        self._last_indices: Dict[str, int] = {c: 0 for c in self._channel_names}

    # -- input handling -------------------------------------------------

    def _resolve_files(self) -> tuple[str, Dict[str, int]]:
        """Returns (prefix, ordered {path: guessed number})."""
        loc = self._args[0]
        if loc.endswith(constants.EEG_EXTENSION):
            if len(self._args) < 2:
                raise ValueError(
                    "A .eeg input requires a guessed number: "
                    "<location of a .eeg file> <guessed number>"
                )
            return "", {loc: int(self._args[1])}
        if loc.endswith(".txt"):
            prefix = loc[: loc.rfind("/")] + "/" if "/" in loc else ""
            return prefix, sources.parse_info_txt(self._fs.read_text(loc))
        raise ValueError(
            "Please enter the input in one of these formats: "
            "1. <location of info.txt file> "
            "2. <location of a .eeg file> <guessed number> *<optional values>"
        )

    # -- loading --------------------------------------------------------

    def load(self) -> extractor.EpochBatch:
        """Parse inputs and extract epochs from every resolvable file."""
        prefix, files = self._resolve_files()
        balance = extractor.BalanceState()
        batches: List[extractor.EpochBatch] = []
        for rel_path, guessed in files.items():
            eeg_path = prefix + rel_path
            try:
                rec = brainvision.load_recording(eeg_path, filesystem=self._fs)
            except FileNotFoundError as e:
                logger.warning("Did not load %s: %s", rel_path, e)
                continue
            batches.append(self._process_recording(rec, guessed, balance))
        self._batch = extractor.EpochBatch.concatenate(batches)
        return self._batch

    # Reference-compatible alias (OffLineDataProvider.loadData).
    load_data = load

    def load_features_device(
        self,
        wavelet_index: int = 8,
        epoch_size: int = 512,
        skip_samples: int = 175,
        feature_size: int = 16,
        backend: str = "xla",
    ):
        """TPU fast path: info.txt run -> DWT features without host epochs.

        Per recording, raw int16 channels stage to the device and one
        fused program produces the L2-normalized feature rows; the
        host handles only marker metadata and the cross-file balance
        state. Returns (features (n, C*feature_size) float32,
        targets (n,) float64).

        ``backend``: "xla" (ops/device_ingest.py — gather + einsum),
        "block" (ops/device_ingest.make_classed_block_ingest_featurizer
        — tile-row gathers with windows batched by alignment class, so
        each class contracts as one matmul; the host gather plan is
        memoized in ops/plan_cache, and re-ingesting an unchanged
        recording re-plans nothing), or "pallas"
        (ops/ingest_pallas.py — the fully fused VMEM-chunked kernel;
        interpret mode off-TPU).

        Numerics follow the float32 device path (tolerance-level vs
        the bit-exact host path) — use :meth:`load` + a host-backend
        WaveletTransform when bit parity with the Java reference is
        required.
        """
        from ..epochs.extractor import BalanceState
        from ..obs import chaos
        from ..ops import device_ingest

        if backend not in ("xla", "block", "pallas"):
            raise ValueError(f"unknown device-ingest backend {backend!r}")
        # chaos injection: one fused-backend attempt fails (a Pallas
        # lowering error, an OOM) — the pipeline's degradation ladder
        # catches it and steps down a backend
        chaos.maybe_fire("ingest.fused")
        prefix, files = self._resolve_files()
        balance = BalanceState()
        if backend == "pallas":
            import os

            from ..ops import ingest_pallas

            pallas_featurizer = ingest_pallas.make_pallas_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                pre=self._pre,
                # None -> the library's platform default (bank128 on
                # compiled Mosaic, exact on interpreter platforms);
                # EEG_PALLAS_MODE overrides
                mode=os.environ.get("EEG_PALLAS_MODE") or None,
            )
        if backend == "block":
            # the host-planned alignment-classed form: positions here
            # are always concrete IngestPlan metadata, so the plan
            # cache applies and the 128-variant bank's MACs don't
            featurizer = device_ingest.make_classed_block_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                pre=self._pre,
            )
        elif backend == "xla":
            featurizer = device_ingest.make_device_ingest_featurizer(
                wavelet_index=wavelet_index,
                epoch_size=epoch_size,
                skip_samples=skip_samples,
                feature_size=feature_size,
                channels=tuple(range(1, len(self._channel_names) + 1)),
                pre=self._pre,
                post=self._post,
            )
        feats: List[np.ndarray] = []
        targets: List[np.ndarray] = []
        for rel_path, guessed in files.items():
            try:
                rec = brainvision.load_recording(
                    prefix + rel_path, filesystem=self._fs
                )
            except FileNotFoundError as e:
                logger.warning("Did not load %s: %s", rel_path, e)
                continue
            raw, res, n_samples = device_ingest.stage_raw(
                rec, self._channel_indices(rec)
            )
            plan = device_ingest.plan_ingest(
                rec.markers,
                guessed,
                n_samples,
                pre=self._pre,
                post=self._post,
                balance=balance,
            )
            # async dispatch: keep the device array; the next file's
            # host parse/stage overlaps this file's device compute
            if backend == "pallas":
                kept = plan.positions[plan.mask]
                feats.append((pallas_featurizer(raw, res, kept), None))
            else:
                feats.append(
                    (featurizer(raw, res, plan.positions, plan.mask),
                     plan.mask)
                )
            targets.append(plan.targets)
        n_feat = len(self._channel_names) * feature_size
        if not feats:
            return (
                np.zeros((0, n_feat), dtype=np.float32),
                np.zeros((0,), dtype=np.float64),
            )
        return (
            np.concatenate(
                [
                    np.asarray(out) if mask is None else np.asarray(out)[mask]
                    for out, mask in feats
                ]
            ),
            np.concatenate(targets),
        )

    def _channel_indices(self, rec: brainvision.Recording) -> List[int]:
        indices = []
        for name in self._channel_names:
            idx = rec.header.channel_index(name)
            if idx is None:
                idx = self._last_indices[name]
                logger.warning(
                    "Channel %s not found; reusing stale index %d", name, idx
                )
            self._last_indices[name] = idx
            indices.append(idx)
        return indices

    def _process_recording(
        self,
        rec: brainvision.Recording,
        guessed: int,
        balance: extractor.BalanceState,
    ) -> extractor.EpochBatch:
        channels = rec.read_channels(self._channel_indices(rec))
        return extractor.extract_epochs(
            channels,
            rec.markers,
            guessed,
            pre=self._pre,
            post=self._post,
            balance=balance,
        )

    # -- reference-parity accessors ------------------------------------

    @property
    def batch(self) -> extractor.EpochBatch:
        if self._batch is None:
            self.load()
        assert self._batch is not None
        return self._batch

    def get_data(self) -> List[np.ndarray]:
        """List of (3, 750) float64 epochs (reference ``getData``)."""
        return [e for e in self.batch.epochs]

    def get_data_labels(self) -> List[float]:
        """List of 0.0/1.0 labels (reference ``getDataLabels``)."""
        return [float(t) for t in self.batch.targets]
