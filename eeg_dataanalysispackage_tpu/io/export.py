"""Epoch CSV export (reference: DataTransformation/DataProviderUtils.java).

``writeEpochsToCSV`` dumps channel Pz (``epoch[2]``) of every epoch as
a comma-separated row with a trailing comma (DataProviderUtils.java:30-47;
the ``Epochs.csv`` artifact at the reference repo root is its output).
Numbers are formatted with ``utils.java_compat.java_double_to_string``
— ``Double.toString`` semantics — so output diffs byte-exactly against
reference artifacts (modulo the documented pre-JDK-19 shortest-digit
cases, which parse equal).
"""

from __future__ import annotations

import numpy as np

from ..utils.java_compat import java_double_to_string


def write_epochs_to_csv(
    epochs: np.ndarray, path: str = "Epochs.csv", channel: int = 2
) -> None:
    """Write ``epochs[:, channel, :]`` rows as ``v0,v1,...,v749,\\n``."""
    arr = np.asarray(epochs, dtype=np.float64)
    with open(path, "w") as f:
        for row in arr[:, channel, :]:
            f.write("".join(f"{java_double_to_string(v)}," for v in row))
            f.write("\n")


def write_channel_text(
    channel: np.ndarray, path: str, filesystem=None
) -> None:
    """Write one raw channel as text, one sample per line.

    The equivalent of the reference's raw-read smoke path
    (HadoopLoadingTest.tryRAWEEG, HadoopLoadingTest.java:56-119: read
    a channel, ``sc.parallelize``, ``saveAsTextFile`` back to storage)
    — here a straight write through the pluggable filesystem, with
    ``Double.toString`` number formatting for byte parity with
    ``saveAsTextFile`` artifacts. Without an explicit ``filesystem``
    the path's scheme routes it (``hdfs://``/``http(s)://``/``gs://``
    / local), same as the provider and model persistence.
    """
    from . import remote

    fs = filesystem or remote.filesystem_for(path)
    arr = np.asarray(channel, dtype=np.float64).ravel()
    fs.write_bytes(
        path,
        "".join(f"{java_double_to_string(v)}\n" for v in arr).encode("ascii"),
    )


def read_epochs_csv(path: str) -> np.ndarray:
    """Read a ``writeEpochsToCSV``-format file back into (n, T) float64
    (rows have a trailing comma)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip().rstrip(",")
            if line:
                rows.append([float(x) for x in line.split(",")])
    return np.array(rows, dtype=np.float64)
