"""BrainVision (.vhdr/.vmrk/.eeg) reader.

Parses the Brain Vision Data Exchange format (INI-style header + marker
files, multiplexed int16 binary data) the way the reference's closed
``eegloader-hdfs`` jar does, as observed through
``/root/reference/src/main/java/cz/zcu/kiv/DataTransformation/OffLineDataProvider.java:167-196``
and the fixture headers (``test-data/DoD/DoD2015_01.vhdr``).

Scaling: each int16 sample is multiplied by the per-channel resolution
(e.g. 0.1 uV) in float64, matching ``readBinaryData(...) -> double[]``.

The hot demux (int16 -> scaled float) is vectorized numpy here; the
optional native C++ path lives in ``io/native.py``.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class ChannelInfo:
    """One ``Ch<n>=<Name>,<Ref>,<Resolution>,<Unit>`` entry."""

    number: int  # 1-based channel number, as in the header
    name: str
    reference: str
    resolution: float
    units: str


@dataclasses.dataclass(frozen=True)
class Marker:
    """One ``Mk<n>=<Type>,<Description>,<Position>,...`` entry.

    ``stimulus`` carries the description text (e.g. ``"S  2"``);
    ``position`` is the raw position-in-data-points field, used directly
    as the sample index the way the reference uses
    ``marker.getPosition()`` (OffLineDataProvider.java:220-225).
    """

    name: str
    kind: str
    stimulus: str
    position: int

    def stimulus_index(self) -> int:
        """Digits of the stimulus text minus one; -1 when no digits.

        Mirrors ``replaceAll("[\\D]", "")`` + parse - 1
        (OffLineDataProvider.java:207-214).
        """
        digits = re.sub(r"\D", "", self.stimulus)
        if digits:
            return int(digits) - 1
        return -1


@dataclasses.dataclass(frozen=True)
class Header:
    data_file: str
    marker_file: str
    data_format: str  # BINARY
    orientation: str  # MULTIPLEXED | VECTORIZED
    num_channels: int
    sampling_interval_us: float
    binary_format: str  # INT_16 | IEEE_FLOAT_32
    channels: List[ChannelInfo]

    @property
    def sampling_rate_hz(self) -> float:
        return 1e6 / self.sampling_interval_us

    def channel_index(self, name: str) -> Optional[int]:
        """0-based index of a channel by case-insensitive name."""
        lname = name.lower()
        for i, ch in enumerate(self.channels):
            if ch.name.lower() == lname:
                return i
        return None


_SECTION_RE = re.compile(r"^\[(?P<name>.+)\]\s*$")
_KV_RE = re.compile(r"^(?P<key>[^=;]+)=(?P<value>.*)$")


def _parse_ini(text: str) -> Dict[str, Dict[str, str]]:
    """Minimal INI parse: sections, key=value, ';' comments skipped.

    The [Comment] section of real vhdr files contains free text with
    '=' signs; values are kept verbatim, later sections win on dup keys.
    """
    sections: Dict[str, Dict[str, str]] = {}
    current: Optional[Dict[str, str]] = None
    for raw in text.splitlines():
        line = raw.strip("\r\n")
        if not line or line.lstrip().startswith(";"):
            continue
        m = _SECTION_RE.match(line.strip())
        if m:
            current = sections.setdefault(m.group("name"), {})
            continue
        if current is None:
            continue
        kv = _KV_RE.match(line)
        if kv:
            current[kv.group("key").strip()] = kv.group("value")
    return sections


def _unescape_name(name: str) -> str:
    # Commas in channel names are coded as "\1" per the format spec.
    return name.replace("\\1", ",")


def parse_vhdr(text: str) -> Header:
    """Parse a .vhdr header (C++ parser when built, Python otherwise).

    The native parser (native/eeg_host.cc::eeg_parse_vhdr) is kept in
    semantic lockstep with :func:`parse_vhdr_py` and returns None for
    any input it cannot represent exactly, so behavior is always
    defined by the Python implementation.
    """
    from . import native

    header = native.parse_vhdr(text)
    if header is not None:
        return header
    return parse_vhdr_py(text)


def parse_vhdr_py(text: str) -> Header:
    sections = _parse_ini(text)
    common = sections.get("Common Infos", {})
    binary = sections.get("Binary Infos", {})
    chan_section = sections.get("Channel Infos", {})

    channels: List[ChannelInfo] = []
    chan_keys = [k for k in chan_section if re.fullmatch(r"Ch\d+", k)]
    for key in sorted(chan_keys, key=lambda k: int(k[2:])):
        parts = chan_section[key].split(",")
        # <Name>,<Reference>,<Resolution>,<Unit>, future extensions
        name = _unescape_name(parts[0]) if parts else ""
        ref = parts[1] if len(parts) > 1 else ""
        res = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
        units = parts[3] if len(parts) > 3 else "uV"
        channels.append(
            ChannelInfo(
                number=int(key[2:]),
                name=name,
                reference=ref,
                resolution=res,
                units=units,
            )
        )

    return Header(
        data_file=common.get("DataFile", ""),
        marker_file=common.get("MarkerFile", ""),
        data_format=common.get("DataFormat", "BINARY"),
        orientation=common.get("DataOrientation", "MULTIPLEXED"),
        num_channels=int(common.get("NumberOfChannels", len(channels) or 1)),
        sampling_interval_us=float(common.get("SamplingInterval", 1000)),
        binary_format=binary.get("BinaryFormat", "INT_16"),
        channels=channels,
    )


_MARKER_KEY_RE = re.compile(r"^Mk\d+$")


def parse_vmrk(text: str) -> List[Marker]:
    """Parse a .vmrk marker file (C++ parser when built, Python otherwise)."""
    from . import native

    markers = native.parse_vmrk(text)
    if markers is not None:
        return markers
    return parse_vmrk_py(text)


def parse_vmrk_py(text: str) -> List[Marker]:
    sections = _parse_ini(text)
    infos = sections.get("Marker Infos", {})
    markers: List[Marker] = []
    # preserve numeric Mk order
    for key in sorted(infos, key=lambda k: int(k[2:]) if k[2:].isdigit() else 0):
        if not _MARKER_KEY_RE.match(key):
            continue
        parts = infos[key].split(",")
        kind = parts[0] if parts else ""
        stimulus = _unescape_name(parts[1]) if len(parts) > 1 else ""
        try:
            position = int(parts[2]) if len(parts) > 2 else 0
        except ValueError:
            position = 0
        markers.append(Marker(name=key, kind=kind, stimulus=stimulus, position=position))
    return markers


_BINARY_DTYPES = {
    "INT_16": np.dtype("<i2"),
    "INT_32": np.dtype("<i4"),
    "IEEE_FLOAT_32": np.dtype("<f4"),
}


class Recording:
    """A parsed BrainVision triplet with lazy channel access."""

    def __init__(self, header: Header, markers: List[Marker], raw: np.ndarray):
        self.header = header
        self.markers = markers
        # raw: (num_samples, num_channels) unscaled samples
        self._raw = raw

    @property
    def num_samples(self) -> int:
        return self._raw.shape[0]

    def read_channel(self, index: int) -> np.ndarray:
        """Full channel as float64 scaled by its resolution (0-based index).

        Matches ``DataTransformer.readBinaryData`` returning double[]
        (OffLineDataProvider.java:186-188). The closed eegloader jar
        performs the sample*resolution scaling in *float32* before
        widening to double — pinned empirically by bit-comparing the
        fixture epochs against the reference's Epochs.csv artifact
        (diffs of exactly 2^-12 at |x|~2285 otherwise).
        """
        res = np.float32(self.header.channels[index].resolution)
        return (self._raw[:, index].astype(np.float32) * res).astype(np.float64)

    def raw_int16(self, indices: Sequence[int]) -> np.ndarray:
        """(len(indices), num_samples) UNSCALED int16 channel matrix.

        The device-ingest path (ops/device_ingest.py) ships these raw
        samples to HBM and applies the resolution scaling on device,
        halving host->device transfer vs staging float32 epochs.
        Raises for non-INT_16 recordings (callers fall back to
        :meth:`read_channels`).
        """
        if self._raw.dtype != np.int16:
            raise TypeError(
                f"raw_int16 requires INT_16 data, got {self._raw.dtype}"
            )
        return np.ascontiguousarray(self._raw[:, list(indices)].T)

    def resolutions(self, indices: Sequence[int]) -> np.ndarray:
        """(len(indices),) float32 per-channel resolution factors."""
        return np.array(
            [self.header.channels[i].resolution for i in indices],
            dtype=np.float32,
        )

    def read_channels(self, indices: Sequence[int]) -> np.ndarray:
        """(len(indices), num_samples) float64 scaled channel matrix.

        Demuxed by the native C++ kernel (io/native.py) when built;
        the numpy path below is bit-identical.
        """
        res = np.array(
            [self.header.channels[i].resolution for i in indices], dtype=np.float32
        )
        if self._raw.dtype == np.int16:
            from . import native

            if self._raw.flags["C_CONTIGUOUS"]:
                out = native.demux_int16(self._raw, indices, res)
            elif self._raw.T.flags["C_CONTIGUOUS"]:
                out = native.demux_int16(
                    np.ascontiguousarray(self._raw.T), indices, res,
                    vectorized=True,
                )
            else:
                out = None
            if out is not None:
                return out
        scaled32 = self._raw[:, list(indices)].T.astype(np.float32) * res[:, None]
        return scaled32.astype(np.float64)


def load_recording_bytes(
    vhdr_bytes: bytes, vmrk_bytes: bytes, eeg_bytes: bytes
) -> Recording:
    """Build a :class:`Recording` from an already-read triplet.

    The single-read seam: callers that need both the raw bytes (for a
    content digest — io/feature_cache keys) and the parsed recording
    read each file exactly once and hand the bytes here, instead of
    digesting in one pass and re-reading in :func:`load_recording`.
    Text decodes utf-8 with replacement, matching the FileSystem
    protocol's ``read_text`` (io/sources.py), so both entry points
    parse identical header/marker text.
    """
    header = parse_vhdr(vhdr_bytes.decode("utf-8", errors="replace"))
    markers = parse_vmrk(vmrk_bytes.decode("utf-8", errors="replace"))
    return _recording_from_blob(header, markers, eeg_bytes)


def load_recording(
    eeg_path: str,
    vhdr_path: Optional[str] = None,
    vmrk_path: Optional[str] = None,
    filesystem=None,
) -> Recording:
    """Load a BrainVision triplet.

    Sibling .vhdr/.vmrk default to .eeg with the suffix substituted, as
    ``setFileNames`` does (OffLineDataProvider.java:327-365).
    ``filesystem`` is an ``io.sources`` FileSystem; defaults to local.
    """
    from . import sources

    fs = filesystem or sources.LocalFileSystem()
    base, _ = os.path.splitext(eeg_path)
    vhdr_path = vhdr_path or base + ".vhdr"
    vmrk_path = vmrk_path or base + ".vmrk"

    for p in (vhdr_path, vmrk_path, eeg_path):
        if not fs.exists(p):
            raise FileNotFoundError(f"No related file found: {p}")

    header = parse_vhdr(fs.read_text(vhdr_path))
    markers = parse_vmrk(fs.read_text(vmrk_path))
    blob = fs.read_bytes(eeg_path)
    return _recording_from_blob(header, markers, blob)


def _recording_from_blob(
    header: Header, markers: List[Marker], blob: bytes
) -> Recording:
    dtype = _BINARY_DTYPES.get(header.binary_format)
    if dtype is None:
        raise ValueError(f"Unsupported BinaryFormat: {header.binary_format}")
    flat = np.frombuffer(blob, dtype=dtype)
    nch = header.num_channels
    nsamp = flat.size // nch
    flat = flat[: nsamp * nch]
    if header.orientation.upper() == "MULTIPLEXED":
        raw = flat.reshape(nsamp, nch)
    else:  # VECTORIZED: ch1 all samples, ch2 all samples, ...
        raw = flat.reshape(nch, nsamp).T
    return Recording(header, markers, raw)
