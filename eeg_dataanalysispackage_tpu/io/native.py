"""ctypes binding to the native host kernels (``native/eeg_host.cc``).

The C++ library is the TPU-native stand-in for the reference's closed
``eegloader-hdfs`` jar and the per-marker epoching loop
(OffLineDataProvider.java:167-196, 200-265): int16 demux with
per-channel resolution scaling, window gather + float32 baseline
correction, and the sequential class-balance scan — the host-side hot
loops that fill device staging buffers.

The library is built on demand with ``make`` (g++) and cached next to
the source; every entry point has a bit-identical numpy fallback in
``io/brainvision.py`` / ``epochs/extractor.py``, so the framework is
fully functional without a toolchain. Set ``EEG_TPU_NATIVE=0`` to
force the numpy paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libeeg_host.so")

# The C++ gather kernel uses a fixed stack window buffer.
MAX_WINDOW = 4096

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64 = ctypes.c_int64
_pd = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_pf = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_pi16 = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
_pi64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_pu8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native build failed, using numpy paths: %s", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("EEG_TPU_NATIVE", "1") == "0":
            return None
        lib_path = _LIB_PATH
        src = os.path.join(_NATIVE_DIR, "eeg_host.cc")
        if not os.path.exists(src):
            # installed wheel: setup.py ships the prebuilt library as
            # package data next to this module
            packaged = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "libeeg_host.so"
            )
            if not os.path.exists(packaged):
                return None
            lib_path = packaged
        else:
            stale = not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)
            )
            if stale and not _build():
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            logger.warning("could not load %s: %s", lib_path, e)
            return None

        lib.eeg_demux_int16.argtypes = [
            _pi16, _i64, _i64, _pi64, _i64, _pf, _pd,
        ]
        lib.eeg_demux_int16_vectorized.argtypes = list(
            lib.eeg_demux_int16.argtypes
        )
        lib.eeg_valid_windows.argtypes = [_pi64, _i64, _i64, _i64, _pu8]
        lib.eeg_valid_windows.restype = _i64
        lib.eeg_gather_baseline.argtypes = [
            _pd, _i64, _i64, _pi64, _pu8, _i64, _i64, _i64, _pd,
        ]
        lib.eeg_balance_scan.argtypes = [_pu8, _i64, _pi64, _pu8]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is built/loadable (builds on demand)."""
    return _load() is not None


def demux_int16(
    raw: np.ndarray,
    indices,
    resolutions,
    vectorized: bool = False,
) -> Optional[np.ndarray]:
    """(S, C) [or (C, S) vectorized] int16 -> (n_sel, S) float64.

    Returns None when the native library is unavailable; callers fall
    back to the numpy path.
    """
    lib = _load()
    if lib is None:
        return None
    raw = np.ascontiguousarray(raw, dtype=np.int16)
    if vectorized:
        n_channels, n_samples = raw.shape
    else:
        n_samples, n_channels = raw.shape
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    res = np.ascontiguousarray(resolutions, dtype=np.float32)
    out = np.empty((idx.size, n_samples), dtype=np.float64)
    fn = lib.eeg_demux_int16_vectorized if vectorized else lib.eeg_demux_int16
    fn(raw, n_samples, n_channels, idx, idx.size, res, out)
    return out


def gather_baseline(
    channels: np.ndarray,
    positions: np.ndarray,
    pre: int,
    post: int,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Window gather + f32 baseline correction, epochs of ``post`` samples.

    channels: (n_channels, n_samples) float64. Returns
    (epochs (n_valid, n_channels, post) float64, valid (n_pos,) bool),
    or None when the native library is unavailable or the window
    exceeds the native buffer.
    """
    if pre + post > MAX_WINDOW:
        return None
    lib = _load()
    if lib is None:
        return None
    channels = np.ascontiguousarray(channels, dtype=np.float64)
    pos = np.ascontiguousarray(positions, dtype=np.int64)
    n_channels, n_samples = channels.shape
    valid = np.empty(pos.size, dtype=np.uint8)
    n_valid = lib.eeg_valid_windows(pos, pos.size, pre, n_samples, valid)
    out = np.empty((int(n_valid), n_channels, post), dtype=np.float64)
    lib.eeg_gather_baseline(
        channels, n_channels, n_samples, pos, valid, pos.size, pre, post, out
    )
    return out, valid.astype(bool)


def balance_scan(
    is_target: np.ndarray, counters: np.ndarray
) -> Optional[np.ndarray]:
    """Sequential balance filter; mutates ``counters`` ([n_t, n_nt])."""
    lib = _load()
    if lib is None:
        return None
    t = np.ascontiguousarray(is_target, dtype=np.uint8)
    keep = np.empty(t.size, dtype=np.uint8)
    c = np.ascontiguousarray(counters, dtype=np.int64)
    lib.eeg_balance_scan(t, t.size, c, keep)
    counters[:] = c
    return keep.astype(bool)
