"""ctypes binding to the native host kernels (``native/eeg_host.cc``).

The C++ library is the TPU-native stand-in for the reference's closed
``eegloader-hdfs`` jar and the per-marker epoching loop
(OffLineDataProvider.java:167-196, 200-265): int16 demux with
per-channel resolution scaling, window gather + float32 baseline
correction, and the sequential class-balance scan — the host-side hot
loops that fill device staging buffers.

The library is built on demand with ``make`` (g++) and cached next to
the source; every entry point has a bit-identical numpy fallback in
``io/brainvision.py`` / ``epochs/extractor.py``, so the framework is
fully functional without a toolchain. Set ``EEG_TPU_NATIVE=0`` to
force the numpy paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import re
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libeeg_host.so")

# The C++ gather kernel uses a fixed stack window buffer.
MAX_WINDOW = 4096

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64 = ctypes.c_int64
_pd = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_pf = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_pi16 = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
_pi64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_pu8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native build failed, using numpy paths: %s", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("EEG_TPU_NATIVE", "1") == "0":
            return None
        lib_path = _LIB_PATH
        src = os.path.join(_NATIVE_DIR, "eeg_host.cc")
        if not os.path.exists(src):
            # installed wheel: setup.py ships the prebuilt library as
            # package data next to this module
            packaged = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "libeeg_host.so"
            )
            if not os.path.exists(packaged):
                return None
            lib_path = packaged
        else:
            stale = not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)
            )
            if stale and not _build():
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            logger.warning("could not load %s: %s", lib_path, e)
            return None

        lib.eeg_demux_int16.argtypes = [
            _pi16, _i64, _i64, _pi64, _i64, _pf, _pd,
        ]
        lib.eeg_demux_int16_vectorized.argtypes = list(
            lib.eeg_demux_int16.argtypes
        )
        lib.eeg_valid_windows.argtypes = [_pi64, _i64, _i64, _i64, _pu8]
        lib.eeg_valid_windows.restype = _i64
        lib.eeg_gather_baseline.argtypes = [
            _pd, _i64, _i64, _pi64, _pu8, _i64, _i64, _i64, _pd,
        ]
        lib.eeg_balance_scan.argtypes = [_pu8, _i64, _pi64, _pu8]
        try:  # absent from pre-parser prebuilt libraries
            lib.eeg_parse_vhdr.argtypes = [
                ctypes.c_char_p, _i64, ctypes.POINTER(_HeaderInfo),
                ctypes.POINTER(_ChannelInfo), _i64,
            ]
            lib.eeg_parse_vhdr.restype = _i64
            lib.eeg_parse_vmrk.argtypes = [
                ctypes.c_char_p, _i64, ctypes.POINTER(_MarkerInfo), _i64,
            ]
            lib.eeg_parse_vmrk.restype = _i64
            lib.has_parsers = True
        except AttributeError:
            lib.has_parsers = False
        _lib = lib
        return _lib


class _HeaderInfo(ctypes.Structure):
    _fields_ = [
        ("sampling_interval_us", ctypes.c_double),
        ("num_channels", ctypes.c_int64),
        ("data_file", ctypes.c_char * 256),
        ("marker_file", ctypes.c_char * 256),
        ("data_format", ctypes.c_char * 32),
        ("orientation", ctypes.c_char * 32),
        ("binary_format", ctypes.c_char * 32),
    ]


class _ChannelInfo(ctypes.Structure):
    _fields_ = [
        ("resolution", ctypes.c_double),
        ("number", ctypes.c_int64),
        ("name", ctypes.c_char * 128),
        ("reference", ctypes.c_char * 64),
        ("units", ctypes.c_char * 32),
    ]


class _MarkerInfo(ctypes.Structure):
    _fields_ = [
        ("position", ctypes.c_int64),
        ("name", ctypes.c_char * 32),
        ("kind", ctypes.c_char * 64),
        ("stimulus", ctypes.c_char * 64),
    ]


def available() -> bool:
    """True if the native library is built/loadable (builds on demand)."""
    return _load() is not None


def demux_int16(
    raw: np.ndarray,
    indices,
    resolutions,
    vectorized: bool = False,
) -> Optional[np.ndarray]:
    """(S, C) [or (C, S) vectorized] int16 -> (n_sel, S) float64.

    Returns None when the native library is unavailable; callers fall
    back to the numpy path.
    """
    lib = _load()
    if lib is None:
        return None
    raw = np.ascontiguousarray(raw, dtype=np.int16)
    if vectorized:
        n_channels, n_samples = raw.shape
    else:
        n_samples, n_channels = raw.shape
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    res = np.ascontiguousarray(resolutions, dtype=np.float32)
    out = np.empty((idx.size, n_samples), dtype=np.float64)
    fn = lib.eeg_demux_int16_vectorized if vectorized else lib.eeg_demux_int16
    fn(raw, n_samples, n_channels, idx, idx.size, res, out)
    return out


def gather_baseline(
    channels: np.ndarray,
    positions: np.ndarray,
    pre: int,
    post: int,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Window gather + f32 baseline correction, epochs of ``post`` samples.

    channels: (n_channels, n_samples) float64. Returns
    (epochs (n_valid, n_channels, post) float64, valid (n_pos,) bool),
    or None when the native library is unavailable or the window
    exceeds the native buffer.
    """
    if pre + post > MAX_WINDOW:
        return None
    lib = _load()
    if lib is None:
        return None
    channels = np.ascontiguousarray(channels, dtype=np.float64)
    pos = np.ascontiguousarray(positions, dtype=np.int64)
    n_channels, n_samples = channels.shape
    valid = np.empty(pos.size, dtype=np.uint8)
    n_valid = lib.eeg_valid_windows(pos, pos.size, pre, n_samples, valid)
    out = np.empty((int(n_valid), n_channels, post), dtype=np.float64)
    lib.eeg_gather_baseline(
        channels, n_channels, n_samples, pos, valid, pos.size, pre, post, out
    )
    return out, valid.astype(bool)


def balance_scan(
    is_target: np.ndarray, counters: np.ndarray
) -> Optional[np.ndarray]:
    """Sequential balance filter; mutates ``counters`` ([n_t, n_nt])."""
    lib = _load()
    if lib is None:
        return None
    t = np.ascontiguousarray(is_target, dtype=np.uint8)
    keep = np.empty(t.size, dtype=np.uint8)
    c = np.ascontiguousarray(counters, dtype=np.int64)
    lib.eeg_balance_scan(t, t.size, c, keep)
    counters[:] = c
    return keep.astype(bool)


# The C++ parser works byte-wise on '\n'/'\r\n' line structure and
# ASCII whitespace/digits; Python's splitlines()/strip()/\d/int()
# additionally honor \v, \f, \x1c-\x1e, lone \r, and the Unicode
# decimal-digit and whitespace classes. Inputs using any of those
# route to the Python parser so it defines behavior. Other non-ASCII
# text (channel names, µV units) is byte-transparent and stays native.
# \x00: ctypes c_char-array reads stop at the first NUL, which would
# silently truncate fields the Python parser keeps whole.
_EXOTIC_TEXT_RE = re.compile(r"\r(?!\n)|[\x00\v\f\x1c\x1d\x1e]")


def _native_parseable(text: str) -> bool:
    if _EXOTIC_TEXT_RE.search(text):
        return False
    if text.isascii():
        return True
    return not any(
        ord(c) > 127 and (c.isdigit() or c.isspace()) for c in text
    )


def parse_vhdr(text: str):
    """Parse a .vhdr via the C++ parser; None -> caller falls back.

    Returns an ``io.brainvision.Header``. A negative status from the
    native side (numeric parse failure, oversized field) also returns
    None so the Python parser defines the behavior for exotic inputs.
    """
    lib = _load()
    if lib is None or not getattr(lib, "has_parsers", False):
        return None
    if not _native_parseable(text):
        return None
    from . import brainvision

    try:
        data = text.encode("utf-8")
    except UnicodeEncodeError:  # lone surrogates (surrogateescape reads)
        return None
    max_channels = data.count(b"\n") + 2
    hdr = _HeaderInfo()
    chans = (_ChannelInfo * max_channels)()
    n = lib.eeg_parse_vhdr(data, len(data), ctypes.byref(hdr), chans,
                           max_channels)
    if n < 0:
        return None
    channels = [
        brainvision.ChannelInfo(
            number=int(c.number),
            name=c.name.decode("utf-8"),
            reference=c.reference.decode("utf-8"),
            resolution=float(c.resolution),
            units=c.units.decode("utf-8"),
        )
        for c in chans[:n]
    ]
    return brainvision.Header(
        data_file=hdr.data_file.decode("utf-8"),
        marker_file=hdr.marker_file.decode("utf-8"),
        data_format=hdr.data_format.decode("utf-8"),
        orientation=hdr.orientation.decode("utf-8"),
        num_channels=int(hdr.num_channels),
        sampling_interval_us=float(hdr.sampling_interval_us),
        binary_format=hdr.binary_format.decode("utf-8"),
        channels=channels,
    )


def parse_vmrk(text: str):
    """Parse a .vmrk via the C++ parser; None -> caller falls back."""
    lib = _load()
    if lib is None or not getattr(lib, "has_parsers", False):
        return None
    if not _native_parseable(text):
        return None
    from . import brainvision

    try:
        data = text.encode("utf-8")
    except UnicodeEncodeError:  # lone surrogates (surrogateescape reads)
        return None
    max_markers = data.count(b"\n") + 2
    marks = (_MarkerInfo * max_markers)()
    n = lib.eeg_parse_vmrk(data, len(data), marks, max_markers)
    if n < 0:
        return None
    return [
        brainvision.Marker(
            name=m.name.decode("utf-8"),
            kind=m.kind.decode("utf-8"),
            stimulus=m.stimulus.decode("utf-8"),
            position=int(m.position),
        )
        for m in marks[:n]
    ]
