"""Per-call deadline budgets, threadable through nested I/O layers.

The serving layer (``serve/``) promises every request a bounded
lifetime: a request admitted with a 2 s deadline must resolve —
answer, shed, or deadline-exceeded with evidence — within that budget,
no matter how many retry ladders fire beneath it. The retry machinery
in :mod:`io.remote` bounds ONE request's cost, but it sleeps through
its backoff schedule blind to how much time the *caller* has left: a
request with 50 ms remaining would happily sleep 4 s before its next
attempt. This module is the missing currency — a monotonic-clock
:class:`Deadline` plus a thread-local ambient scope, so a layer that
never heard of serving (an HTTP chunk fetch three frames down) can
still ask "can I afford this sleep?" before taking it.

Design follows the :mod:`obs.chaos` pattern: installing a scope is a
context manager, reading it is one thread-local lookup, and code that
runs outside any scope pays a None-check. Deadlines nest — an inner
scope may only shrink the budget (the effective deadline is the
tightest enclosing one), mirroring how gRPC propagates deadlines.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional


class DeadlineExceededError(TimeoutError):
    """The caller's deadline budget is spent.

    Subclasses ``TimeoutError`` (an ``OSError``), so I/O layers that
    already treat timeouts as I/O failures handle it unchanged.
    """


class Deadline:
    """An absolute expiry on the monotonic clock.

    ``clock`` is injectable so tests drive expiry without sleeping.
    """

    __slots__ = ("_expiry", "_clock", "budget_s")

    def __init__(self, budget_s: float, clock=time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._expiry = clock() + float(budget_s)

    @classmethod
    def after(cls, budget_s: float, clock=time.monotonic) -> "Deadline":
        return cls(budget_s, clock=clock)

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._expiry - self._clock())

    @property
    def expired(self) -> bool:
        return self._clock() >= self._expiry

    def can_cover(self, seconds: float) -> bool:
        """Whether the remaining budget covers ``seconds`` of work —
        the question a retry loop asks before committing to a backoff
        sleep it could never wake from in time."""
        return self.remaining() >= seconds

    def raise_if_expired(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceededError(
                f"{what}: deadline exceeded "
                f"(budget {self.budget_s:.3f}s spent)"
            )

    def __repr__(self) -> str:
        return (
            f"Deadline(budget={self.budget_s:.3f}s, "
            f"remaining={self.remaining():.3f}s)"
        )


def cond_wait(cond: "threading.Condition", predicate, what: str,
              slice_s: float = 0.1) -> None:
    """Wait on ``cond`` (whose lock the caller must hold) until
    ``predicate()`` is true, honouring the ambient deadline scope:
    outside any scope this is a plain ``cond.wait()`` loop; inside
    one, the wait re-checks in short slices and raises
    :class:`DeadlineExceededError` the moment the budget is spent —
    the shape every cross-tenant wait in this codebase needs (the
    feature cache's single-flight guard, the prefix-dedup registry),
    extracted here so no two of them can drift."""
    while not predicate():
        ambient = active_deadline()
        if ambient is None:
            cond.wait()
        else:
            ambient.raise_if_expired(what)
            cond.wait(timeout=min(slice_s, ambient.remaining()))


_LOCAL = threading.local()


def active_deadline() -> Optional[Deadline]:
    """The calling thread's tightest enclosing deadline, or None."""
    stack = getattr(_LOCAL, "stack", None)
    if not stack:
        return None
    # nesting only shrinks: the tightest (earliest-expiring) enclosing
    # deadline governs, whatever order the scopes were opened in
    return min(stack, key=lambda d: d.remaining())


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Install ``deadline`` as the calling thread's ambient budget for
    the block. ``None`` is accepted and is a no-op, so call sites can
    thread an optional deadline without branching."""
    if deadline is None:
        yield None
        return
    stack = getattr(_LOCAL, "stack", None)
    if stack is None:
        stack = []
        _LOCAL.stack = stack
    stack.append(deadline)
    try:
        yield deadline
    finally:
        stack.pop()
