"""Spark MLlib 1.x on-disk model-directory interchange.

The reference persists its trained classifiers with MLlib's own
``model.save(sc, path)`` (LogisticRegressionClassifier.java:144-152;
SVMClassifier.java analogous; ``"file://" + path`` for the tree
family at DecisionTreeClassifier.java:156-165,
RandomForestClassifier.java ditto), producing the MLlib *format
version 1.0* model directory:

    <dir>/metadata/part-00000     one JSON object (+ _SUCCESS)
    <dir>/data/part-*.parquet     one small DataFrame (+ _SUCCESS)

This module reads — and, for fixtures and reverse migration, writes —
those directories, so a model saved by an existing reference
deployment loads drop-in here (``load_clf=logreg&load_name=<dir>``)
and a model trained here can be handed back to a Spark 1.6 cluster.

Layouts (Spark 1.6.2, format class tags in the metadata JSON):

- GLM (``LogisticRegressionModel`` / ``SVMModel``): metadata
  ``{"class", "version": "1.0", "numFeatures", "numClasses"}``; data
  is one row ``(weights: VectorUDT, intercept: double,
  threshold: double?)``. The VectorUDT struct is
  ``(type: tinyint, size: int?, indices: array<int>?,
  values: array<double>)`` with type 1 = dense, 0 = sparse.
- Trees (``DecisionTreeModel``): metadata carries ``algo`` and
  ``numNodes`` at top level; data is one row per node:
  ``(treeId: int, nodeId: int, predict: (predict: double,
  prob: double), impurity: double, isLeaf: boolean,
  split: (feature: int, threshold: double, featureType: int,
  categories: array<double>)?, leftNodeId: int?, rightNodeId: int?,
  infoGain: double?)``. Continuous splits (featureType 0) route
  ``feature <= threshold`` to the left child.
- Ensembles (``RandomForestModel`` / ``GradientBoostedTreesModel``):
  same node rows distinguished by ``treeId``; metadata nests
  ``{"algo", "treeAlgo", "combiningStrategy", "treeWeights"}``.
  Combining: Vote = per-tree class majority (random forests),
  Sum = ``sign(sum(w_i * tree_i(x)))`` (GBT), Average for
  regression ensembles.

The DL4J side (``NeuralNetworkClassifier.java:171-187``,
``ModelSerializer`` zips): the WEIGHTS are not importable — the zip
wraps ND4J's closed native array serialization, for which no public
layout contract exists — but the ARCHITECTURE is
(``io/dl4j_compat.py`` reads the zip's open ``configuration.json``
back into the ``config_*`` surface; retrain after porting).
models/nn.py keeps its own open serialization for native round trips.

Categorical splits never occur in the reference's pipelines (all 48
DWT features are continuous), so importing a tree with a
featureType-1 split raises rather than guessing category semantics.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

GLM_LOGREG = "org.apache.spark.mllib.classification.LogisticRegressionModel"
GLM_SVM = "org.apache.spark.mllib.classification.SVMModel"
TREE_DT = "org.apache.spark.mllib.tree.model.DecisionTreeModel"
TREE_RF = "org.apache.spark.mllib.tree.model.RandomForestModel"
TREE_GBT = "org.apache.spark.mllib.tree.model.GradientBoostedTreesModel"

_FORMAT_VERSION = "1.0"


def _pq():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet as pq

        return pq
    except ImportError as e:  # pragma: no cover - pyarrow is baked in
        raise ImportError(
            "MLlib model-directory interchange needs pyarrow for the "
            "parquet data files; the native npz formats "
            "(io/modelfiles.py) work without it"
        ) from e


def strip_file_prefix(path: str) -> str:
    """The reference prepends ``file://`` for the tree family
    (DecisionTreeClassifier.java:157); tolerate it everywhere."""
    return path[7:] if path.startswith("file://") else path


def _is_remote(path: str) -> bool:
    """Remoteness from the SAME dispatch that will serve the IO
    (io/modelfiles -> remote.filesystem_for): an unregistered scheme
    falls through to the local filesystem there, so it must count as
    local here too or writer and reader route one URI differently
    (review finding)."""
    from . import modelfiles

    return not modelfiles._is_local(path)


def _remote_fs(path: str):
    from . import modelfiles

    return modelfiles._fs_for(path)


def is_model_dir(path: str) -> bool:
    """True iff ``path`` looks like an MLlib model directory (has the
    ``metadata/`` part files). The classifiers use this to route
    ``load()`` between their native npz and this importer. Remote
    URIs are probed through the pluggable filesystem when it can
    list directories (``hdfs://`` — both drivers); listing-less
    schemes (plain http, the gs ranged-read adapter) return False
    and fall through to the byte-level npz path."""
    if _is_remote(path):
        fs = _remote_fs(path)
        if not hasattr(fs, "list_dir"):
            return False
        from .remote import RemoteIOError

        try:
            return any(
                name.startswith("part-")
                for name in fs.list_dir(path.rstrip("/") + "/metadata")
            )
        except (FileNotFoundError, OSError, RemoteIOError, ValueError):
            return False
    path = strip_file_prefix(path)
    meta = os.path.join(path, "metadata")
    return os.path.isdir(meta) and any(
        name.startswith("part-") for name in os.listdir(meta)
    )


def _ensure_local(path: str):
    """(local_dir, cleanup_fn): identity for local paths; for remote
    URIs, download the model directory's metadata/ and data/ entries
    into a temp dir (the reference's load-models-from-HDFS flow,
    DecisionTreeClassifier.java:163-165 against the Const.java
    namenode)."""
    if not _is_remote(path):
        return strip_file_prefix(path), (lambda: None)
    import shutil
    import tempfile

    fs = _remote_fs(path)
    if not hasattr(fs, "list_dir"):
        raise ValueError(
            f"loading an MLlib model directory from {path!r} needs a "
            f"filesystem with directory listing (local paths or "
            f"hdfs:// — WebHDFS and native drivers); stage it "
            f"locally for other schemes"
        )
    tmp = tempfile.mkdtemp(prefix="mllib_import_")
    try:
        base = path.rstrip("/")
        for sub in ("metadata", "data"):
            os.makedirs(os.path.join(tmp, sub), exist_ok=True)
            for name in fs.list_dir(f"{base}/{sub}"):
                with open(os.path.join(tmp, sub, name), "wb") as f:
                    f.write(fs.read_bytes(f"{base}/{sub}/{name}"))
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return tmp, (lambda: shutil.rmtree(tmp, ignore_errors=True))


def read_metadata(path: str) -> dict:
    """Parse ``<dir>/metadata/part-*`` (first non-empty JSON line;
    Spark writes the object as a single line via json4s)."""
    meta_dir = os.path.join(strip_file_prefix(path), "metadata")
    parts = sorted(
        p for p in os.listdir(meta_dir) if p.startswith("part-")
    )
    if not parts:
        raise FileNotFoundError(f"no metadata part files under {meta_dir}")
    for part in parts:
        with open(os.path.join(meta_dir, part), "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    return json.loads(line)
    raise ValueError(f"empty metadata under {meta_dir}")


def _read_data_rows(path: str) -> List[dict]:
    pq = _pq()
    data_dir = os.path.join(strip_file_prefix(path), "data")
    files = sorted(
        os.path.join(data_dir, p)
        for p in os.listdir(data_dir)
        if p.endswith(".parquet")
    )
    if not files:
        raise FileNotFoundError(f"no parquet part files under {data_dir}")
    rows: List[dict] = []
    for f in files:
        rows.extend(pq.read_table(f).to_pylist())
    return rows


def _vector_to_np(v: dict) -> np.ndarray:
    """VectorUDT struct -> dense float64 array (type 1 = dense,
    0 = sparse with (size, indices, values))."""
    vtype = int(v["type"])
    values = np.asarray(v["values"] or [], dtype=np.float64)
    if vtype == 1:
        return values
    if vtype == 0:
        size = int(v["size"])
        out = np.zeros(size, dtype=np.float64)
        idx = np.asarray(v["indices"] or [], dtype=np.int64)
        out[idx] = values
        return out
    raise ValueError(f"unknown VectorUDT type tag {vtype}")


# ---------------------------------------------------------------- GLM


@dataclass
class GLMModel:
    model_class: str
    weights: np.ndarray  # (numFeatures,) float64
    intercept: float
    threshold: Optional[float]  # None == cleared (raw-score mode)
    num_features: int
    num_classes: int


def read_glm(path: str) -> GLMModel:
    """Load a GLM model directory written by
    ``LogisticRegressionModel.save`` / ``SVMModel.save`` (the
    reference's save/load seam, LogisticRegressionClassifier.java:
    144-152)."""
    path, cleanup = _ensure_local(path)
    try:
        return _read_glm_local(path)
    finally:
        cleanup()


def _read_glm_local(path: str) -> GLMModel:
    meta = read_metadata(path)
    cls = meta.get("class", "")
    if cls not in (GLM_LOGREG, GLM_SVM):
        raise ValueError(f"not a GLM classification model dir: {cls!r}")
    rows = _read_data_rows(path)
    if len(rows) != 1:
        raise ValueError(
            f"GLM data must be a single row; found {len(rows)}"
        )
    row = rows[0]
    weights = _vector_to_np(row["weights"])
    threshold = row.get("threshold")
    return GLMModel(
        model_class=cls,
        weights=weights,
        intercept=float(row["intercept"]),
        threshold=None if threshold is None else float(threshold),
        num_features=int(meta.get("numFeatures", weights.shape[0])),
        num_classes=int(meta.get("numClasses", 2)),
    )


def write_glm(
    path: str,
    model_class: str,
    weights: np.ndarray,
    intercept: float = 0.0,
    threshold: Optional[float] = 0.5,
    num_classes: int = 2,
) -> None:
    """Write a format-1.0 GLM model directory a Spark 1.6 cluster (or
    :func:`read_glm`) can load. Also the fixture generator for the
    import tests. ``path`` may be a remote URI (built locally, then
    uploaded file-by-file through io/modelfiles)."""
    materialize_model_dir(
        path,
        lambda local: _write_glm_local(
            local, model_class, weights, intercept, threshold,
            num_classes,
        ),
    )


def _write_glm_local(
    path: str,
    model_class: str,
    weights: np.ndarray,
    intercept: float,
    threshold: Optional[float],
    num_classes: int,
) -> None:
    import pyarrow as pa

    pq = _pq()
    weights = np.asarray(weights, dtype=np.float64)
    _write_metadata(
        path,
        {
            "class": model_class,
            "version": _FORMAT_VERSION,
            "numFeatures": int(weights.shape[0]),
            "numClasses": int(num_classes),
        },
    )
    vec_t = pa.struct(
        [
            ("type", pa.int8()),
            ("size", pa.int32()),
            ("indices", pa.list_(pa.int32())),
            ("values", pa.list_(pa.float64())),
        ]
    )
    schema = pa.schema(
        [
            ("weights", vec_t),
            ("intercept", pa.float64()),
            ("threshold", pa.float64()),
        ]
    )
    row = {
        "weights": {
            "type": 1,
            "size": None,
            "indices": None,
            "values": weights.tolist(),
        },
        "intercept": float(intercept),
        "threshold": None if threshold is None else float(threshold),
    }
    _write_data(
        pq,
        pa.Table.from_pylist([row], schema=schema),
        path,
        spark_schema=_GLM_SPARK_SCHEMA,
    )


#: Spark SQL schema JSON for the GLM data table, embedded verbatim as
#: the ``org.apache.spark.sql.parquet.row.metadata`` footer key. Spark
#: 1.6's ``GLMClassificationModel.SaveLoadV1_0.loadData`` pattern-
#: matches ``Row(weights: Vector, ...)`` — without the ``udt`` entry
#: tagging the weights struct as VectorUDT, the row deserializes as a
#: plain struct and the match throws MatchError, so an exported
#: logreg/svm dir would not load on an actual cluster (ADVICE,
#: medium). Field order mirrors the parquet schema; ``metadata`` maps
#: are the empty defaults ``CatalystTypeConverters`` writes.
_GLM_SPARK_SCHEMA = {
    "type": "struct",
    "fields": [
        {
            "name": "weights",
            "type": {
                "type": "udt",
                "class": "org.apache.spark.mllib.linalg.VectorUDT",
                "pyClass": "pyspark.mllib.linalg.VectorUDT",
                "sqlType": {
                    "type": "struct",
                    "fields": [
                        {
                            "name": "type",
                            "type": "byte",
                            "nullable": False,
                            "metadata": {},
                        },
                        {
                            "name": "size",
                            "type": "integer",
                            "nullable": True,
                            "metadata": {},
                        },
                        {
                            "name": "indices",
                            "type": {
                                "type": "array",
                                "elementType": "integer",
                                "containsNull": False,
                            },
                            "nullable": True,
                            "metadata": {},
                        },
                        {
                            "name": "values",
                            "type": {
                                "type": "array",
                                "elementType": "double",
                                "containsNull": False,
                            },
                            "nullable": True,
                            "metadata": {},
                        },
                    ],
                },
            },
            "nullable": True,
            "metadata": {},
        },
        {
            "name": "intercept",
            "type": "double",
            "nullable": False,
            "metadata": {},
        },
        {
            "name": "threshold",
            "type": "double",
            "nullable": True,
            "metadata": {},
        },
    ],
}


# -------------------------------------------------------------- trees


@dataclass
class MLlibTreeEnsemble:
    """Imported tree family in nodeId-compacted array form; one dict
    per tree with arrays ``feature``/``threshold``/``left``/``right``/
    ``leaf``/``predict`` (leaf nodes self-loop so the fixed-iteration
    descent below is total)."""

    model_class: str
    algo: str
    trees: List[Dict[str, np.ndarray]]
    tree_weights: np.ndarray  # (n_trees,) float64
    combining: str  # "vote" | "sum" | "average"

    def predict(self, features: np.ndarray) -> np.ndarray:
        """Reference-semantics prediction over raw (continuous)
        features: TreeEnsembleModel.predict — Vote = per-tree class
        majority; Sum = ``1 if sum(w_i * t_i(x)) > 0 else 0`` (the
        GBT classification threshold); Average = weighted mean.

        Vote ties follow Spark 1.6 ``predictByVoting`` exactly: it
        takes ``maxBy`` over a ``mutable.HashMap[Int, Double]``, and
        ``maxBy`` keeps the FIRST maximum in the map's iteration
        order. For the binary vote keys {0, 1} that order is fixed by
        the hash table, not by tree order: with the initial 16-bucket
        table, byteswap32-improved Int hashing puts key 1 in bucket 6
        and key 0 in bucket 0, and ``entriesIterator`` walks buckets
        DOWNWARD from the highest populated index — so key 1 always
        iterates first and an exact weighted tie deterministically
        predicts class 1.0 (ADVICE divergence note; reachable for
        even-sized equal-weight forests)."""
        X = np.asarray(features, dtype=np.float64)
        per_tree = np.stack([_descend(t, X) for t in self.trees])
        w = self.tree_weights[:, None]
        if self.combining == "sum":
            total = (w * per_tree).sum(axis=0)
            return (total > 0.0).astype(np.float64)
        if self.combining == "vote":
            votes1 = ((per_tree > 0.5) * w).sum(axis=0)
            votes0 = ((per_tree <= 0.5) * w).sum(axis=0)
            # >= : the tie goes to class 1 (the JVM vote map's
            # iteration order above), never to class 0
            return (votes1 >= votes0).astype(np.float64)
        return (w * per_tree).sum(axis=0) / self.tree_weights.sum()


def _descend(tree: Dict[str, np.ndarray], X: np.ndarray) -> np.ndarray:
    n = X.shape[0]
    node = np.zeros(n, dtype=np.int64)
    rows = np.arange(n)
    # node count bounds the path length; leaves self-loop so extra
    # iterations are no-ops
    for _ in range(len(tree["leaf"])):
        leaf = tree["leaf"][node]
        if leaf.all():
            break
        go_left = X[rows, tree["feature"][node]] <= tree["threshold"][node]
        nxt = np.where(go_left, tree["left"][node], tree["right"][node])
        node = np.where(leaf, node, nxt)
    return tree["predict"][node]


def _nodes_to_tree(nodes: List[dict]) -> Dict[str, np.ndarray]:
    nodes = sorted(nodes, key=lambda r: int(r["nodeId"]))
    index = {int(r["nodeId"]): i for i, r in enumerate(nodes)}
    k = len(nodes)
    tree = {
        "feature": np.zeros(k, dtype=np.int64),
        "threshold": np.full(k, np.inf, dtype=np.float64),
        "left": np.arange(k, dtype=np.int64),
        "right": np.arange(k, dtype=np.int64),
        "leaf": np.ones(k, dtype=bool),
        "predict": np.zeros(k, dtype=np.float64),
    }
    for i, r in enumerate(nodes):
        tree["predict"][i] = float(r["predict"]["predict"])
        if bool(r["isLeaf"]):
            continue
        split = r["split"]
        if split is None:
            raise ValueError(
                f"internal node {r['nodeId']} has no split record"
            )
        if int(split["featureType"]) != 0:
            raise NotImplementedError(
                "categorical MLlib splits are not supported (the "
                "reference's 48 DWT features are all continuous)"
            )
        tree["leaf"][i] = False
        tree["feature"][i] = int(split["feature"])
        tree["threshold"][i] = float(split["threshold"])
        tree["left"][i] = index[int(r["leftNodeId"])]
        tree["right"][i] = index[int(r["rightNodeId"])]
    return tree


def _normalize_combining(raw: str) -> str:
    c = raw.strip().lower()
    if c in ("vote", "majority"):
        return "vote"
    if c == "sum":
        return "sum"
    if c in ("average", "avg"):
        return "average"
    raise ValueError(f"unknown combining strategy {raw!r}")


def read_tree_ensemble(path: str) -> MLlibTreeEnsemble:
    """Load a DecisionTreeModel / RandomForestModel /
    GradientBoostedTreesModel directory (the save targets at
    DecisionTreeClassifier.java:156-157 and the RF/GBT analogues)."""
    path, cleanup = _ensure_local(path)
    try:
        return _read_tree_ensemble_local(path)
    finally:
        cleanup()


def _read_tree_ensemble_local(path: str) -> MLlibTreeEnsemble:
    meta = read_metadata(path)
    cls = meta.get("class", "")
    if cls == TREE_DT:
        algo = meta.get("algo", "Classification")
        tree_weights = np.ones(1, dtype=np.float64)
        combining = "vote"
    elif cls in (TREE_RF, TREE_GBT):
        inner = meta.get("metadata", {})
        algo = inner.get("algo", "Classification")
        tree_weights = np.asarray(
            inner.get("treeWeights", []), dtype=np.float64
        )
        combining = _normalize_combining(
            inner.get(
                "combiningStrategy",
                "sum" if cls == TREE_GBT else "vote",
            )
        )
    else:
        raise ValueError(f"not an MLlib tree model dir: {cls!r}")

    by_tree: Dict[int, List[dict]] = {}
    for row in _read_data_rows(path):
        by_tree.setdefault(int(row.get("treeId", 0)), []).append(row)
    trees = [_nodes_to_tree(by_tree[t]) for t in sorted(by_tree)]
    if combining == "vote":
        # the vote path (and every consumer in models/trees.py) is
        # binary — same refuse-don't-guess policy as categorical
        # splits: a multiclass model's labels would be silently
        # collapsed by the >0.5 vote threshold
        for t in trees:
            labels = np.unique(t["predict"][t["leaf"]])
            if not np.isin(labels, (0.0, 1.0)).all():
                raise NotImplementedError(
                    f"multiclass MLlib tree model (leaf labels "
                    f"{labels.tolist()}) is not supported; the "
                    f"reference pipeline is binary (target vs "
                    f"non-target)"
                )
    if tree_weights.shape[0] == 0:
        tree_weights = np.ones(len(trees), dtype=np.float64)
    if tree_weights.shape[0] != len(trees):
        raise ValueError(
            f"treeWeights has {tree_weights.shape[0]} entries for "
            f"{len(trees)} trees"
        )
    return MLlibTreeEnsemble(
        model_class=cls,
        algo=algo,
        trees=trees,
        tree_weights=tree_weights,
        combining=combining,
    )


def write_tree_ensemble(
    path: str,
    model_class: str,
    trees: Sequence[Dict[str, np.ndarray]],
    tree_weights: Optional[Sequence[float]] = None,
    algo: str = "Classification",
    combining: Optional[str] = None,
) -> None:
    """Write a format-1.0 tree model directory from the compact array
    form (:class:`MLlibTreeEnsemble` layout). NodeIds use MLlib's
    heap convention (root 1, children ``2n``/``2n+1``-free explicit
    links are what the reader consumes, so any injective id works;
    the writer emits depth-first ids starting at 1). ``path`` may be
    a remote URI (built locally, then uploaded through
    io/modelfiles)."""
    materialize_model_dir(
        path,
        lambda local: _write_tree_ensemble_local(
            local, model_class, trees, tree_weights, algo, combining
        ),
    )


def _write_tree_ensemble_local(
    path: str,
    model_class: str,
    trees: Sequence[Dict[str, np.ndarray]],
    tree_weights: Optional[Sequence[float]],
    algo: str,
    combining: Optional[str],
) -> None:
    import pyarrow as pa

    pq = _pq()
    if tree_weights is None:
        tree_weights = [1.0] * len(trees)

    # DFS-reachable node order per tree, computed up front: the
    # emitted rows AND the metadata numNodes must agree. Device-grown
    # heaps carry unreachable padded slots (trees_device
    # .heap_to_host_arrays fixed-size arrays), and Spark 1.6's
    # DecisionTreeModel.load asserts reconstructed count ==
    # metadata numNodes — counting array length would make the
    # exported directory unloadable there.
    orders: List[List[int]] = []
    for tree in trees:
        order: List[int] = []
        stack = [0]
        while stack:
            i = stack.pop()
            order.append(i)
            if not tree["leaf"][i]:
                stack.append(int(tree["right"][i]))
                stack.append(int(tree["left"][i]))
        orders.append(order)

    if model_class == TREE_DT:
        if len(trees) != 1:
            raise ValueError("DecisionTreeModel holds exactly one tree")
        meta = {
            "class": model_class,
            "version": _FORMAT_VERSION,
            "algo": algo,
            "numNodes": len(orders[0]),
        }
    elif model_class in (TREE_RF, TREE_GBT):
        meta = {
            "class": model_class,
            "version": _FORMAT_VERSION,
            "metadata": {
                "algo": algo,
                "treeAlgo": (
                    "Regression" if model_class == TREE_GBT else algo
                ),
                "combiningStrategy": (
                    combining
                    or ("Sum" if model_class == TREE_GBT else "Vote")
                ),
                "treeWeights": [float(w) for w in tree_weights],
            },
        }
    else:
        raise ValueError(f"unknown tree model class {model_class!r}")
    _write_metadata(path, meta)

    rows: List[dict] = []
    for tid, tree in enumerate(trees):
        # depth-first renumbering from 1 (ids are explicit links, any
        # injective assignment round-trips)
        order = orders[tid]
        ids = {i: k + 1 for k, i in enumerate(order)}
        for i in order:
            leaf = bool(tree["leaf"][i])
            rows.append(
                {
                    "treeId": tid,
                    "nodeId": ids[i],
                    "predict": {
                        "predict": float(tree["predict"][i]),
                        "prob": 0.0,
                    },
                    "impurity": 0.0,
                    "isLeaf": leaf,
                    "split": (
                        None
                        if leaf
                        else {
                            "feature": int(tree["feature"][i]),
                            "threshold": float(tree["threshold"][i]),
                            "featureType": 0,
                            "categories": [],
                        }
                    ),
                    "leftNodeId": (
                        None if leaf else ids[int(tree["left"][i])]
                    ),
                    "rightNodeId": (
                        None if leaf else ids[int(tree["right"][i])]
                    ),
                    "infoGain": None if leaf else 0.0,
                }
            )
    predict_t = pa.struct(
        [("predict", pa.float64()), ("prob", pa.float64())]
    )
    split_t = pa.struct(
        [
            ("feature", pa.int32()),
            ("threshold", pa.float64()),
            ("featureType", pa.int32()),
            ("categories", pa.list_(pa.float64())),
        ]
    )
    schema = pa.schema(
        [
            ("treeId", pa.int32()),
            ("nodeId", pa.int32()),
            ("predict", predict_t),
            ("impurity", pa.float64()),
            ("isLeaf", pa.bool_()),
            ("split", split_t),
            ("leftNodeId", pa.int32()),
            ("rightNodeId", pa.int32()),
            ("infoGain", pa.float64()),
        ]
    )
    _write_data(pq, pa.Table.from_pylist(rows, schema=schema), path)


# ------------------------------------------------------------ helpers


def materialize_model_dir(path: str, build_fn) -> None:
    """Run ``build_fn(local_dir)`` and land the resulting model
    directory at ``path`` — directly for local paths, or by building
    in a temp dir and uploading every file through the pluggable
    filesystem for remote URIs (``hdfs://``/``gs://``/``http(s)://``
    — the reference's models-on-HDFS flow,
    LogisticRegressionClassifier.java:144-152 saving to the
    Const.java namenode). Without this, a remote ``save_name`` would
    silently become a junk relative local directory (review
    finding)."""
    import shutil
    import tempfile

    from . import modelfiles

    if modelfiles._is_local(path):
        build_fn(strip_file_prefix(path))
        return
    tmp = tempfile.mkdtemp(prefix="mllib_export_")
    try:
        build_fn(tmp)
        # clear any previous export first (the remote analogue of
        # delete_local_dir_target): a surviving old data part file
        # would be concatenated with the new one by every reader —
        # ours and Spark's (review finding). Filesystems without
        # delete can still LIST: deterministic part naming overwrites
        # our own previous export, but a directory Spark itself wrote
        # uses uuid-suffixed parts (part-r-00000-<uuid>.gz.parquet)
        # that no overwrite reaches — refuse rather than silently
        # coexist with them (ADVICE, low).
        fs = modelfiles._fs_for(path)
        if hasattr(fs, "delete_dir"):
            fs.delete_dir(path.rstrip("/"))
        elif hasattr(fs, "list_dir"):
            _check_no_stale_parts(fs, path.rstrip("/"), tmp)
        for root, _dirs, files in os.walk(tmp):
            rel_root = os.path.relpath(root, tmp)
            for name in files:
                rel = (
                    name
                    if rel_root == "."
                    else f"{rel_root}/{name}"
                )
                with open(os.path.join(root, name), "rb") as f:
                    modelfiles.write_model_bytes(
                        path.rstrip("/") + "/" + rel, f.read()
                    )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _check_no_stale_parts(fs, path: str, tmp: str) -> None:
    """For a listing-capable filesystem WITHOUT recursive delete:
    refuse to upload over part files the upcoming writes won't
    overwrite. Every model-dir reader (ours and Spark's) concatenates
    all ``part-*`` files in ``data/``, so a uuid-suffixed leftover
    from a Spark-written directory would merge with the new export
    into a corrupt model. Missing target dirs are fine (fresh
    export); only per-subdir mismatched part files raise."""
    for sub in ("data", "metadata"):
        local_sub = os.path.join(tmp, sub)
        if not os.path.isdir(local_sub):
            continue
        new_names = set(os.listdir(local_sub))
        try:
            existing = fs.list_dir(f"{path}/{sub}")
        except (FileNotFoundError, OSError):
            continue
        stale = [
            name
            for name in existing
            if name.startswith("part-") and name not in new_names
        ]
        if stale:
            raise IOError(
                f"refusing to export model dir over {path}/{sub}: "
                f"existing part files {sorted(stale)} would not be "
                f"overwritten (uuid-suffixed Spark output?) and every "
                f"reader would concatenate them with the new rows — "
                f"delete the directory first"
            )


def _write_metadata(path: str, meta: dict) -> None:
    meta_dir = os.path.join(path, "metadata")
    os.makedirs(meta_dir, exist_ok=True)
    with open(
        os.path.join(meta_dir, "part-00000"), "w", encoding="utf-8"
    ) as f:
        f.write(json.dumps(meta, separators=(",", ":")) + "\n")
    open(os.path.join(meta_dir, "_SUCCESS"), "w").close()


def _write_data(pq, table, path: str, spark_schema: dict = None) -> None:
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir, exist_ok=True)
    if spark_schema is not None:
        # Spark SQL reads its row schema (UDT tags included) from this
        # footer key in preference to the parquet schema; pyarrow's
        # own tables carry no footer metadata from from_pylist, so
        # replace rather than merge
        table = table.replace_schema_metadata(
            {
                "org.apache.spark.sql.parquet.row.metadata": json.dumps(
                    spark_schema, separators=(",", ":")
                )
            }
        )
    # Spark-style part naming + gzip default codec
    # (spark.sql.parquet.compression.codec). DETERMINISTIC name, no
    # uuid: a re-export to the same remote directory must overwrite
    # the previous part file, not accumulate a second one the reader
    # (ours or Spark's) would concatenate into a corrupt model
    # (review finding).
    name = "part-r-00000.gz.parquet"
    pq.write_table(
        table,
        os.path.join(data_dir, name),
        compression="gzip",
        # parquet format 1.0: Spark 1.6 bundles parquet-mr 1.7, which
        # predates the v2 file metadata; every type in these schemas
        # (double/int/bool/struct/list) is expressible in 1.0, so the
        # floor costs nothing and maximizes JVM-side readability
        version="1.0",
    )
    open(os.path.join(data_dir, "_SUCCESS"), "w").close()
